"""Span and metrics exporters: Chrome trace JSON + Prometheus text.

Two consumers, two formats:

- **Chrome trace-event JSON** (``spans_to_chrome``): the span JSONL a
  ``serve --trace-spans-dir`` run wrote, converted into a file Perfetto or
  ``chrome://tracing`` loads directly — one track per thread (the gateway
  workers each get their own), spans as complete ("X") events, span events
  as instants, and every ``gateway.queue_wait`` span drawn as a FLOW arrow
  from the submitting thread to the worker that picked the tick up (the
  visual for the queue-wait number that diagnoses worker thrash). The
  ``solver spans`` CLI subcommand is a thin wrapper over this.

- **Prometheus v0.0.4 text** (``render_prometheus``): the gateway's
  per-shard ``SchedulerMetrics`` as labeled samples —
  ``{fleet,shard,worker,health}`` — so per-shard counters surface through
  one scrape instead of being summed away; latency histograms render as
  summaries (p50/p99 quantiles + ``_sum``/``_count``). ``# HELP`` text
  comes from ``sched.metrics.METRIC_REGISTRY`` (the same registry dlint
  DLP019 holds every literal counter name to), so dashboards and code
  cannot drift apart. ``parse_prometheus_text`` is the minimal in-repo
  parser the round-trip tests (and any quick operator sanity check) use.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

__all__ = [
    "read_spans",
    "spans_to_chrome",
    "top_spans",
    "span_stats",
    "render_prometheus",
    "parse_prometheus_text",
]


# -- span JSONL -> Chrome trace-event JSON ----------------------------------


def read_spans(path) -> List[dict]:
    """Parse a span JSONL file (one span object per line, blanks skipped)."""
    out: List[dict] = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if line:
            out.append(json.loads(line))
    return out


def spans_to_chrome(spans: List[dict]) -> dict:
    """Chrome trace-event JSON for a list of span records.

    Timestamps convert ms -> µs (the trace-event unit). Thread tracks are
    minted in first-appearance order with metadata events naming them, so
    Perfetto shows ``gw-worker-0`` / ``gw-worker-1`` / the loop thread as
    separate rows. Queue waits additionally emit an ``s``/``f`` flow pair:
    the arrow starts on the thread that ENQUEUED (the queue-wait span's
    parent's thread) and lands on the worker thread at pickup.
    """
    tids: Dict[str, int] = {}
    events: List[dict] = []
    by_id = {s["span_id"]: s for s in spans}

    def tid_for(thread: str) -> int:
        if thread not in tids:
            tids[thread] = len(tids) + 1
            events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": 1,
                    "tid": tids[thread],
                    "args": {"name": thread},
                }
            )
        return tids[thread]

    for s in spans:
        tid = tid_for(s.get("thread", "main"))
        t0_us = s["t0_ms"] * 1e3
        dur_us = max(0.0, s["dur_ms"]) * 1e3
        events.append(
            {
                "name": s["name"],
                "cat": "distilp",
                "ph": "X",
                "pid": 1,
                "tid": tid,
                "ts": t0_us,
                "dur": dur_us,
                "args": {
                    "trace_id": s["trace_id"],
                    "span_id": s["span_id"],
                    **(s.get("attrs") or {}),
                },
            }
        )
        for ev in s.get("events") or []:
            events.append(
                {
                    "name": ev.get("name", "event"),
                    "cat": "distilp",
                    "ph": "i",
                    "s": "t",
                    "pid": 1,
                    "tid": tid,
                    "ts": ev.get("t_ms", s["t0_ms"]) * 1e3,
                    "args": {
                        k: v for k, v in ev.items() if k not in ("name", "t_ms")
                    },
                }
            )
        if s["name"] == "gateway.queue_wait":
            parent = by_id.get(s.get("parent_id") or "")
            src_tid = tid_for(parent["thread"]) if parent else tid
            flow_id = int(s["span_id"], 16)
            events.append(
                {
                    "name": "queue", "cat": "flow", "ph": "s", "id": flow_id,
                    "pid": 1, "tid": src_tid, "ts": t0_us,
                }
            )
            events.append(
                {
                    "name": "queue", "cat": "flow", "ph": "f", "bp": "e",
                    "id": flow_id, "pid": 1, "tid": tid,
                    "ts": t0_us + dur_us,
                }
            )
    # Stable load order: metadata first (ph M has no ts), then by time.
    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def top_spans(spans: List[dict], n: int = 3) -> List[dict]:
    """The n slowest spans (the walkthrough's "where did the time go")."""
    return sorted(spans, key=lambda s: s.get("dur_ms", 0.0), reverse=True)[:n]


def span_stats(spans: List[dict], top: int = 3) -> List[dict]:
    """Per-span-name aggregates for CI logs: Perfetto is the deep-dive
    tool, but a test log needs "which span got slow" as TEXT. One row per
    span name — count, total/p50/p99/max duration, and the ``top``
    slowest instances with their trace ids (the handle a post-mortem
    greps the span JSONL for). Rows sort by total duration, descending —
    the"where did the wall clock go" order."""
    from ..sched.metrics import _quantile

    by_name: Dict[str, List[dict]] = {}
    for s in spans:
        by_name.setdefault(s.get("name", "?"), []).append(s)
    rows: List[dict] = []
    for name, group in by_name.items():
        durs = sorted(float(s.get("dur_ms", 0.0)) for s in group)
        slowest = sorted(
            group, key=lambda s: s.get("dur_ms", 0.0), reverse=True
        )[:top]
        rows.append(
            {
                "name": name,
                "count": len(group),
                "total_ms": round(sum(durs), 3),
                "p50_ms": round(_quantile(durs, 0.50), 3),
                "p99_ms": round(_quantile(durs, 0.99), 3),
                "max_ms": round(durs[-1], 3) if durs else 0.0,
                "slowest": [
                    {
                        "dur_ms": s.get("dur_ms", 0.0),
                        "trace_id": s.get("trace_id"),
                        "thread": s.get("thread"),
                    }
                    for s in slowest
                ],
            }
        )
    rows.sort(key=lambda r: r["total_ms"], reverse=True)
    return rows


# -- Prometheus v0.0.4 text exposition --------------------------------------

_PROM_PREFIX = "distilp_"
_WORKER_EVENTS_RE = re.compile(r"^worker_(\d+)_events$")
_HEALTH_RANK = {"healthy": 0, "degraded": 1, "broken": 2}


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _labels_txt(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _help_for(name: str) -> str:
    from ..sched.metrics import registry_help

    return registry_help(name) or "distilp metric (unregistered)"


class _PromDoc:
    """Accumulates samples per metric name, renders HELP/TYPE + samples."""

    def __init__(self) -> None:
        # name -> (type, help, [(labels, value)])
        self._metrics: Dict[str, Tuple[str, str, list]] = {}

    def add(
        self,
        name: str,
        value,
        labels: Dict[str, str],
        mtype: str = "counter",
        help_name: Optional[str] = None,
    ) -> None:
        full = _PROM_PREFIX + name
        if full not in self._metrics:
            self._metrics[full] = (mtype, _help_for(help_name or name), [])
        self._metrics[full][2].append((dict(labels), value))

    def add_summary(
        self, name: str, snap: dict, labels: Dict[str, str]
    ) -> None:
        """A LatencyHist snapshot as a Prometheus summary (ms units).

        Quantiles come from the hist's cap-bounded recent window, the
        ``_sum``/``_count`` pair from the all-time fields — exactly the
        split ``LatencyHist.snapshot`` documents.
        """
        full = _PROM_PREFIX + name
        if full not in self._metrics:
            self._metrics[full] = ("summary", _help_for(name), [])
        _, _, samples = self._metrics[full]
        for q, key in (("0.5", "p50_ms"), ("0.99", "p99_ms")):
            samples.append(({**labels, "quantile": q}, snap.get(key, 0.0)))
        count = snap.get("count", 0)
        # Exact running total when the snapshot carries it: reconstructing
        # the sum as rounded-mean*count can DECREASE between scrapes
        # (rounding flips while count grows), which reads as a counter
        # reset and spikes rate() negative.
        total = snap.get("total_ms")
        if total is None:
            total = round(snap.get("mean_ms", 0.0) * count, 3)
        samples.append(({**labels, "__suffix__": "_sum"}, total))
        samples.append(({**labels, "__suffix__": "_count"}, count))

    def render(self) -> str:
        lines: List[str] = []
        for full in sorted(self._metrics):
            mtype, help_txt, samples = self._metrics[full]
            lines.append(f"# HELP {full} {help_txt}")
            lines.append(f"# TYPE {full} {mtype}")
            for labels, value in samples:
                suffix = labels.pop("__suffix__", "")
                lines.append(f"{full}{suffix}{_labels_txt(labels)} {value}")
        return "\n".join(lines) + "\n"


def render_prometheus(
    shards: List[dict],
    gateway_counters: Optional[dict] = None,
    gateway_latency: Optional[dict] = None,
    worker_gauges: Optional[Dict[str, Dict[str, float]]] = None,
) -> str:
    """Prometheus v0.0.4 text for gateway + per-shard scheduler metrics.

    ``shards`` entries carry ``fleet``/``shard``/``worker``/``health`` plus
    the shard scheduler's ``counters`` and ``latency`` snapshot dicts;
    every per-shard sample is labeled with all four, so two shards of the
    same gateway stay distinguishable in one scrape. Gateway-level
    counters render unlabeled, except the ``worker_<i>_events`` family,
    which folds into one ``worker_events`` metric with a ``worker`` label.
    ``worker_gauges`` maps gauge name -> {worker id -> live value} (e.g.
    ``worker_queue_depth``, the admission-control input), rendered as one
    ``worker``-labeled gauge per name.
    """
    doc = _PromDoc()
    for entry in shards:
        labels = {
            "fleet": entry["fleet"],
            "shard": entry["shard"],
            "worker": str(entry["worker"]),
        }
        for name, value in sorted(entry.get("counters", {}).items()):
            doc.add(name, value, labels)
        # Health is deliberately NOT an identity label on the counter and
        # summary series above: it is volatile, and a healthy->degraded
        # flip would mint brand-new series for every counter exactly when
        # rate()/increase() over the transition matters most. It rides
        # here instead — a gauge whose VALUE is the health rank, with the
        # state string as a label on this one metric only.
        doc.add(
            "health_state",
            _HEALTH_RANK.get(entry["health"], 2),
            {**labels, "health": entry["health"]},
            mtype="gauge",
        )
        for name, snap in sorted(entry.get("latency", {}).items()):
            doc.add_summary(name, snap, labels)
    for name, value in sorted((gateway_counters or {}).items()):
        m = _WORKER_EVENTS_RE.match(name)
        if m:
            doc.add(
                "worker_events", value, {"worker": m.group(1)},
                help_name="worker_events",
            )
        else:
            doc.add(name, value, {})
    for name, snap in sorted((gateway_latency or {}).items()):
        doc.add_summary(name, snap, {})
    for name, per_worker in sorted((worker_gauges or {}).items()):
        for worker_id, value in sorted(per_worker.items()):
            doc.add(name, value, {"worker": str(worker_id)}, mtype="gauge")
    return doc.render()


# -- the minimal parser (round-trip tests, operator sanity checks) ----------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)\s*$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape_label(value: str) -> str:
    # One left-to-right pass: sequential str.replace calls corrupt values
    # where an earlier replacement manufactures a later escape (a literal
    # backslash followed by 'n' must stay backslash+n, not newline).
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), value
    )


def parse_prometheus_text(text: str) -> dict:
    """Parse v0.0.4 exposition text into ``{help, type, samples}``.

    ``samples`` is a list of ``(name, labels_dict, value)``; malformed
    lines raise (the round-trip test exists to catch renderer drift, so a
    lenient parser would defeat it).
    """
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, txt = line[len("# HELP "):].partition(" ")
            helps[name] = txt
            continue
        if line.startswith("# TYPE "):
            name, _, txt = line[len("# TYPE "):].partition(" ")
            types[name] = txt.strip()
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line: {raw!r}")
        labels = {
            k: _unescape_label(v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        samples.append((m.group("name"), labels, float(m.group("value"))))
    return {"help": helps, "type": types, "samples": samples}

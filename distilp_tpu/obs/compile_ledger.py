"""Compile ledger: XLA compilation & dispatch telemetry for the serving stack.

Every layer above can now see wall time (spans), solver-interior
convergence (conv traces) and objective health (SLOs) — but the layer
that actually dominates tail latency on an accelerator stack, XLA
compilation, was invisible: a cold solve, a persistent-cache hit, or a
silent recompile minted by a flipped static argument all showed up only
as an unexplained multi-second span. The ledger makes compiles first-class
events:

- **Entry-point registry.** Every module-level jitted entry point is
  wrapped once via :func:`instrument` (``dlint`` DLP020 enforces this for
  ``sched//gateway//solver//ops//twin/``): the wrapper is a transparent
  passthrough while no ledger is enabled (one module-global read per
  dispatch), and with a ledger enabled it counts dispatches and computes
  the call's STATIC-ARG signature plus a SHAPE-BUCKET signature, pushed
  onto a thread-local context stack for the duration of the call.
- **Compile events.** ``jax.monitoring`` duration/event listeners
  (registered once per process, dormant while no ledger is enabled)
  attribute every ``backend_compile`` duration — and every persistent
  compilation-cache hit/miss — to the innermost entry point on the
  compiling thread's context stack. Where ``jax.monitoring`` is
  unavailable the wrapper itself falls back to first-seen-signature
  detection (``ledger.fallback``): a signature never dispatched before
  records a synthetic compile event whose duration is that call's wall
  time.
- **Cause taxonomy.** Each compile event is classified against the
  entry point's signature history: ``cold`` (first compile ever),
  ``cache_hit`` (persistent cache served the executable), ``static_arg_flip``
  (a static argument changed — ``lp_backend``/``trace``/``diag``/``iters``/
  ``chunk`` each mint a new executable), ``shape_bucket_change`` (same
  statics, new argument shapes) and ``recompile`` (an exact signature
  compiled AGAIN — the storm class the ledger exists to catch).
- **Recompile-storm alarm.** N compiles of the same entry point inside a
  sliding window mark the event ``storm`` and bump the ledger's storm
  counter; the scheduler surfaces storms as the ``recompile_storms``
  metric (flight-recorded per tick, SLO-rule-able via ``c.recompile_storms``).
- **Cost attribution** (opt-in, ``cost_analysis=True``): the first real
  compile of an entry point additionally runs
  ``fn.lower(*args).compile().cost_analysis()`` and records FLOPs /
  bytes-accessed next to the compile counters (the AOT re-lowering is
  paid once per entry point, and its own compile events are suppressed).

Like every obs module this one is stdlib-only at import time (jax loads
lazily inside :func:`enable`) and opt-in: with no ledger enabled the
instrumented entry points run the exact pre-ledger path.

The JSONL dump follows the flight-recorder convention (header line +
one event per line) and round-trips byte-stably; :func:`render_report`
is a pure function of a dump, so ``solver compiles`` renders the same
bytes on every replay.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.lockwatch import make_lock

__all__ = [
    "CompileLedger",
    "InstrumentedJit",
    "instrument",
    "registered_entry_points",
    "set_dispatch_hook",
    "parse_cost_analysis",
    "enable",
    "disable",
    "current",
    "ledger_to_jsonl",
    "ledger_from_jsonl",
    "render_report",
    "CAUSES",
]

# The jax.monitoring event names this ledger listens for (jax 0.4.x).
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
CACHE_HIT_RETRIEVAL_EVENT = "/jax/compilation_cache/cache_retrieval_time_sec"
CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
CACHE_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"

CAUSE_COLD = "cold"
CAUSE_CACHE_HIT = "cache_hit"
CAUSE_STATIC_FLIP = "static_arg_flip"
CAUSE_SHAPE = "shape_bucket_change"
CAUSE_RECOMPILE = "recompile"
CAUSES = (
    CAUSE_COLD, CAUSE_CACHE_HIT, CAUSE_STATIC_FLIP, CAUSE_SHAPE,
    CAUSE_RECOMPILE,
)

# Attribution bucket for compiles that fired with no instrumented entry
# point on the compiling thread's stack — exactly the executables DLP020
# hunts (an inline jit, a stray eager compile in a dependency).
UNREGISTERED = "(unregistered)"

# name -> {"static_argnames": (...,)}: the process-wide entry-point
# registry. Populated at import time by the instrument() sites, so the
# expected cold-compile surface is enumerable without enabling anything.
_REGISTRY: Dict[str, dict] = {}

_tls = threading.local()
_LEDGER: Optional["CompileLedger"] = None
_LEDGER_LOCK = make_lock("compile_ledger.global")
# None = not probed yet; True/False = jax.monitoring listeners installed.
_MONITORING_OK: Optional[bool] = None
# Registry ride-along (obs.memory): one callable invoked per dispatch of
# every instrumented entry point, BEFORE the call — (wrapper, args,
# kwargs). None (the default) keeps the passthrough path at one extra
# module-global read; the hook owner is responsible for its own dormancy
# check and for never raising into the dispatch.
_DISPATCH_HOOK = None


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def parse_cost_analysis(cost) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes_accessed) from an XLA ``cost_analysis()`` return —
    a dict on some jaxlibs, a one-element list of dicts on others. ONE
    copy, shared with ``obs.memory``'s AOT pass: the two ledgers' FLOPs
    must come from the same parse or the analytic-vs-measured report
    silently compares different numbers."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not hasattr(cost, "get"):
        return None, None
    flops = cost.get("flops")
    bytes_accessed = cost.get("bytes accessed")
    return (
        float(flops) if flops is not None else None,
        float(bytes_accessed) if bytes_accessed is not None else None,
    )


def _static_sig(static_argnames: Sequence[str], kwargs: dict) -> str:
    """Canonical text of the call's static-argument values.

    Statics at this repo's entry points are always passed by keyword
    (``M=``, ``lp_backend=``, ``trace=`` ...); a static left to its
    default is recorded as absent — the jit cache treats the explicit
    default and the omission identically only when the call sites agree,
    and the ledger's job is to show what the call actually passed.
    """
    parts = [
        f"{k}={kwargs[k]!r}" for k in static_argnames if k in kwargs
    ]
    return ",".join(parts)


def _shape_leaf(x) -> Optional[str]:
    shape = getattr(x, "shape", None)
    if shape is None:
        return None
    dtype = getattr(x, "dtype", "")
    return f"{dtype}{list(shape)}"


def _shape_walk(x, out: List[str]) -> None:
    # Containers (dicts, NamedTuple batch structs, lists) flatten without
    # jax: tree structure at these entry points is plain python.
    leaf = _shape_leaf(x)
    if leaf is not None:
        out.append(leaf)
        return
    if isinstance(x, dict):
        for k in sorted(x):
            _shape_walk(x[k], out)
    elif isinstance(x, (list, tuple)):
        for v in x:
            _shape_walk(v, out)


def _shape_sig(
    args: tuple, kwargs: dict, static_argnames: Sequence[str]
) -> str:
    """Shape-bucket signature of the call's ARRAY arguments: dtype+shape
    per leaf, statics excluded. Long signatures (the twin's ~20-array data
    dict) compress to a count + stable digest so events stay one line."""
    out: List[str] = []
    for a in args:
        _shape_walk(a, out)
    for k in sorted(kwargs):
        if k in static_argnames:
            continue
        _shape_walk(kwargs[k], out)
    sig = ";".join(out)
    if len(sig) > 120:
        import hashlib

        digest = hashlib.sha1(sig.encode()).hexdigest()[:10]
        sig = f"{len(out)}leaves:{digest}"
    return sig


class CompileLedger:
    """Process-wide compile/dispatch ledger (see module docstring).

    All mutation happens under one re-entrant lock: wrappers dispatch from
    many shard-worker threads while the monitoring listeners attribute
    compiles and a timeline sampler reads the counters.
    """

    def __init__(
        self,
        capacity: int = 4096,
        storm_threshold: int = 5,
        storm_window_s: float = 60.0,
        cost_analysis: bool = False,
    ):
        if capacity < 1:
            raise ValueError("compile ledger capacity must be >= 1")
        if storm_threshold < 2:
            raise ValueError("storm threshold must be >= 2")
        self.capacity = capacity
        self.storm_threshold = storm_threshold
        self.storm_window_s = storm_window_s
        self.cost_analysis = cost_analysis
        # True = no jax.monitoring; the wrappers synthesize compile events
        # from first-seen signatures (set by enable(), or by tests).
        self.fallback = False
        self._lock = make_lock("compile_ledger.entries", kind="rlock")
        self._t0 = time.monotonic()
        self.events: "deque[dict]" = deque(maxlen=capacity)
        self._seq = 0  # total compile events ever (ring may have evicted)
        self.dispatches: Dict[str, int] = {}
        self.compiles: Dict[str, int] = {}
        self.compile_ms: Dict[str, float] = {}
        self.entry_cache_hits: Dict[str, int] = {}
        self.causes: Dict[str, int] = {}
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_requests = 0
        self.storms = 0
        self.unattributed = 0
        self.costs: Dict[str, dict] = {}
        self.cost_errors = 0
        # Classification state: per entry, the (static, shape) signatures
        # compiled so far and the statics seen — what separates a flip
        # from a shape-bucket change from an outright recompile.
        self._sigs: Dict[str, Dict[Tuple[str, str], int]] = {}
        self._statics: Dict[str, set] = {}
        # Storm detection: per entry, recent compile timestamps.
        self._recent: Dict[str, deque] = {}
        self._storming: Dict[str, bool] = {}

    # -- the write side ----------------------------------------------------

    def seq(self) -> int:
        """Monotonic compile-event counter — the capture token the
        scheduler snapshots around a tick (``events_since``)."""
        with self._lock:
            return self._seq

    def note_dispatch(self, entry: str) -> None:
        with self._lock:
            self.dispatches[entry] = self.dispatches.get(entry, 0) + 1

    def note_compile(
        self,
        entry: str,
        static_sig: str,
        shape_sig: str,
        ms: float,
        cache: Optional[str] = None,
    ) -> dict:
        """Record one compile event and classify its cause."""
        now = time.monotonic()
        with self._lock:
            self._seq += 1
            sigs = self._sigs.setdefault(entry, {})
            statics = self._statics.setdefault(entry, set())
            key = (static_sig, shape_sig)
            if cache == "hit":
                cause = CAUSE_CACHE_HIT
            elif not sigs:
                cause = CAUSE_COLD
            elif key in sigs:
                cause = CAUSE_RECOMPILE
            elif static_sig not in statics:
                cause = CAUSE_STATIC_FLIP
            else:
                cause = CAUSE_SHAPE
            sigs[key] = sigs.get(key, 0) + 1
            statics.add(static_sig)
            # Storm window: compiles of THIS entry in the last window_s.
            ring = self._recent.setdefault(entry, deque())
            ring.append(now)
            while ring and now - ring[0] > self.storm_window_s:
                ring.popleft()
            storm = storm_start = False
            if len(ring) >= self.storm_threshold:
                storm = True
                if not self._storming.get(entry):
                    # ONE transition per episode: `storms` (and every
                    # consumer of it — the c.recompile_storms series,
                    # the scheduler's recompile_storms counter) counts
                    # alarms, while the per-event `storm` flag keeps
                    # marking every compile the episode contains.
                    storm_start = True
                    self._storming[entry] = True
                    self.storms += 1
            else:
                self._storming[entry] = False
            ev = {
                "seq": self._seq,
                "t": round(now - self._t0, 6),
                "thread": threading.get_ident(),
                "entry": entry,
                "cause": cause,
                "compile_ms": round(ms, 3),
                "cache": cache,
                "static": static_sig,
                "shapes": shape_sig,
            }
            if storm:
                ev["storm"] = True
            if storm_start:
                ev["storm_start"] = True
            self.events.append(ev)
            self.compiles[entry] = self.compiles.get(entry, 0) + 1
            self.compile_ms[entry] = (
                self.compile_ms.get(entry, 0.0) + ms
            )
            self.causes[cause] = self.causes.get(cause, 0) + 1
            if cache == "hit":
                self.cache_hits += 1
                self.entry_cache_hits[entry] = (
                    self.entry_cache_hits.get(entry, 0) + 1
                )
            elif cache == "miss":
                self.cache_misses += 1
            if entry == UNREGISTERED:
                self.unattributed += 1
            return ev

    # -- listener/wrapper plumbing -----------------------------------------

    def _compile_from_listener(self, ms: float, cache: Optional[str]) -> None:
        if getattr(_tls, "suppress", False):
            return  # our own cost-analysis re-lowering, not user work
        stack = getattr(_tls, "stack", None)
        if stack:
            entry, static_sig, shape_sig = stack[-1]
        else:
            entry, static_sig, shape_sig = UNREGISTERED, "", ""
        self.note_compile(entry, static_sig, shape_sig, ms, cache=cache)

    def _fallback_note(self, frame: tuple, ms: float) -> None:
        """Wrap-the-jit fallback: a first-seen signature is the only
        compile evidence available, and the call's wall time stands in
        for the compile duration (an over-estimate that includes the
        execute — honest enough to count and classify by). Membership
        check and record happen under ONE (re-entrant) lock hold:
        concurrent same-signature dispatches — the gateway warmup shape,
        every fleet compiling the same layout at once — must not record
        twice and mint a spurious 'recompile'."""
        entry, static_sig, shape_sig = frame
        with self._lock:
            if (static_sig, shape_sig) in self._sigs.get(entry, {}):
                return
            self.note_compile(entry, static_sig, shape_sig, ms, cache=None)

    def _note_cost(self, entry: str, wrapper, args, kwargs) -> None:
        """Opt-in FLOPs/bytes attribution via the AOT path, once per
        entry point; its own lower/compile events are suppressed."""
        with self._lock:
            if entry in self.costs:
                return
            self.costs[entry] = {}  # claim before releasing the lock
        _tls.suppress = True
        try:
            cost = wrapper._fn.lower(*args, **kwargs).compile().cost_analysis()
            flops, bytes_accessed = parse_cost_analysis(cost)
            with self._lock:
                self.costs[entry] = {
                    "flops": flops,
                    "bytes_accessed": bytes_accessed,
                }
        except Exception:  # dlint: disable=DLP017 counted on the ledger itself (cost_errors); cost attribution is advisory and this module owns its own sink
            with self._lock:
                self.cost_errors += 1
                self.costs.pop(entry, None)
        finally:
            _tls.suppress = False

    def _dispatch(self, wrapper: "InstrumentedJit", args, kwargs):
        entry = wrapper.entry_point
        self.note_dispatch(entry)
        frame = (
            entry,
            _static_sig(wrapper.static_argnames, kwargs),
            _shape_sig(args, kwargs, wrapper.static_argnames),
        )
        stack = _stack()
        stack.append(frame)
        tok = self.seq()
        t0 = time.perf_counter()
        try:
            return wrapper._fn(*args, **kwargs)
        finally:
            stack.pop()
            ms = (time.perf_counter() - t0) * 1e3
            if self.fallback:
                self._fallback_note(frame, ms)
            if self.cost_analysis:
                compiled = any(
                    e["entry"] == entry and e["cause"] != CAUSE_CACHE_HIT
                    for e in self.events_since(tok)
                )
                if compiled:
                    self._note_cost(entry, wrapper, args, kwargs)

    # -- the read side -----------------------------------------------------

    def events_since(
        self, token: int, threads: Optional[set] = None
    ) -> List[dict]:
        """Events recorded after ``token`` (a prior ``seq()`` read),
        optionally filtered to the given thread idents — the scheduler
        passes its own solve threads so concurrent shards' compiles are
        never cross-billed to this tick."""
        with self._lock:
            out = [e for e in self.events if e["seq"] > token]
        if threads is not None:
            out = [e for e in out if e.get("thread") in threads]
        return out

    def counters(self) -> dict:
        """Flat totals for timeline emission / serve summaries."""
        with self._lock:
            return {
                "compiles": self._seq,
                "compile_cache_hits": self.cache_hits,
                "compile_cache_misses": self.cache_misses,
                "compile_cache_requests": self.cache_requests,
                "compile_ms_total": round(
                    sum(self.compile_ms.values()), 3
                ),
                "recompile_storms": self.storms,
                "dispatches": sum(self.dispatches.values()),
                "unattributed_compiles": self.unattributed,
            }

    def timeline_series(self) -> Dict[str, float]:
        """The ledger's timeline emission — ONE definition shared by
        ``Scheduler.timeline_sample`` and ``Gateway.timeline_sample`` so
        the two serving shapes' series names cannot drift. Cumulative,
        zero-valued from the first sample (a counter minted mid-incident
        has no baseline — the PR 13 lesson), emitted only while a ledger
        is enabled so feature-off samples stay byte-identical."""
        c = self.counters()
        return {
            "c.compiles": float(c["compiles"]),
            "c.compile_cache_hits": float(c["compile_cache_hits"]),
            "c.recompile_storms": float(c["recompile_storms"]),
            "compile_ms": float(c["compile_ms_total"]),
        }

    def cache_hit_rate(self) -> Optional[float]:
        """Persistent-cache hit rate over cache-visible requests; None
        when the persistent cache never engaged (DISTILP_COMPILE_CACHE
        unset — hits and misses both zero)."""
        with self._lock:
            total = self.cache_hits + self.cache_misses
            if total == 0:
                return None
            return self.cache_hits / total

    def summary(self) -> dict:
        """Per-entry-point table + cause histogram, JSON-able."""
        with self._lock:
            names = sorted(
                set(self.dispatches) | set(self.compiles) | set(_REGISTRY)
            )
            entries = {}
            for name in names:
                entries[name] = {
                    "registered": name in _REGISTRY,
                    "dispatches": self.dispatches.get(name, 0),
                    "compiles": self.compiles.get(name, 0),
                    "compile_ms": round(self.compile_ms.get(name, 0.0), 3),
                    "cache_hits": self.entry_cache_hits.get(name, 0),
                }
                if name in self.costs and self.costs[name]:
                    entries[name]["cost"] = dict(self.costs[name])
            return {
                "entries": entries,
                "causes": dict(sorted(self.causes.items())),
                "counters": self.counters(),
                "cache_hit_rate": self.cache_hit_rate(),
                "fallback": self.fallback,
            }

    def dump(self) -> dict:
        """The ledger as one JSON-able blob (header + event list)."""
        with self._lock:
            return {
                "header": {
                    "compile_ledger": 1,
                    "capacity": self.capacity,
                    "storm_threshold": self.storm_threshold,
                    "storm_window_s": self.storm_window_s,
                    "registry": sorted(_REGISTRY),
                    "summary": self.summary(),
                },
                "events": [dict(e) for e in self.events],
            }

    def to_jsonl(self) -> str:
        return ledger_to_jsonl(self.dump())

    def dump_jsonl(self, path) -> None:
        from pathlib import Path

        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(self.to_jsonl(), encoding="utf-8")


class InstrumentedJit:
    """Transparent wrapper around one module-level jitted entry point.

    With no ledger enabled the call path is one module-global read plus
    the underlying dispatch; attribute access (``.lower``, ``.trace``,
    ``._fun``) forwards to the wrapped jit so AOT consumers are
    unaffected. Calls that happen INSIDE an outer trace run at trace
    time only — their dispatch counts are trace-time counts, and their
    compiles are attributed to the enclosing entry point (the executable
    that actually gets built).
    """

    __slots__ = ("entry_point", "_fn", "static_argnames")

    def __init__(self, entry_point: str, fn, static_argnames=()):
        self.entry_point = entry_point
        self._fn = fn
        self.static_argnames = tuple(static_argnames)

    def __call__(self, *args, **kwargs):
        hook = _DISPATCH_HOOK
        if hook is not None:
            # The memory ledger's registry ride-along (set_dispatch_hook):
            # runs before the call so a first-dispatch AOT analysis sees
            # the exact arguments the real dispatch is about to compile.
            hook(self, args, kwargs)
        led = _LEDGER
        if led is None:
            return self._fn(*args, **kwargs)
        return led._dispatch(self, args, kwargs)

    def __getattr__(self, attr):
        return getattr(self._fn, attr)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"InstrumentedJit({self.entry_point!r}, {self._fn!r})"


def instrument(entry_point: str, fn, static_argnames=()) -> InstrumentedJit:
    """Register + wrap a jitted entry point (the DLP020-sanctioned idiom:
    ``X = instrument("layer.name", jax.jit(impl, static_argnames=S), S)``).

    Re-registering a name replaces the wrapped callable (the twin's
    kernel cache rebuilds after ``reset``); the registry entry survives.
    """
    _REGISTRY[entry_point] = {"static_argnames": tuple(static_argnames)}
    return InstrumentedJit(entry_point, fn, static_argnames)


def registered_entry_points() -> List[str]:
    """Sorted names of every instrumented entry point imported so far —
    the expected cold-compile surface ``make smoke-compile`` checks
    compiles against."""
    return sorted(_REGISTRY)


def set_dispatch_hook(hook) -> None:
    """Install (or clear, with None) the per-dispatch registry hook —
    the seam ``obs.memory`` rides to AOT-analyze each entry point once.
    Process-wide like the ledger itself; the hook must check its own
    dormancy and swallow its own failures (a raising hook would take
    every instrumented dispatch down with it)."""
    global _DISPATCH_HOOK
    _DISPATCH_HOOK = hook


# -- process-wide enable/disable ---------------------------------------------


def _on_duration(event: str, duration: float, **kw) -> None:
    led = _LEDGER
    if led is None or led.fallback:
        return
    if event == BACKEND_COMPILE_EVENT:
        if getattr(_tls, "cache_hit_pending", False):
            # The event wrapping compile_or_get_cached fires even when the
            # persistent cache served the executable — that retrieval was
            # already recorded as THE cache-hit event below; recording the
            # wrapper too would double-count every hit as a recompile.
            _tls.cache_hit_pending = False
            return
        cache = "miss" if getattr(_tls, "cache_miss", False) else None
        _tls.cache_miss = False
        led._compile_from_listener(duration * 1e3, cache=cache)
    elif event == CACHE_HIT_RETRIEVAL_EVENT:
        # A persistent-cache hit skips the real backend compile; the
        # retrieval time is the dispatch-blocking cost that remains.
        _tls.cache_hit_pending = True
        led._compile_from_listener(duration * 1e3, cache="hit")


def _on_event(event: str, **kw) -> None:
    led = _LEDGER
    if led is None or led.fallback:
        return
    if event == CACHE_MISS_EVENT:
        # Pairs with the backend_compile duration that follows on this
        # same thread (the compile the cache could not serve).
        _tls.cache_miss = True
    elif event == CACHE_REQUEST_EVENT:
        with led._lock:
            led.cache_requests += 1


def enable(ledger: Optional[CompileLedger] = None, **kwargs) -> CompileLedger:
    """Install ``ledger`` (or a fresh one built from ``kwargs``) as THE
    process ledger and make sure the jax.monitoring listeners are
    registered. Idempotent per process; listeners stay registered across
    disable/enable cycles and are dormant while no ledger is current.
    Returns the installed ledger.
    """
    global _LEDGER, _MONITORING_OK
    with _LEDGER_LOCK:
        led = ledger if ledger is not None else CompileLedger(**kwargs)
        if _MONITORING_OK is None:
            try:
                from jax import monitoring  # lazy: obs stays jax-free

                monitoring.register_event_duration_secs_listener(_on_duration)
                monitoring.register_event_listener(_on_event)
                _MONITORING_OK = True
            except Exception:  # dlint: disable=DLP017 recorded as ledger.fallback below — the wrap-the-jit path IS the accounting when listeners are unavailable
                _MONITORING_OK = False
        if not _MONITORING_OK:
            led.fallback = True
        _LEDGER = led
        return led


def disable() -> Optional[CompileLedger]:
    """Detach the process ledger (listeners go dormant); returns it."""
    global _LEDGER
    with _LEDGER_LOCK:
        led, _LEDGER = _LEDGER, None
        return led


def current() -> Optional[CompileLedger]:
    return _LEDGER


# -- persistence + report (the flight-recorder JSONL convention) -------------


def ledger_to_jsonl(dump: dict) -> str:
    """Header line + one event per line; pure function of the dump, so
    ``to_jsonl(from_jsonl(s)) == s`` byte-for-byte."""
    lines = [json.dumps(dump["header"], sort_keys=True)]
    for ev in dump["events"]:
        lines.append(json.dumps(ev, sort_keys=True))
    return "\n".join(lines) + "\n"


def ledger_from_jsonl(text: str) -> dict:
    """Parse a dumped ledger back into the ``dump()`` shape."""
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError("empty compile-ledger dump")
    header = json.loads(lines[0])
    if not isinstance(header, dict) or "compile_ledger" not in header:
        raise ValueError("compile-ledger dump missing its header line")
    if header["compile_ledger"] != 1:
        raise ValueError(
            f"unknown compile-ledger dump version {header['compile_ledger']!r}"
        )
    return {
        "header": header,
        "events": [json.loads(ln) for ln in lines[1:]],
    }


def render_report(dump: dict, top: int = 5) -> str:
    """Deterministic text report over a ``dump()``/``ledger_from_jsonl``
    blob: per-entry-point table, cause histogram, cache hit rate, top-N
    recompile offenders. No clocks, no thread ids — byte-identical on
    every replay of the same dump."""
    summary = dump["header"].get("summary", {})
    entries = summary.get("entries", {})
    causes = summary.get("causes", {})
    counters = summary.get("counters", {})
    out: List[str] = []
    out.append("compile ledger")
    out.append(
        "  compiles={compiles} dispatches={dispatches} "
        "storms={recompile_storms} unattributed={unattributed_compiles} "
        "compile_ms={compile_ms_total}".format(
            **{
                k: counters.get(k, 0)
                for k in (
                    "compiles", "dispatches", "recompile_storms",
                    "unattributed_compiles", "compile_ms_total",
                )
            }
        )
    )
    rate = summary.get("cache_hit_rate")
    out.append(
        "  persistent cache: "
        + (
            "not engaged (DISTILP_COMPILE_CACHE unset?)"
            if rate is None
            else "hit rate {:.1%} ({} hits / {} misses)".format(
                rate,
                counters.get("compile_cache_hits", 0),
                counters.get("compile_cache_misses", 0),
            )
        )
    )
    out.append("")
    out.append(
        f"  {'entry point':<34s} {'disp':>7s} {'compiles':>8s} "
        f"{'ms':>10s} {'hits':>5s}  registered"
    )
    for name in sorted(entries):
        e = entries[name]
        out.append(
            f"  {name:<34s} {e['dispatches']:>7d} {e['compiles']:>8d} "
            f"{e['compile_ms']:>10.1f} {e['cache_hits']:>5d}  "
            f"{'yes' if e['registered'] else 'NO'}"
        )
        cost = e.get("cost")
        if cost and (cost.get("flops") or cost.get("bytes_accessed")):
            out.append(
                "  {:<34s} flops={} bytes={}".format(
                    "", cost.get("flops"), cost.get("bytes_accessed")
                )
            )
    out.append("")
    out.append("  causes:")
    for cause in CAUSES:
        if causes.get(cause):
            out.append(f"    {cause:<20s} {causes[cause]:>6d}")
    offenders = sorted(
        (
            (name, e["compiles"])
            for name, e in entries.items()
            if e["compiles"] > 1
        ),
        key=lambda kv: (-kv[1], kv[0]),
    )[: max(0, top)]
    if offenders:
        out.append("")
        out.append(f"  top recompile offenders (compiles > 1, top {top}):")
        for name, n in offenders:
            out.append(f"    {name:<34s} {n:>6d}")
    storms = [e for e in dump.get("events", []) if e.get("storm")]
    if storms:
        out.append("")
        out.append(f"  storm-flagged events: {len(storms)}")
        for ev in storms[: max(0, top)]:
            out.append(
                f"    seq={ev['seq']} {ev['entry']} cause={ev['cause']} "
                f"static=[{ev['static']}]"
            )
    return "\n".join(out) + "\n"

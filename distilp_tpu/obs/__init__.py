"""Observability for the serving stack: tracing, exporters, flight data,
solver-interior convergence reports, metrics timelines and SLO alerting.

Seven pieces, all opt-in and backend-free (the obs layer imports neither
jax nor numpy nor the solver — it is plumbing the serving layers thread
data through; ``convergence``/``slo`` add pydantic, already a core
dependency):

- ``trace``  — span-based tracing of the event path (HTTP ingest → shard
  routing → worker queue wait → scheduler tick → solve → publish), a
  bounded finished-span ring, a JSONL writer, and the NOOP twins that make
  the disabled path free;
- ``export`` — span JSONL → Chrome trace-event JSON (Perfetto loadable;
  the ``solver spans`` CLI) and the labeled Prometheus v0.0.4 text
  exposition of scheduler metrics (+ the minimal parser that round-trips
  it in tests);
- ``flight`` — the flight recorder: per-shard rings of the last N tick
  records, auto-dumped to a post-mortem JSONL on breaker-open or a
  chaos-contract violation, readable live over HTTP;
- ``convergence`` — typed reports over the solver's in-jit telemetry
  (per-chunk LP residual traces, the branch-and-bound round log): the
  ``solver diagnose`` CLI and the bench ``convergence`` section render
  these, and the digest rides ``timings`` onto the ``sched.solve`` span
  and flight-recorder tick records;
- ``timeline`` — the in-process time-series layer: a fixed-cadence
  sampler snapshots the serving tier's own sinks into bounded per-series
  rings of (t, value), with rates/ratios/window fractions derived from
  deltas and a flight-recorder-style JSONL dump/load;
- ``compile_ledger`` — XLA compilation & dispatch telemetry: every
  registered jit entry point's compiles classified by cause (cold /
  cache-hit / static-arg-flip / shape-bucket-change / recompile), a
  recompile-storm alarm, and the ``solver compiles`` report — the layer
  the zero-recompile warm-serving gate reads;
- ``memory`` — the memory ledger riding the same entry-point registry:
  per-entry static memory models from AOT ``memory_analysis()`` (+
  FLOPs), live-array/RSS watermark sampling with a warm-path leak gate,
  the ``mem_headroom_bytes`` signal, and the ``solver memory`` report —
  the layer the zero-leak warm-serving gate reads;
- ``slo`` — declarative SLO specs compiled into error budgets with
  multi-window multi-burn-rate alert rules (hysteretic open/close, the
  ``sched.alert`` span + flight trail), the ``GET /slo``/``GET /signals``
  payloads (``SignalsPayload`` is the versioned autoscaling contract)
  and the ``solver slo`` CLI's offline timeline replay.

See README "Observability" / "Convergence diagnostics" for the span model,
the label table, and the trace-buffer semantics.
"""

from . import compile_ledger, memory
from .convergence import (
    ConvergenceTrace,
    LPChunkSample,
    RoundRecord,
    SearchTrace,
    build_search_trace,
    search_trace_from_jsonl,
    search_trace_to_jsonl,
)
from .export import (
    parse_prometheus_text,
    read_spans,
    render_prometheus,
    span_stats,
    spans_to_chrome,
    top_spans,
)
from .flight import FlightRecorder
from .slo import (
    AlertRule,
    BurnWindow,
    SignalsPayload,
    SLOConfig,
    SLOEngine,
    SLOSpec,
    build_signals,
)
from .timeline import (
    Timeline,
    TimelineSampler,
    flatten_metrics_snapshot,
    synthesize_overload_timeline,
)
from .trace import (
    NOOP_SPAN,
    NOOP_TRACER,
    JsonlSpanWriter,
    Span,
    SpanContext,
    Tracer,
    now_ms,
)

__all__ = [
    "compile_ledger",
    "memory",
    "Tracer",
    "Span",
    "SpanContext",
    "JsonlSpanWriter",
    "NOOP_TRACER",
    "NOOP_SPAN",
    "now_ms",
    "read_spans",
    "spans_to_chrome",
    "top_spans",
    "span_stats",
    "render_prometheus",
    "parse_prometheus_text",
    "FlightRecorder",
    "Timeline",
    "TimelineSampler",
    "flatten_metrics_snapshot",
    "synthesize_overload_timeline",
    "SLOConfig",
    "SLOSpec",
    "SLOEngine",
    "AlertRule",
    "BurnWindow",
    "SignalsPayload",
    "build_signals",
    "LPChunkSample",
    "ConvergenceTrace",
    "RoundRecord",
    "SearchTrace",
    "build_search_trace",
    "search_trace_to_jsonl",
    "search_trace_from_jsonl",
]

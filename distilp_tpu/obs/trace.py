"""Span-based tracing for the serving path: where did an event's time go?

One churn event's journey — HTTP parse, shard routing, the wait on the
owning worker's queue, the scheduler tick, the solve, the publish — is a
TREE of spans sharing one ``trace_id``. Each span carries wall-clock start
and duration, free-form attributes (the solver's ``timings`` dict rides
the solve span), and point-in-time events (quarantine decisions, breaker
transitions, health changes). Finished spans land in a lock-protected
bounded ring and, optionally, a JSONL writer (``serve --trace-spans-dir``);
``solver spans`` converts that JSONL into Chrome trace-event JSON
(Perfetto / chrome://tracing loadable — see ``obs.export``).

Off by default, and the disabled path is a no-op: every instrumentation
site talks to a tracer-shaped object, and :data:`NOOP_TRACER` answers all
of it with shared do-nothing singletons — no ids minted, no clocks read,
no locks taken — so ``--workers 1`` serving without the flag stays
byte-identical to the uninstrumented daemon (pinned by the smoke gates'
counter assertions).

Parenting across threads is EXPLICIT, never ambient: asyncio code passes
``SpanContext`` objects (a thread-local "current span" would leak between
interleaved coroutines on the loop thread and mis-parent spans), while the
synchronous scheduler path uses the per-thread stack — ``with
tracer.span(...)`` nests, and a worker thread adopts a foreign context via
``tracer.attach(ctx)`` before running a tick, so the tick's spans parent
under the gateway ingest span that enqueued it.

All span timestamps are ``time.perf_counter()`` milliseconds: monotonic,
comparable across threads of one process (which is all a trace ever
spans), and exactly what the Chrome converter wants.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, NamedTuple, Optional

from ..utils.lockwatch import make_lock

__all__ = [
    "Span",
    "SpanContext",
    "Tracer",
    "NOOP_TRACER",
    "NOOP_SPAN",
    "JsonlSpanWriter",
    "now_ms",
]

# One process-wide id mint: `next()` on an itertools.count is atomic under
# the GIL, so ids are unique across worker threads without a lock.
_IDS = itertools.count(1)

# Sentinel: "parent not given — use the calling thread's current span".
_CURRENT = object()


def now_ms() -> float:
    """The tracer's clock: monotonic milliseconds (perf_counter)."""
    return time.perf_counter() * 1e3


def _next_id() -> str:
    return f"{next(_IDS):012x}"


class SpanContext(NamedTuple):
    """The propagatable identity of a span (what children parent to)."""

    trace_id: str
    span_id: str


def _clean(value: Any):
    """Attribute values must survive json.dumps; coerce the near-misses
    (numpy scalars from the solver's timings dict) and stringify the rest."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    try:
        return float(value)
    except (TypeError, ValueError):  # dlint: disable=DLP017 type-coercion fallback (str repr), not fault recovery — nothing is swallowed
        return str(value)


class Span:
    """One timed unit of work; record lands in the tracer ring on end().

    Usable as a context manager (``with tracer.span(...)``: participates in
    the thread-local nesting stack) or started/ended manually via
    ``tracer.start_span`` + ``end()`` (no stack participation — the asyncio
    idiom, where explicit parents are the only sound propagation).
    """

    __slots__ = (
        "_tracer", "name", "trace_id", "span_id", "parent_id",
        "t0_ms", "attrs", "events", "thread", "_ended",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        parent_id: Optional[str],
        attrs: Optional[dict] = None,
    ):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _next_id()
        self.parent_id = parent_id
        self.t0_ms = now_ms()
        self.attrs: Dict[str, Any] = (
            {k: _clean(v) for k, v in attrs.items()} if attrs else {}
        )
        self.events: List[dict] = []
        self.thread = threading.current_thread().name
        self._ended = False

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = _clean(value)

    def add_event(self, name: str, **attrs) -> None:
        ev = {"name": name, "t_ms": now_ms()}
        for k, v in attrs.items():
            ev[k] = _clean(v)
        self.events.append(ev)

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def end(self) -> None:
        if self._ended:  # idempotent: error paths may end twice
            return
        self._ended = True
        self._tracer._record(self)

    def __enter__(self) -> "Span":
        self._tracer._push(self.context())
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer._pop()
        self.end()
        return False


class _NoopSpan:
    """The disabled path: one shared instance, every method a no-op."""

    __slots__ = ()

    def set_attr(self, key: str, value) -> None:
        pass

    def add_event(self, name: str, **attrs) -> None:
        pass

    def context(self) -> None:
        return None

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class _Attach:
    """Context manager installing a foreign SpanContext as the calling
    thread's current span (the worker-thread adoption idiom)."""

    __slots__ = ("_tracer", "_ctx")

    def __init__(self, tracer: "Tracer", ctx: Optional[SpanContext]):
        self._tracer = tracer
        self._ctx = ctx

    def __enter__(self) -> "_Attach":
        if self._ctx is not None:
            self._tracer._push(self._ctx)
        return self

    def __exit__(self, *exc) -> bool:
        if self._ctx is not None:
            self._tracer._pop()
        return False


class Tracer:
    """Collects finished spans into a bounded ring (+ optional writer).

    Thread safety: the ring append and the writer flush happen under one
    lock (spans finish on gateway workers, the asyncio loop thread and the
    replay thread at once); the per-thread nesting stack is thread-local
    and needs none.
    """

    enabled = True

    def __init__(self, capacity: int = 8192, writer=None):
        if capacity < 1:
            raise ValueError("tracer ring capacity must be >= 1")
        self._ring: "deque[dict]" = deque(maxlen=capacity)  # guarded-by: self._lock
        self._lock = make_lock("trace.ring")
        self._writer = writer
        self._local = threading.local()
        self.dropped = 0  # writer failures (serving outranks span loss)

    # -- the thread-local nesting stack ------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, ctx: SpanContext) -> None:
        self._stack().append(ctx)

    def _pop(self) -> None:
        st = self._stack()
        if st:
            st.pop()

    def current(self) -> Optional[SpanContext]:
        st = self._stack()
        return st[-1] if st else None

    def attach(self, ctx: Optional[SpanContext]) -> _Attach:
        """Adopt ``ctx`` as this thread's current span (None = no-op)."""
        return _Attach(self, ctx)

    # -- span lifecycle ----------------------------------------------------

    def _resolve(self, parent) -> tuple:
        """(trace_id, parent_id) for a new span under ``parent``."""
        if parent is _CURRENT:
            parent = self.current()
        if parent is None:
            return _next_id(), None
        return parent.trace_id, parent.span_id

    def span(self, name: str, parent=_CURRENT, attrs: Optional[dict] = None) -> Span:
        """A span for ``with``: enters the thread-local nesting stack."""
        trace_id, parent_id = self._resolve(parent)
        return Span(self, name, trace_id, parent_id, attrs)

    def start_span(
        self, name: str, parent=_CURRENT, attrs: Optional[dict] = None
    ) -> Span:
        """A manually ended span: never touches the nesting stack (use for
        asyncio code, where the stack would leak across coroutines)."""
        trace_id, parent_id = self._resolve(parent)
        return Span(self, name, trace_id, parent_id, attrs)

    def record_span(
        self,
        name: str,
        t0_ms: float,
        t1_ms: Optional[float] = None,
        parent: Optional[SpanContext] = None,
        attrs: Optional[dict] = None,
    ) -> SpanContext:
        """Record a span after the fact from explicit timestamps — the
        queue-wait idiom: enqueue time was noted on the submitting thread,
        the span materializes at pickup on the worker thread."""
        trace_id, parent_id = self._resolve(parent)
        span = Span(self, name, trace_id, parent_id, attrs)
        span.t0_ms = t0_ms
        span._ended = True  # recorded below, never via end()
        self._record(span, t1_ms=t1_ms if t1_ms is not None else now_ms())
        return span.context()

    def _record(self, span: Span, t1_ms: Optional[float] = None) -> None:
        rec = {
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "name": span.name,
            "t0_ms": round(span.t0_ms, 3),
            "dur_ms": round((t1_ms if t1_ms is not None else now_ms()) - span.t0_ms, 3),
            "thread": span.thread,
            "attrs": span.attrs,
            "events": span.events,
        }
        with self._lock:
            self._ring.append(rec)
            if self._writer is not None:
                try:
                    self._writer.write(rec)  # dlint: disable=DLP031 file order must match ring order; the writer is line-buffered JSONL and a span record is tiny
                except OSError:  # dlint: disable=DLP017 accounted in self.dropped; the tracer has no metrics sink and span loss must never fail a tick
                    self.dropped += 1

    # -- the read side -----------------------------------------------------

    def drain(self) -> List[dict]:
        """Snapshot-and-clear of the finished-span ring."""
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out

    def spans(self) -> List[dict]:
        """Snapshot of the finished-span ring (ring left intact)."""
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None


class _NoopTracer:
    """The disabled tracer: every call answers with shared no-ops. No ids,
    no clock reads, no locks — instrumentation sites cost an attribute
    lookup and a constant return."""

    enabled = False

    def span(self, name, parent=_CURRENT, attrs=None) -> _NoopSpan:
        return NOOP_SPAN

    def start_span(self, name, parent=_CURRENT, attrs=None) -> _NoopSpan:
        return NOOP_SPAN

    def record_span(self, name, t0_ms, t1_ms=None, parent=None, attrs=None):
        return None

    def attach(self, ctx) -> _NoopSpan:
        return NOOP_SPAN

    def current(self) -> None:
        return None

    def drain(self) -> list:
        return []

    def spans(self) -> list:
        return []

    def close(self) -> None:
        pass


NOOP_TRACER = _NoopTracer()


class JsonlSpanWriter:
    """Append-only JSONL sink for finished spans (one object per line).

    The tracer serializes calls under its own lock, so the writer itself
    stays lock-free; ``default=str`` keeps an exotic attribute value from
    ever killing a tick over a log line.
    """

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")
        self.written = 0

    def write(self, record: dict) -> None:
        self._fh.write(json.dumps(record, default=str) + "\n")
        self.written += 1

    def close(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

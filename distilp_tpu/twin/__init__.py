"""Digital-twin evaluation of HALDA placements.

The solver optimizes an analytic proxy for per-token latency; this package
*executes* placements against that proxy's own physics and stress-tests
them under device drift:

- ``model``  — deterministic pipeline-execution model (host numpy oracle);
- ``engine`` — vmapped Monte-Carlo perturbation engine (one JAX dispatch
  per robustness report; lazy jax import);
- ``report`` — pydantic report schemas (importable without a backend);
- ``api``    — ``evaluate_placement`` / ``robustness_report`` /
  ``rank_agreement`` / ``twin_p95_score``.

Used by ``solver evaluate`` (CLI), the scheduler's risk-aware serving mode
(``sched.scheduler``), and the ``twin_*`` bench section.
"""

from .api import (
    applicable_candidates,
    evaluate_placement,
    rank_agreement,
    robustness_report,
    twin_p95_score,
)
from .model import (
    TwinArrays,
    build_twin_arrays,
    placement_applicable,
    placement_vectors,
    simulate_placement,
)
from .report import (
    DeviceSensitivity,
    DeviceTwinRow,
    RobustnessReport,
    TwinEvaluation,
)

__all__ = [
    "evaluate_placement",
    "robustness_report",
    "rank_agreement",
    "twin_p95_score",
    "applicable_candidates",
    "TwinArrays",
    "build_twin_arrays",
    "placement_applicable",
    "placement_vectors",
    "simulate_placement",
    "RobustnessReport",
    "TwinEvaluation",
    "DeviceTwinRow",
    "DeviceSensitivity",
]

"""Vmapped Monte-Carlo perturbation engine for the digital twin.

One jitted JAX program evaluates EVERYTHING a robustness report needs in a
single dispatch (MPAX-style batched math programming, arXiv:2412.09734):

- ``samples`` log-normal perturbation draws (seeded ``jax.random``, mean-1
  multiplicative jitter on per-device compute throughput, link time, disk
  rate and memory headroom, plus optional straggler/dropout scenarios),
- one deterministic sensitivity probe per device (that device alone
  degraded by a fixed factor),
- the unperturbed base run,

all stacked on one batch axis and pushed through ``jax.vmap`` of the same
pipeline-execution math as ``twin.model.simulate_placement`` (the host
numpy oracle the engine is tested against). The placement enters as
precomputed per-device vectors, so every candidate placement of one fleet
shape reuses one compiled program — the risk-aware scheduler prices many
candidates per tick against a single compile.

jax imports live inside functions: the twin layer is lazy (dlint DLP013),
so reports and schemas stay importable without a backend.
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs.compile_ledger import instrument
from .model import PlacementVectors

# The jitted kernel, built on first use (lazy jax import). jit's own cache
# handles per-shape (M, R) specialization behind this single callable.
# Build is locked: the gateway's shard workers score risk-aware ticks
# from several threads, and two concurrent first uses would otherwise
# both trace (wasted compile) and race the global's publication.
_KERNEL = None
_KERNEL_LOCK = threading.Lock()


def _get_kernel():
    global _KERNEL
    if _KERNEL is not None:
        return _KERNEL
    with _KERNEL_LOCK:
        return _build_kernel()


def _build_kernel():
    global _KERNEL
    if _KERNEL is not None:  # lost the build race: use the winner's
        return _KERNEL

    import jax
    import jax.numpy as jnp

    def _eval_one(data, comp, comm, disk, mem):
        """Latency + feasibility of one perturbed execution (traced)."""
        comp_s = data["compute0"] * comp
        comm_s = data["comm0"] * comm
        off_s = data["off0"] * comm
        # Capacity drift shrinks/grows positive headroom; rows already in
        # deficit (rhs <= 0) and inactive rows (huge rhs) keep their value.
        ram_rhs = jnp.where(
            data["ram_rhs"] > 0.0, data["ram_rhs"] * mem, data["ram_rhs"]
        )
        cuda_rhs = jnp.where(
            data["cuda_rhs"] > 0.0, data["cuda_rhs"] * mem, data["cuda_rhs"]
        )
        metal_rhs = jnp.where(
            data["metal_rhs"] > 0.0, data["metal_rhs"] * mem, data["metal_rhs"]
        )
        bp = data["bp"]
        s_need = jnp.maximum(
            0.0,
            jnp.ceil(jnp.maximum(0.0, data["ram_lhs0"] - ram_rhs) / bp - 1e-12),
        )
        vram_deficit = jnp.maximum(
            jnp.maximum(0.0, data["cuda_lhs0"] - cuda_rhs),
            jnp.maximum(0.0, data["metal_lhs0"] - metal_rhs),
        )
        t_need = jnp.maximum(0.0, jnp.ceil(vram_deficit / bp - 1e-12))
        violation = jnp.any(s_need > data["s_cap"] + 1e-9) | jnp.any(
            t_need > data["t_cap"] + 1e-9
        )
        s_used = jnp.minimum(s_need, data["s_cap"])
        t_used = jnp.minimum(t_need, data["t_cap"])
        disk_s = (data["pen_set"] * s_used + data["pen_vram"] * t_used) * disk
        busy = comp_s + disk_s + off_s + comm_s
        cycle = jnp.max(busy + 0.5 * data["prefetch0"] * disk)
        latency = (
            jnp.sum(comp_s + disk_s)
            + data["kfac"] * cycle
            + jnp.sum(comm_s)
            + jnp.sum(off_s)
            + data["kappa"]
        )
        return latency, violation

    def _mc(data, seed, sigmas, dropout_p, dropout_slowdown, degrade, samples):
        """(latencies, violations) over [samples | M sensitivity | base]."""
        M = data["compute0"].shape[0]
        key = jax.random.key(seed)
        k_norm, k_drop = jax.random.split(key)
        z = jax.random.normal(k_norm, (4, samples, M))
        # Mean-1 log-normal: exp(sigma z - sigma^2/2); sigma=0 -> exactly 1.
        sig = sigmas.reshape(4, 1, 1)
        jit = jnp.exp(sig * z - 0.5 * sig * sig)
        comp, comm, disk, mem = jit[0], jit[1], jit[2], jit[3]
        straggler = jax.random.bernoulli(k_drop, dropout_p, (samples, M))
        comp = comp * jnp.where(straggler, dropout_slowdown, 1.0)

        ones_m = jnp.ones((M, M))
        sens = 1.0 + (degrade - 1.0) * jnp.eye(M)  # row j: device j degraded
        one = jnp.ones((1, M))
        comp_all = jnp.concatenate([comp, sens, one])
        comm_all = jnp.concatenate([comm, sens, one])
        disk_all = jnp.concatenate([disk, ones_m, one])
        mem_all = jnp.concatenate([mem, ones_m, one])
        return jax.vmap(_eval_one, in_axes=(None, 0, 0, 0, 0))(
            data, comp_all, comm_all, disk_all, mem_all
        )

    # Registered compile-ledger entry point, cached into a module global
    # behind _KERNEL_LOCK — the ONE sanctioned function-scope jit shape
    # (built once per process, never per call); the justified disable is
    # exactly what DLP020's fixture documents.
    _KERNEL = instrument(
        "twin.mc_kernel",
        jax.jit(_mc, static_argnames=("samples",)),  # dlint: disable=DLP020 built ONCE into the module-global kernel cache behind _KERNEL_LOCK; jax must not import at module scope here (DLP013)
        static_argnames=("samples",),
    )
    return _KERNEL


def _device_data(vec: PlacementVectors) -> dict:
    """The placement's vectors as a dict of arrays for the jitted kernel."""
    return {
        "compute0": np.asarray(vec.compute0),
        "comm0": np.asarray(vec.comm0),
        "off0": np.asarray(vec.off0),
        "prefetch0": np.asarray(vec.prefetch0),
        "pen_set": np.asarray(vec.pen_set),
        "pen_vram": np.asarray(vec.pen_vram),
        "ram_lhs0": np.asarray(vec.ram_lhs0),
        "ram_rhs": np.asarray(vec.ram_rhs),
        "cuda_lhs0": np.asarray(vec.cuda_lhs0),
        "cuda_rhs": np.asarray(vec.cuda_rhs),
        "metal_lhs0": np.asarray(vec.metal_lhs0),
        "metal_rhs": np.asarray(vec.metal_rhs),
        "s_cap": np.asarray(vec.s_cap),
        "t_cap": np.asarray(vec.t_cap),
        "bp": np.float64(vec.bp),
        "kfac": np.float64(vec.k - 1),
        "kappa": np.float64(vec.kappa),
    }


def run_monte_carlo(
    vec: PlacementVectors,
    samples: int = 1024,
    seed: int = 0,
    sigma_compute: float = 0.08,
    sigma_comm: float = 0.15,
    sigma_disk: float = 0.10,
    sigma_mem: float = 0.0,
    dropout_p: float = 0.0,
    dropout_slowdown: float = 8.0,
    degrade: float = 1.25,
) -> dict:
    """One dispatch: MC samples + per-device sensitivity probes + base run.

    Returns plain numpy: ``latencies`` (samples,), ``violations`` (samples,)
    bool, ``sens_latencies`` (M,), ``base_latency`` float, ``base_violation``
    bool. Deterministic for a fixed seed (seeded ``jax.random``; the chunk
    order inside the one program is fixed).
    """
    if samples < 1:
        raise ValueError("samples must be >= 1")
    kernel = _get_kernel()
    data = _device_data(vec)
    sigmas = np.asarray(
        [sigma_compute, sigma_comm, sigma_disk, sigma_mem], dtype=np.float64
    )
    lat, viol = kernel(
        data,
        np.uint32(seed),
        sigmas,
        np.float64(dropout_p),
        np.float64(dropout_slowdown),
        np.float64(degrade),
        samples=int(samples),
    )
    lat = np.asarray(lat)
    viol = np.asarray(viol)
    M = vec.compute0.shape[0]
    return {
        "latencies": lat[:samples],
        "violations": viol[:samples],
        "sens_latencies": lat[samples : samples + M],
        "base_latency": float(lat[-1]),
        "base_violation": bool(viol[-1]),
    }


def reset_kernel_cache() -> None:
    """Drop the jitted program (tests use this to count retraces)."""
    global _KERNEL
    with _KERNEL_LOCK:
        _KERNEL = None

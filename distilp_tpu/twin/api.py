"""Public digital-twin API: evaluate, stress-test and rank placements.

``evaluate_placement``  — deterministic simulated execution (host numpy),
with the per-device breakdown and the HALDA-objective cross-check.
``robustness_report``   — seeded vmapped Monte-Carlo: latency quantiles
under device drift, feasibility-violation probability, worst-device
sensitivity ranking; one JAX dispatch per report.
``rank_agreement``      — does the twin order candidate placements the same
way the solver objective does? (The proxy-validation question the ISSUE's
golden-fixture tests pin.)
``twin_p95_score``      — the risk-aware scheduler's scoring primitive.

Every backend-touching entry point arms the axon guard first
(``force_cpu_if_env_requested``): plain ``JAX_PLATFORMS=cpu`` library users
must never wedge on a dead tunneled-TPU plugin (VERDICT round-5 finding 2).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..axon_guard import force_cpu_if_env_requested
from ..common import DeviceProfile, ModelProfile
from ..solver.result import HALDAResult
from .model import (
    TwinArrays,
    build_twin_arrays,
    placement_applicable,
    placement_vectors,
    simulate_placement,
)
from .report import DeviceSensitivity, RobustnessReport, TwinEvaluation

# Feasibility-violation weight in the risk score: a placement with ANY
# observed violation probability must lose to every violation-free one at
# any latency scale this solver produces (objectives are O(10) seconds) —
# so the penalty has a fixed step at p > 0 plus a graded term that still
# orders violating candidates among themselves.
VIOLATION_PENALTY_S = 1e3


def evaluate_placement(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    result: HALDAResult,
    kv_bits: str = "8bit",
    moe: Optional[bool] = None,
    load_factors: Optional[Sequence[float]] = None,
    batch_size: int = 1,
    cross_check: bool = True,
) -> TwinEvaluation:
    """Deterministically execute ``result`` on the fleet's digital twin.

    ``kv_bits``/``moe``/``batch_size``/``load_factors`` must match what the
    placement was solved with — they define the coefficient vocabulary the
    twin prices against (same builders as the solver). ``cross_check``
    fills the report's objective/rel_err fields from ``result.obj_value``.
    """
    arrays = build_twin_arrays(
        devs, model, kv_bits=kv_bits, moe=moe,
        load_factors=load_factors, batch_size=batch_size,
    )
    return simulate_placement(
        arrays,
        result.w,
        result.n,
        y=result.y,
        k=result.k,
        objective=result.obj_value if cross_check else None,
    )


def robustness_report(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    result: HALDAResult,
    samples: int = 1024,
    seed: int = 0,
    kv_bits: str = "8bit",
    moe: Optional[bool] = None,
    load_factors: Optional[Sequence[float]] = None,
    batch_size: int = 1,
    sigma_compute: float = 0.08,
    sigma_comm: float = 0.15,
    sigma_disk: float = 0.10,
    sigma_mem: float = 0.0,
    dropout_p: float = 0.0,
    dropout_slowdown: float = 8.0,
    degrade: float = 1.25,
    arrays: Optional[TwinArrays] = None,
) -> RobustnessReport:
    """Monte-Carlo robustness report for one placement (one JAX dispatch).

    ``arrays`` lets repeated callers (the risk-aware scheduler scoring many
    candidates per tick) reuse one fleet build. Deterministic for a fixed
    seed.
    """
    force_cpu_if_env_requested()
    from .engine import run_monte_carlo

    if arrays is None:
        arrays = build_twin_arrays(
            devs, model, kv_bits=kv_bits, moe=moe,
            load_factors=load_factors, batch_size=batch_size,
        )
    vec = placement_vectors(arrays, result.w, result.n, y=result.y, k=result.k)
    out = run_monte_carlo(
        vec,
        samples=samples,
        seed=seed,
        sigma_compute=sigma_compute,
        sigma_comm=sigma_comm,
        sigma_disk=sigma_disk,
        sigma_mem=sigma_mem,
        dropout_p=dropout_p,
        dropout_slowdown=dropout_slowdown,
        degrade=degrade,
    )
    lat = np.asarray(out["latencies"], dtype=float)
    base = out["base_latency"]
    deltas = np.maximum(0.0, np.asarray(out["sens_latencies"], dtype=float) - base)
    total = float(deltas.sum())
    order = np.argsort(-deltas, kind="stable")
    sensitivity = [
        DeviceSensitivity(
            name=devs[int(j)].name,
            delta_s=float(deltas[int(j)]),
            share=float(deltas[int(j)] / total) if total > 0 else 0.0,
        )
        for j in order
    ]
    return RobustnessReport(
        samples=int(samples),
        seed=int(seed),
        sigma_compute=sigma_compute,
        sigma_comm=sigma_comm,
        sigma_disk=sigma_disk,
        sigma_mem=sigma_mem,
        dropout_p=dropout_p,
        dropout_slowdown=dropout_slowdown,
        degrade=degrade,
        base_latency_s=base,
        mean_s=float(lat.mean()),
        p50_s=float(np.percentile(lat, 50)),
        p95_s=float(np.percentile(lat, 95)),
        p99_s=float(np.percentile(lat, 99)),
        worst_s=float(lat.max()),
        p_violation=float(np.asarray(out["violations"]).mean()),
        sensitivity=sensitivity,
    )


def rank_agreement(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    results: Sequence[HALDAResult],
    kv_bits: str = "8bit",
    moe: Optional[bool] = None,
    batch_size: int = 1,
    tie_tol: float = 1e-9,
) -> Dict[str, object]:
    """Twin-vs-objective ranking check over candidate placements.

    Evaluates each candidate's unperturbed twin latency (float64 host
    path) and compares the induced order against the solver objectives:
    ``pairwise_inversions`` counts candidate pairs the two orders disagree
    on (pairs whose objectives differ by less than ``tie_tol`` are ties and
    cannot invert), ``spearman`` is the rank correlation. The acceptance
    bar on the golden fixtures is zero inversions.
    """
    if len(results) < 2:
        raise ValueError("rank agreement needs at least two candidate placements")
    arrays = build_twin_arrays(devs, model, kv_bits=kv_bits, moe=moe, batch_size=batch_size)
    twin = np.array(
        [
            simulate_placement(arrays, r.w, r.n, y=r.y, k=r.k).latency_s
            for r in results
        ]
    )
    obj = np.array([r.obj_value for r in results])
    inversions = 0
    pairs = 0
    for i in range(len(results)):
        for j in range(i + 1, len(results)):
            if abs(obj[i] - obj[j]) <= tie_tol:
                continue
            pairs += 1
            if (obj[i] - obj[j]) * (twin[i] - twin[j]) < 0:
                inversions += 1
    return {
        "pairwise_inversions": inversions,
        "comparable_pairs": pairs,
        "spearman": _spearman(obj, twin),
        "twin_latencies": [float(x) for x in twin],
        "objectives": [float(x) for x in obj],
        "ks": [int(r.k) for r in results],
    }


def _spearman(a: np.ndarray, b: np.ndarray) -> float:
    """Spearman rank correlation (average ranks for ties; no scipy needed)."""

    def _ranks(x: np.ndarray) -> np.ndarray:
        order = np.argsort(x, kind="stable")
        ranks = np.empty(len(x), dtype=float)
        ranks[order] = np.arange(len(x), dtype=float)
        # Average tied ranks so exact-duplicate objectives don't skew rho.
        for v in np.unique(x):
            mask = x == v
            if mask.sum() > 1:
                ranks[mask] = ranks[mask].mean()
        return ranks

    ra, rb = _ranks(np.asarray(a, dtype=float)), _ranks(np.asarray(b, dtype=float))
    va = ra - ra.mean()
    vb = rb - rb.mean()
    denom = float(np.sqrt((va * va).sum() * (vb * vb).sum()))
    if denom == 0.0:
        return 1.0
    return float((va * vb).sum() / denom)


def twin_p95_score(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    result: HALDAResult,
    samples: int = 256,
    seed: int = 0,
    kv_bits: str = "8bit",
    moe: Optional[bool] = None,
    arrays: Optional[TwinArrays] = None,
    **mc_kwargs,
) -> Dict[str, float]:
    """Risk score of one placement: twin p95 latency + violation penalty.

    The scheduler's risk-aware mode minimizes this over warm-pool
    candidates — lower is better; a placement with any feasibility-
    violation probability is pushed behind every violation-free one.
    Returns ``{"score", "p95_s", "p_violation", "base_latency_s"}``.
    """
    rep = robustness_report(
        devs, model, result, samples=samples, seed=seed,
        kv_bits=kv_bits, moe=moe, arrays=arrays, **mc_kwargs,
    )
    penalty = (
        VIOLATION_PENALTY_S * (1.0 + rep.p_violation)
        if rep.p_violation > 0
        else 0.0
    )
    return {
        "score": rep.p95_s + penalty,
        "p95_s": rep.p95_s,
        "p_violation": rep.p_violation,
        "base_latency_s": rep.base_latency_s,
    }


def applicable_candidates(
    arrays: TwinArrays,
    candidates: Sequence[Optional[HALDAResult]],
) -> List[HALDAResult]:
    """Filter cached placements down to ones this fleet can execute."""
    out: List[HALDAResult] = []
    for c in candidates:
        if c is None:
            continue
        if placement_applicable(arrays, c.w, c.n, y=c.y, k=c.k):
            out.append(c)
    return out

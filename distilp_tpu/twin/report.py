"""Twin report schemas: the JSON contract of the digital-twin evaluation.

Schema-only module (pydantic, no jax, no solver imports) so reports can be
parsed, rendered and round-tripped by processes that never load a backend —
the same layering rule as ``distilp_tpu.common`` (dlint DLP013 applies to
the whole ``twin`` layer).

Two documents:

- :class:`TwinEvaluation`  — one deterministic simulated execution of a
  placement: per-device busy breakdown, the pipeline cycle time, the
  predicted per-token latency, and the cross-check against the HALDA
  objective it must agree with.
- :class:`RobustnessReport` — the vmapped Monte-Carlo view: latency
  quantiles under device drift, feasibility-violation probability, and the
  worst-device sensitivity ranking.
"""

from __future__ import annotations

from typing import List, Optional

from pydantic import BaseModel


class DeviceTwinRow(BaseModel):
    """One device's simulated steady-state execution of its window."""

    name: str
    w: int  # layers hosted
    n: int  # of those, accelerator-resident
    y: Optional[int] = None  # routed experts hosted (MoE placements)
    busy_s: float  # B_i: everything below plus comm/offload constants
    compute_s: float  # a·w + b·n (+ expert share) seconds
    comm_s: float  # t_comm: per-round inter-device link time
    offload_s: float  # xi: host<->accelerator round trip (split memory)
    disk_s: float  # slack-layer streaming penalty seconds
    prefetch_s: float  # F_i: next-window disk prefetch seconds
    spill_layers: int  # layers that overflow RAM and stream from disk
    vram_spill_layers: int  # layers that overflow VRAM/wired memory
    feasible: bool  # required spill fits the placement's slack capacity


class TwinEvaluation(BaseModel):
    """Deterministic pipeline execution of one placement over one fleet."""

    k: int  # pipeline segments
    W: int  # layers per segment
    latency_s: float  # predicted per-token latency (the twin's headline)
    cycle_s: float  # steady-state cycle time C
    bottleneck: str  # device attaining the cycle bound
    feasible: bool  # all devices' spill fits their slack capacity
    # Cross-check against the analytic proxy the solver optimizes: the
    # HALDA objective of the same placement (when the caller has it) and
    # the relative disagreement. The two must agree on the golden
    # fixtures — that is the twin's conformance contract.
    objective_s: Optional[float] = None
    rel_err: Optional[float] = None
    devices: List[DeviceTwinRow] = []

    def render_text(self) -> str:
        lines = [
            "=" * 66,
            "Digital-twin execution",
            "=" * 66,
            f"k={self.k} segments x W={self.W} layers; "
            f"predicted per-token latency {self.latency_s:.6f} s "
            f"(cycle {self.cycle_s:.6f} s, bottleneck {self.bottleneck})",
        ]
        if self.objective_s is not None:
            err = f" (rel err {self.rel_err:.2e})" if self.rel_err is not None else ""
            lines.append(f"HALDA objective cross-check: {self.objective_s:.6f} s{err}")
        if not self.feasible:
            lines.append("WARNING: placement overflows memory beyond disk-slack capacity")
        lines.append("")
        lines.append(
            f"{'device':<30s} {'w':>3s} {'n':>3s} {'busy_s':>10s} "
            f"{'compute':>9s} {'disk':>8s} {'spill':>5s}"
        )
        for d in self.devices:
            flag = "" if d.feasible else "  INFEASIBLE"
            lines.append(
                f"{d.name:<30.30s} {d.w:>3d} {d.n:>3d} {d.busy_s:>10.6f} "
                f"{d.compute_s:>9.6f} {d.disk_s:>8.5f} {d.spill_layers:>5d}{flag}"
            )
        return "\n".join(lines)


class DeviceSensitivity(BaseModel):
    """Latency cost of one device degrading by the probe factor, ranked."""

    name: str
    delta_s: float  # latency increase when only this device slows down
    share: float  # delta normalized over all devices (sums to ~1)


class RobustnessReport(BaseModel):
    """Monte-Carlo what-if view of one placement under device drift.

    Produced by ``twin.api.robustness_report`` from a single vmapped JAX
    dispatch: ``samples`` log-normal perturbation draws + one deterministic
    degraded run per device (the sensitivity probes) + the unperturbed base
    run all evaluate in one batched program.
    """

    samples: int
    seed: int
    # Log-normal jitter widths (mean-1 multiplicative noise per device).
    sigma_compute: float
    sigma_comm: float
    sigma_disk: float
    sigma_mem: float
    # Straggler/dropout scenario: with probability dropout_p a device runs
    # dropout_slowdown x slower for that sample (0 disables).
    dropout_p: float
    dropout_slowdown: float
    degrade: float  # sensitivity-probe slowdown factor
    base_latency_s: float  # unperturbed twin latency (must match objective)
    mean_s: float
    p50_s: float
    p95_s: float
    p99_s: float
    worst_s: float
    p_violation: float  # P(RAM/VRAM overflow beyond slack capacity)
    sensitivity: List[DeviceSensitivity] = []  # worst device first

    def render_text(self) -> str:
        lines = [
            "=" * 66,
            f"Robustness report ({self.samples} Monte-Carlo samples, seed {self.seed})",
            "=" * 66,
            f"jitter: compute {self.sigma_compute:.3f} / comm {self.sigma_comm:.3f} / "
            f"disk {self.sigma_disk:.3f} / mem {self.sigma_mem:.3f}"
            + (
                f"; dropout p={self.dropout_p:.3f} x{self.dropout_slowdown:.1f}"
                if self.dropout_p > 0
                else ""
            ),
            "",
            f"  base latency : {self.base_latency_s:.6f} s",
            f"  mean         : {self.mean_s:.6f} s",
            f"  p50          : {self.p50_s:.6f} s",
            f"  p95          : {self.p95_s:.6f} s",
            f"  p99          : {self.p99_s:.6f} s",
            f"  worst        : {self.worst_s:.6f} s",
            f"  P(mem violation): {self.p_violation:.4f}",
            "",
            f"Worst-device sensitivity (latency cost of a {self.degrade:.2f}x slowdown):",
        ]
        for i, s in enumerate(self.sensitivity, 1):
            lines.append(
                f"  {i:2d}. {s.name:<30.30s} +{s.delta_s:.6f} s ({s.share * 100:5.1f}%)"
            )
        return "\n".join(lines)

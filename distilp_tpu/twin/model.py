"""Digital-twin pipeline execution model of a HALDA placement.

Deterministically simulates executing a placement ``(k, w, n[, y])`` over a
fleet of :class:`DeviceProfile` s: per-segment compute from the same
alpha/beta/xi coefficient vocabulary the solver prices with
(``solver.coeffs``), inter-device transfer from ``t_comm`` (whose measured
link shape is ``comm_latency + payload/comm_bandwidth``), GPU-offload split
from ``n``, memory-overflow disk streaming from the capacity rows, and the
pipeline's steady-state cycle/prefetch overlap.

The simulation reproduces the MILP's own physics on purpose: for a fixed
integer assignment the optimal stall is ``z_i = F_i / 2`` (it equalizes the
cycle and prefetch bounds), the optimal spill is the minimal integer slack
covering each memory deficit, and the steady-state cycle time is
``C = max_i (B_i + F_i/2)`` — so the twin's unperturbed latency must equal
the HALDA objective of the same placement. That equality is the
conformance contract cross-checked on the golden fixtures
(``tests/test_twin.py``); everything the Monte-Carlo engine perturbs
(``twin.engine``) starts from these arrays.

Host-side numpy only (same layering as ``solver.coeffs``): the arrays are
O(M) and built once per placement; the vmapped sampling lives in
``twin.engine``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..common import DeviceProfile, ModelProfile, kv_bits_to_factor
from ..solver.assemble import INACTIVE_RHS  # the MILP's own inactive-row RHS
from ..solver.coeffs import HaldaCoeffs, build_coeffs
from .report import DeviceTwinRow, TwinEvaluation


@dataclass
class TwinArrays:
    """Everything one fleet+model instance's twin needs, as dense arrays.

    Placement-independent: one build serves every candidate placement of
    the same fleet (the risk-aware scheduler scores many placements against
    one build). All arrays are (M,) float64 unless noted.
    """

    M: int
    L: int
    bp: float  # resident bytes per layer (b')
    moe: bool
    E: int  # routed experts (0 in dense mode)
    names: List[str]
    a: np.ndarray  # CPU seconds per layer
    b_gpu: np.ndarray  # accelerator-minus-CPU delta seconds per layer
    g_raw: np.ndarray  # MoE seconds per y-unit, times k (zeros in dense)
    xi: np.ndarray  # host<->accelerator round trip seconds
    t_comm: np.ndarray  # per-round link seconds
    pen_set: np.ndarray  # disk penalty sec per RAM-spilled layer (by set)
    pen_vram: np.ndarray  # disk penalty sec per VRAM-spilled layer
    prefetch_coef: np.ndarray  # b'/s_disk: prefetch seconds per hosted layer
    ram_coef_n: np.ndarray  # b' where the RAM row subtracts n, else 0
    eb_ram: np.ndarray  # resident expert bytes per y in the primary pool
    ram_rhs: np.ndarray  # RAM capacity row RHS (INACTIVE_RHS when absent)
    eb_vram: np.ndarray
    cuda_rhs: np.ndarray
    eb_metal: np.ndarray
    metal_rhs: np.ndarray
    has_gpu: np.ndarray  # bool
    kappa: float  # head I/O + tail-deficit objective constant


def build_twin_arrays(
    devs: Sequence[DeviceProfile],
    model: ModelProfile,
    kv_bits: str = "8bit",
    moe: Optional[bool] = None,
    load_factors: Optional[Sequence[float]] = None,
    batch_size: int = 1,
) -> TwinArrays:
    """Assemble the twin's arrays with the solver's own coefficient builders.

    Mirrors ``solver.api._build_instance``: MoE placements price their
    dense half on the expert-free adjusted profile and carry the expert
    block (``g_raw``/``eb_*``) separately, so the twin and the MILP read
    the same numbers from the same code path.
    """
    from ..solver.moe import adjust_model, build_moe_arrays, resolve_moe

    use_moe = resolve_moe(model, moe)
    kv_factor = kv_bits_to_factor(kv_bits)
    if use_moe:
        coeffs = build_coeffs(devs, adjust_model(model), kv_factor, batch_size=batch_size)
        marr = build_moe_arrays(devs, model, load_factors=load_factors)
    else:
        coeffs = build_coeffs(devs, model, kv_factor, batch_size=batch_size)
        marr = None
    return _arrays_from_coeffs(coeffs, marr, [d.name for d in devs])


def _arrays_from_coeffs(coeffs: HaldaCoeffs, marr, names: List[str]) -> TwinArrays:
    M = coeffs.M
    pen_by_set = {1: coeffs.pen_m1, 2: coeffs.pen_m2, 3: coeffs.pen_m3}
    pen_set = np.zeros(M)
    for i in range(M):
        pen_set[i] = pen_by_set[int(coeffs.set_id[i])][i]

    def _rhs(active: np.ndarray, vals: np.ndarray) -> np.ndarray:
        out = np.where(active, vals, INACTIVE_RHS)
        return np.where(np.isfinite(out), out, INACTIVE_RHS)

    zeros = np.zeros(M)
    return TwinArrays(
        M=M,
        L=coeffs.L,
        bp=float(coeffs.bprime),
        moe=marr is not None,
        E=int(marr.E) if marr is not None else 0,
        names=list(names),
        a=np.asarray(coeffs.a, dtype=float),
        b_gpu=np.asarray(coeffs.b_gpu, dtype=float),
        g_raw=np.asarray(marr.g_raw, dtype=float) if marr is not None else zeros,
        xi=np.asarray(coeffs.xi, dtype=float),
        t_comm=np.asarray(coeffs.t_comm, dtype=float),
        pen_set=pen_set,
        pen_vram=np.asarray(coeffs.pen_vram, dtype=float),
        prefetch_coef=coeffs.bprime / np.asarray(coeffs.s_disk, dtype=float),
        ram_coef_n=np.where(coeffs.ram_minus_n, coeffs.bprime, 0.0),
        eb_ram=np.asarray(marr.eb_ram, dtype=float) if marr is not None else zeros,
        ram_rhs=_rhs(np.ones(M, dtype=bool), np.asarray(coeffs.ram_rhs, dtype=float)),
        eb_vram=np.asarray(marr.eb_vram, dtype=float) if marr is not None else zeros,
        cuda_rhs=_rhs(coeffs.cuda_row, np.asarray(coeffs.cuda_rhs, dtype=float)),
        eb_metal=np.asarray(marr.eb_metal, dtype=float) if marr is not None else zeros,
        metal_rhs=_rhs(coeffs.metal_row, np.asarray(coeffs.metal_rhs, dtype=float)),
        has_gpu=np.asarray(coeffs.has_gpu, dtype=bool),
        kappa=float(coeffs.kappa),
    )


def placement_applicable(arrays: TwinArrays, w, n, y=None, k: Optional[int] = None) -> bool:
    """Whether a (possibly cached/stale) placement can execute on this fleet.

    Structural checks only — the risk-aware scheduler uses this to filter
    warm-pool candidates before pricing them: right device count, window
    sums matching L, the offload count within the window, no accelerator
    layers on accelerator-free devices, and (MoE) a full expert cover.
    """
    w = np.asarray(w)
    n = np.asarray(n)
    if w.shape != (arrays.M,) or n.shape != (arrays.M,):
        return False
    if np.any(w < 1) or np.any(n < 0) or np.any(n > w):
        return False
    if k is not None and (k <= 0 or int(w.sum()) * int(k) != arrays.L):
        return False
    if np.any((n > 0) & ~arrays.has_gpu):
        return False
    if arrays.moe:
        if y is None:
            return False
        y = np.asarray(y)
        if y.shape != (arrays.M,) or np.any(y < 0) or int(y.sum()) != arrays.E:
            return False
    elif y is not None and np.any(np.asarray(y) != 0):
        return False
    return True


@dataclass
class PlacementVectors:
    """One placement reduced to the per-device vectors the engine perturbs.

    Precomputing these on the host keeps the vmapped kernel's signature
    placement-shape-free: every candidate of one fleet shares one compiled
    program (the sample axis is the only batch dimension).
    """

    compute0: np.ndarray  # a·w + b·n + (g/k)·y seconds at nominal speed
    comm0: np.ndarray  # t_comm
    off0: np.ndarray  # xi
    prefetch0: np.ndarray  # F_i at nominal disk speed
    pen_set: np.ndarray
    pen_vram: np.ndarray
    ram_lhs0: np.ndarray  # resident bytes charged to the RAM row
    ram_rhs: np.ndarray
    cuda_lhs0: np.ndarray
    cuda_rhs: np.ndarray
    metal_lhs0: np.ndarray
    metal_rhs: np.ndarray
    s_cap: np.ndarray  # max RAM-spill layers the MILP's slack allows
    t_cap: np.ndarray  # max VRAM-spill layers
    bp: float
    k: int
    kappa: float


def placement_vectors(
    arrays: TwinArrays, w, n, y=None, k: int = 1
) -> PlacementVectors:
    """Reduce one placement to the engine's per-device vectors."""
    w = np.asarray(w, dtype=float)
    n = np.asarray(n, dtype=float)
    if arrays.moe:
        if y is None:
            raise ValueError("MoE twin needs the expert assignment y")
        y = np.asarray(y, dtype=float)
    else:
        y = np.zeros(arrays.M)
    W = arrays.L // int(k)
    compute0 = arrays.a * w + arrays.b_gpu * n + (arrays.g_raw / float(k)) * y
    # Slack caps follow the MILP bounds: W layers in dense mode; in MoE mode
    # a device cannot stream more layers than it hosts (s <= w, t <= n).
    s_cap = np.minimum(w, W) if arrays.moe else np.full(arrays.M, float(W))
    t_cap = np.minimum(n, W) if arrays.moe else np.where(arrays.has_gpu, float(W), 0.0)
    return PlacementVectors(
        compute0=compute0,
        comm0=arrays.t_comm.copy(),
        off0=arrays.xi.copy(),
        prefetch0=arrays.prefetch_coef * w,
        pen_set=arrays.pen_set.copy(),
        pen_vram=arrays.pen_vram.copy(),
        ram_lhs0=arrays.bp * w - arrays.ram_coef_n * n + arrays.eb_ram * y,
        ram_rhs=arrays.ram_rhs.copy(),
        cuda_lhs0=arrays.bp * n + arrays.eb_vram * y,
        cuda_rhs=arrays.cuda_rhs.copy(),
        metal_lhs0=arrays.bp * n + arrays.eb_metal * y,
        metal_rhs=arrays.metal_rhs.copy(),
        s_cap=s_cap,
        t_cap=t_cap,
        bp=arrays.bp,
        k=int(k),
        kappa=arrays.kappa,
    )


def simulate_placement(
    arrays: TwinArrays,
    w: Sequence[int],
    n: Sequence[int],
    y: Optional[Sequence[int]] = None,
    k: int = 1,
    objective: Optional[float] = None,
) -> TwinEvaluation:
    """One deterministic pipeline execution (float64, host numpy).

    This is the engine's conformance oracle AND the user-facing breakdown:
    per-device busy times, spill layers, the cycle bound and the predicted
    per-token latency. ``objective`` (the solver's value for the same
    placement) fills the cross-check fields.
    """
    vec = placement_vectors(arrays, w, n, y=y, k=k)
    M = arrays.M

    ram_deficit = np.maximum(0.0, vec.ram_lhs0 - vec.ram_rhs)
    s_need = np.maximum(0.0, np.ceil(ram_deficit / vec.bp - 1e-12))
    vram_deficit = np.maximum(
        np.maximum(0.0, vec.cuda_lhs0 - vec.cuda_rhs),
        np.maximum(0.0, vec.metal_lhs0 - vec.metal_rhs),
    )
    t_need = np.maximum(0.0, np.ceil(vram_deficit / vec.bp - 1e-12))
    feas = (s_need <= vec.s_cap + 1e-9) & (t_need <= vec.t_cap + 1e-9)
    s_used = np.minimum(s_need, vec.s_cap)
    t_used = np.minimum(t_need, vec.t_cap)

    # + 0.0 normalizes the -0.0 that np.maximum(0.0, -0.0) may hand back.
    disk_s = vec.pen_set * s_used + vec.pen_vram * t_used + 0.0
    busy = vec.compute0 + disk_s + vec.off0 + vec.comm0
    cycle_terms = busy + 0.5 * vec.prefetch0
    C = float(cycle_terms.max())
    bottleneck = int(np.argmax(cycle_terms))
    latency = (
        float((vec.compute0 + disk_s).sum())
        + (vec.k - 1) * C
        + float(vec.comm0.sum() + vec.off0.sum())
        + vec.kappa
    )

    y_list = list(np.asarray(y, dtype=int)) if (arrays.moe and y is not None) else None
    rows = [
        DeviceTwinRow(
            name=arrays.names[i],
            w=int(w[i]),
            n=int(n[i]),
            y=int(y_list[i]) if y_list is not None else None,
            busy_s=float(busy[i]),
            compute_s=float(vec.compute0[i]),
            comm_s=float(vec.comm0[i]),
            offload_s=float(vec.off0[i]),
            disk_s=float(disk_s[i]),
            prefetch_s=float(vec.prefetch0[i]),
            spill_layers=int(s_used[i]),
            vram_spill_layers=int(t_used[i]),
            feasible=bool(feas[i]),
        )
        for i in range(M)
    ]
    rel_err = None
    if objective is not None:
        rel_err = abs(latency - objective) / max(1e-12, abs(objective))
    return TwinEvaluation(
        k=int(k),
        W=arrays.L // int(k),
        latency_s=latency,
        cycle_s=C,
        bottleneck=arrays.names[bottleneck],
        feasible=bool(feas.all()),
        objective_s=objective,
        rel_err=rel_err,
        devices=rows,
    )

"""Seeded open-loop arrival schedules: Poisson x diurnal x regional bursts.

The arrival model factors into three deterministic pieces:

- **base process** — a Poisson stream at ``base_rate`` events/sec summed
  across the fleet set (independent thin streams per fleet is the same
  process; one stream plus a weighted fleet pick is cheaper and lets the
  burst correlation below fall out naturally);
- **diurnal modulation** — the rate is scaled by
  ``1 + diurnal_amplitude * sin(2*pi*t/period + phase)``: the day/night
  swing every consumer-facing service rides (amplitude 0 turns it off);
- **correlated regional bursts** — fleets are partitioned round-robin
  into ``n_regions`` regions; each region gets its own Poisson process
  of burst onsets, and while a burst is live every fleet in that region
  arrives ``burst_factor`` times more often. Correlation is the point:
  a regional incident hits MANY fleets that hash to the SAME handful of
  workers at once, which is the queue shape shedding and coalescing
  exist for (independent per-fleet spikes average out and never stress
  a bounded queue the same way).

Sampling is inhomogeneous-Poisson thinning against the peak rate, so the
schedule is an exact draw of the composite process and a pure function
of ``(config, n_fleets)`` — same inputs, byte-identical schedule, which
is what lets ``tests/traces/openloop_*.jsonl`` be committed captures
with regeneration tests (the ``spec_burst``/``spec_flap`` pattern).

Event *payloads* ride the existing churn simulator: each fleet's events
come from ``sched.sim.generate_trace`` under ``scenario``, with the
event's trace-time ``t`` rewritten to its scheduled arrival time.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np
from pydantic import BaseModel

from ..sched.events import event_from_dict
from ..sched.sim import generate_trace
from ..gateway.traces import make_fleet_from_spec


class ArrivalConfig(BaseModel):
    """One open-loop arrival process, fully seeded.

    ``base_rate`` is the fleet-set aggregate events/sec at the diurnal
    midpoint with no burst live; the peak offered rate is
    ``base_rate * (1 + diurnal_amplitude) * burst_factor`` (every region
    bursting at the diurnal crest). ``duration_s`` is schedule time — the
    executor compresses or dilates it with ``time_scale`` at replay.
    """

    seed: int = 0
    duration_s: float = 60.0
    base_rate: float = 2.0
    diurnal_amplitude: float = 0.0
    diurnal_period_s: float = 86400.0
    diurnal_phase: float = 0.0
    n_regions: int = 1
    burst_rate_per_region: float = 0.0  # burst onsets/sec, per region
    burst_factor: float = 1.0
    burst_duration_s: float = 0.0
    scenario: str = "drift"
    fleet_size: int = 3
    fleet_seed: int = 0


class ScheduledEvent(NamedTuple):
    """One arrival: fire ``event`` at ``at_s`` (schedule time) for
    ``fleet_id`` — whether or not the service has kept up."""

    at_s: float
    fleet_id: str
    event: object


def _fleet_specs(config: ArrivalConfig, n_fleets: int) -> Dict[str, dict]:
    """Deterministic synthetic-fleet specs, the loadgen's naming scheme
    (``f000``..) and spec-line shape, so open-loop and closed-loop arms
    of a bench sweep are built over the identical fleet set."""
    return {
        f"f{i:03d}": {
            "m": config.fleet_size,
            "seed": config.fleet_seed * 1000 + i,
        }
        for i in range(n_fleets)
    }


def _burst_windows(
    config: ArrivalConfig, rng: np.random.Generator
) -> List[List[Tuple[float, float]]]:
    """Per-region burst [start, end) windows over the schedule horizon.

    Drawn up front (one exponential-gap walk per region) so the rate
    function below is a pure lookup — thinning needs rate(t) at arbitrary
    t, and drawing burst onsets lazily would entangle the two streams'
    randomness."""
    windows: List[List[Tuple[float, float]]] = []
    for _region in range(max(1, config.n_regions)):
        region_windows: List[Tuple[float, float]] = []
        if config.burst_rate_per_region > 0 and config.burst_factor > 1:
            t = 0.0
            while True:
                t += float(rng.exponential(1.0 / config.burst_rate_per_region))
                if t >= config.duration_s:
                    break
                region_windows.append((t, t + config.burst_duration_s))
        windows.append(region_windows)
    return windows


def _bursting(windows: List[Tuple[float, float]], t: float) -> bool:
    return any(a <= t < b for a, b in windows)


def generate_openloop_schedule(
    config: ArrivalConfig, n_fleets: int
) -> Tuple[Dict[str, dict], List[ScheduledEvent]]:
    """(fleet specs, timestamped events) — a pure function of its inputs.

    Two-pass: first the arrival process decides WHEN and WHICH FLEET
    (thinning against the peak rate, fleet picked in proportion to its
    live burst weight), then each fleet's event payloads are drawn from
    the churn simulator in one batch of exactly the count that fleet was
    assigned. The per-fleet event stream is therefore the same ordered
    ``generate_trace`` prefix regardless of how arrivals interleave
    across fleets — interleaving and payloads stay independently seeded.
    """
    if n_fleets < 1:
        raise ValueError("need at least one fleet")
    rng = np.random.default_rng(config.seed)
    specs = _fleet_specs(config, n_fleets)
    fleet_ids = list(specs)
    regions = [i % max(1, config.n_regions) for i in range(n_fleets)]
    windows = _burst_windows(config, rng)

    def diurnal(t: float) -> float:
        if config.diurnal_amplitude <= 0:
            return 1.0
        return 1.0 + config.diurnal_amplitude * math.sin(
            2.0 * math.pi * t / config.diurnal_period_s
            + config.diurnal_phase
        )

    peak = (
        config.base_rate
        * (1.0 + max(0.0, config.diurnal_amplitude))
        * max(1.0, config.burst_factor)
    )
    if peak <= 0:
        raise ValueError("arrival config has a non-positive peak rate")

    # Pass 1: arrival instants + fleet assignment (thinning).
    arrivals: List[Tuple[float, int]] = []  # (t, fleet index)
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= config.duration_s:
            break
        weights = np.array(
            [
                config.burst_factor
                if _bursting(windows[regions[i]], t)
                else 1.0
                for i in range(n_fleets)
            ]
        )
        # Aggregate rate at t = mean fleet weight x diurnal x base.
        rate_t = config.base_rate * diurnal(t) * float(weights.mean())
        if rng.random() >= rate_t / peak:
            continue
        fleet_idx = int(rng.choice(n_fleets, p=weights / weights.sum()))
        arrivals.append((t, fleet_idx))

    # Pass 2: per-fleet payloads from the churn simulator, then stitch.
    counts = [0] * n_fleets
    for _, i in arrivals:
        counts[i] += 1
    payloads: List[List] = []
    for i, fleet_id in enumerate(fleet_ids):
        devices = make_fleet_from_spec(fleet_id, specs[fleet_id])
        payloads.append(
            generate_trace(
                config.scenario,
                counts[i],
                seed=config.seed * 7919 + i,
                base_fleet=devices,
            )
            if counts[i]
            else []
        )
    cursor = [0] * n_fleets
    items: List[ScheduledEvent] = []
    for at_s, i in arrivals:
        ev = payloads[i][cursor[i]]
        cursor[i] += 1
        # The payload's trace-time t is the simulator's exponential walk;
        # rewrite it to the scheduled arrival so the one timeline in the
        # file is the one the executor fires on.
        ev = ev.model_copy(update={"t": round(at_s, 6)})
        items.append(ScheduledEvent(round(at_s, 6), fleet_ids[i], ev))
    return specs, items


# -- the JSONL wire format ---------------------------------------------------
#
# A superset of the gateway trace (gateway.traces): spec lines identical,
# event lines additionally carry "at_s". The closed-loop replayers parse
# these files unchanged (read_gateway_trace ignores unknown keys), so one
# committed capture serves both the open-loop harness and a deterministic
# sequential replay.


def write_openloop_trace(
    path, specs: Dict[str, dict], items: List[ScheduledEvent]
) -> None:
    """Write the schedule; spec lines first, then events in fire order."""
    with open(Path(path), "w") as f:
        for fleet_id, spec in specs.items():
            f.write(json.dumps({"fleet": fleet_id, "synthetic": spec}) + "\n")
        for at_s, fleet_id, ev in items:
            data = ev.model_dump(exclude_defaults=True)
            data["kind"] = ev.kind
            f.write(
                json.dumps(
                    {"fleet": fleet_id, "at_s": at_s, "event": data}
                )
                + "\n"
            )


def read_openloop_trace(
    path,
) -> Tuple[Dict[str, dict], List[ScheduledEvent]]:
    """Load a schedule back; raises on event lines without a timestamp
    (a file without them is a closed-loop gateway trace — replay it with
    ``serve``, not the open-loop executor)."""
    specs: Dict[str, dict] = {}
    items: List[ScheduledEvent] = []
    with open(path, "r") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            data = json.loads(line)
            fleet_id = data.get("fleet")
            if not fleet_id:
                raise ValueError(
                    f"{path}:{lineno}: open-loop trace line without a "
                    "fleet tag"
                )
            if "synthetic" in data:
                specs[fleet_id] = dict(data["synthetic"])
            elif "event" in data:
                if "at_s" not in data:
                    raise ValueError(
                        f"{path}:{lineno}: event line without at_s — this "
                        "is a closed-loop gateway trace, not an open-loop "
                        "schedule"
                    )
                if fleet_id not in specs:
                    raise ValueError(
                        f"{path}:{lineno}: event for undeclared fleet "
                        f"{fleet_id!r}"
                    )
                items.append(
                    ScheduledEvent(
                        float(data["at_s"]),
                        fleet_id,
                        event_from_dict(data["event"]),
                    )
                )
            else:
                raise ValueError(
                    f"{path}:{lineno}: open-loop trace line needs a "
                    "'synthetic' spec or an 'event'"
                )
    return specs, items


def is_openloop_trace(path) -> Optional[bool]:
    """True when the file's first event line carries ``at_s``; False when
    it is a plain (closed-loop) trace; None when it has no event lines."""
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                data = json.loads(line)
            except ValueError:  # dlint: disable=DLP017 format probe: a non-JSON line means "not an open-loop trace", not a fault
                return False
            if "event" in data:
                return "at_s" in data
    return None

"""Open-loop traffic: seeded arrival processes + the overload harness.

Every load number before this package came from CLOSED-loop replay
(``gateway.loadgen``): the next event waits for the previous one's
placement, so offered load can never exceed capacity and collapse
behavior is structurally invisible. This package generates load the way
the world does — events fire at their scheduled time whether or not the
service kept up — and measures what the gateway's admission control
(bounded queues, shedding, coalescing, degraded serving) does about it:

- ``arrivals`` — the seeded arrival-process generator: a Poisson base
  rate modulated by a diurnal curve and correlated regional bursts,
  with per-fleet event payloads drawn from the existing churn simulator
  (``sched.sim``); emits fleet-tagged TIMESTAMPED schedules, plus the
  JSONL trace format (``tests/traces/openloop_*.jsonl`` are committed
  seeded captures with byte-exact regeneration tests);
- ``openloop`` — the executor + harness: fire each event at its
  scheduled time (lateness accumulates, the generator never throttles),
  measure scheduled-time latency (p50/p99/p99.9 — what a CLIENT sees,
  queueing included), count sheds/coalesces/degraded serves, and
  reconcile every shed record-by-record against the flight recorder
  (``shed_violations`` — the ChaosReport.violations() contract extended
  to admission control).

Stdlib + numpy + the existing gateway/sched stack; jax only ever loads
through the schedulers the gateway builds (this layer is in dlint's lazy
set).
"""

from .arrivals import (
    ArrivalConfig,
    ScheduledEvent,
    generate_openloop_schedule,
    read_openloop_trace,
    write_openloop_trace,
)
from .openloop import execute_openloop, run_openloop, shed_violations

__all__ = [
    "ArrivalConfig",
    "ScheduledEvent",
    "generate_openloop_schedule",
    "read_openloop_trace",
    "write_openloop_trace",
    "execute_openloop",
    "run_openloop",
    "shed_violations",
]

"""Open-loop execution: fire the schedule on time, measure the fallout.

The executor's one rule is the open-loop contract: an event fires at its
scheduled instant whether or not earlier events have completed — lateness
accumulates in the queues instead of throttling the generator. That is
exactly what closed-loop replay cannot do, and it is why these numbers
can show collapse: offered load is an input here, not an emergent
property of service speed.

Latency is measured from the event's SCHEDULED time to placement
publication — the client clock. Under overload that includes dispatch
lateness and queue wait, so p99/p99.9 here degrade the way a user's
would; the solve-only view lives in the gateway's own histograms.

``shed_violations`` is the admission-control accounting contract
(``ChaosReport.violations()`` extended to overload): every shed the
gateway counted must be explained record-by-record by the flight
recorder, per fleet, with monotone shed indices — a shed that is counted
but unrecorded (or vice versa) is a contract violation, exactly like an
unaccounted quarantine in the chaos soak.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Sequence

from ..gateway.gateway import Gateway, QueueFull
from ..sched.metrics import _quantile
from ..sched.sim import generate_trace
from .arrivals import ScheduledEvent


def _view_invalid(view) -> bool:
    """The ChaosReport structural-validity check, minus the L cross-check
    (open-loop traces are drift-only by default; a coalesced or near-match
    serve still must be a well-formed placement). Stub schedulers (the
    process-worker test factory) serve plain dicts — nothing to check."""
    r = getattr(view, "result", None)
    if r is None:
        return False
    return r.k < 1 or len(r.w) != len(r.n) or any(w < 0 for w in r.w)


async def execute_openloop(
    gateway: Gateway,
    items: Sequence[ScheduledEvent],
    time_scale: float = 1.0,
    on_event=None,
    timeline=None,
) -> dict:
    """Fire ``items`` at their (scaled) scheduled times; gather results.

    ``time_scale`` compresses (<1) or dilates (>1) the schedule: the
    committed captures carry a leisurely real-time horizon, and the
    overload smokes replay them at a tiny scale to drive the same event
    sequence past saturation deterministically. Returns the report dict
    (see keys below); per-event outcomes stream through ``on_event(item,
    outcome)`` with outcome one of 'served'/'shed'/'failed'.

    ``timeline`` (an ``obs.timeline.Timeline``) is the per-window latency
    feed: each served event's scheduled-time latency lands as a point on
    ``openloop.latency_ms`` (and each shed as a tick on the cumulative
    ``openloop.sheds``) at the moment it happened — so a latency-tier SLO
    evaluated DURING the flood sees the client clock's window, not just
    the end-of-run percentiles this function returns.
    """
    if time_scale <= 0:
        raise ValueError("time_scale must be > 0")
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    lat_ms: List[float] = []
    dispatch_late_ms: List[float] = []
    sheds: List[dict] = []
    counts = {"offered": 0, "served": 0, "shed": 0, "failed": 0, "invalid": 0}
    max_depth_seen = 0
    tasks: List[asyncio.Task] = []

    async def _fire(item: ScheduledEvent, target: float) -> None:
        try:
            view = await gateway.handle_event_async(item.fleet_id, item.event)
        except QueueFull as e:  # dlint: disable=DLP017 the shed was already counted (events_shed) and flight-recorded INSIDE the gateway before this raise; here it only lands in the report
            counts["shed"] += 1
            sheds.append(
                {
                    "fleet": e.fleet_id,
                    "depth": e.depth,
                    "retry_after_s": e.retry_after_s,
                }
            )
            if timeline is not None:
                timeline.record(
                    "openloop.sheds", loop.time(), counts["shed"]
                )
            if on_event is not None:
                on_event(item, "shed")
            return
        done_ms = (loop.time() - target) * 1e3
        if getattr(view, "events_behind", 0) > 0:
            # The tick produced no fresh placement (solve failed); the
            # served answer is the previous one — an error under open
            # loop just like under replay.
            counts["failed"] += 1
            if on_event is not None:
                on_event(item, "failed")
            return
        if _view_invalid(view):
            counts["invalid"] += 1
        counts["served"] += 1
        lat_ms.append(done_ms)
        if timeline is not None:
            # loop.time() IS time.monotonic() on the default event loop,
            # so these points share the timeline sampler's clock.
            timeline.record("openloop.latency_ms", loop.time(), done_ms)
        if on_event is not None:
            on_event(item, "served")

    for item in sorted(items, key=lambda it: it.at_s):
        target = t0 + item.at_s * time_scale
        delay = target - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        # Negative delay = the dispatcher itself is behind; fire NOW and
        # record the lateness — never skip, never throttle.
        counts["offered"] += 1
        dispatch_late_ms.append(max(0.0, (loop.time() - target) * 1e3))
        for w in gateway.live_workers():
            max_depth_seen = max(max_depth_seen, w.depth())
        tasks.append(asyncio.ensure_future(_fire(item, target)))
    if tasks:
        await asyncio.gather(*tasks)
    wall_s = loop.time() - t0
    srt = sorted(lat_ms)
    horizon_s = (
        max(it.at_s for it in items) * time_scale if items else 0.0
    )
    return {
        **counts,
        "wall_s": round(wall_s, 3),
        "offered_eps": (
            round(counts["offered"] / horizon_s, 2) if horizon_s > 0 else 0.0
        ),
        # Goodput: events actually served per second of wall clock — the
        # plateau-vs-cliff gauge. Sheds and failures are not goodput.
        "goodput_eps": (
            round(counts["served"] / wall_s, 2) if wall_s > 0 else 0.0
        ),
        "p50_ms": round(_quantile(srt, 0.50), 3),
        "p99_ms": round(_quantile(srt, 0.99), 3),
        "p999_ms": round(_quantile(srt, 0.999), 3),
        "max_ms": round(srt[-1], 3) if srt else 0.0,
        "dispatch_p99_late_ms": round(
            _quantile(sorted(dispatch_late_ms), 0.99), 3
        ),
        "max_queue_depth_seen": max_depth_seen,
        "shed_samples": sheds[:5],
    }


def shed_violations(gateway: Gateway, flight) -> List[str]:
    """Record-by-record shed reconciliation (empty = contract held).

    Checks, per fleet: the flight recorder's shed records carry strictly
    increasing ``shed_index`` values whose last equals the gateway's
    per-fleet shed tally, each record names a positive Retry-After, and
    the per-fleet tallies sum to the ``events_shed`` counter. The same
    shape as the chaos soak's quarantine accounting: counters must be
    explained by records.

    Ring-overflow semantics: shed records share the fleet's bounded ring
    with ordinary tick records, and eviction is strictly oldest-first —
    so as long as ANY shed record survives, the youngest (index ==
    tally) survives with it, and the last-index check is sound. A fleet
    whose shed records were ALL pushed out by newer tick records is only
    a violation when eviction cannot explain the absence (the ring never
    filled); otherwise the counter stands un-audited rather than
    falsely condemned — size the recorder's capacity to the audit window
    when the reconciliation matters (the harness and bench do).
    """
    out: List[str] = []
    tallies = gateway.shed_counts()
    counter = gateway.metrics.snapshot()["counters"].get("events_shed", 0)
    if counter != sum(tallies.values()):
        out.append(
            f"shed accounting: events_shed={counter} but per-fleet "
            f"tallies sum to {sum(tallies.values())}"
        )
    if flight is None:
        if counter:
            out.append(
                f"shed accounting: {counter} sheds with no flight "
                "recorder attached (sheds must be flight-recorded)"
            )
        return out
    for fleet_id, tally in sorted(tallies.items()):
        ring = flight.snapshot(fleet_id)
        records = [r for r in ring if r.get("shed")]
        if not records:
            if len(ring) < flight.capacity:
                # Nothing was ever evicted from this ring, so the
                # missing records cannot be an overflow artifact.
                out.append(
                    f"shed accounting: fleet {fleet_id} counted {tally} "
                    "shed(s) but has no shed flight records (and the "
                    "ring never overflowed)"
                )
            continue
        indices = [r.get("shed_index") for r in records]
        if any(
            not isinstance(i, int) or i < 1 for i in indices
        ) or indices != sorted(indices) or len(set(indices)) != len(indices):
            out.append(
                f"shed accounting: fleet {fleet_id} has non-monotone "
                f"shed indices {indices}"
            )
        elif indices[-1] != tally:
            out.append(
                f"shed accounting: fleet {fleet_id} newest shed record "
                f"has index {indices[-1]} but the tally is {tally}"
            )
        for r in records:
            ra = r.get("retry_after_s")
            if not isinstance(ra, (int, float)) or ra <= 0:
                out.append(
                    f"shed accounting: fleet {fleet_id} shed record "
                    f"#{r.get('shed_index')} carries no positive "
                    f"Retry-After ({ra!r})"
                )
    for fleet_id in flight.keys():
        shed_recs = [r for r in flight.snapshot(fleet_id) if r.get("shed")]
        if shed_recs and fleet_id not in tallies:
            out.append(
                f"shed accounting: fleet {fleet_id} has shed flight "
                "records but a zero tally"
            )
    return out


def control_violations(gateway: Gateway, loop) -> List[str]:
    """Closed-loop accounting reconciliation (empty = contract held).

    The autoscaler's version of ``shed_violations``: every action the
    control loop took must be explained by the counters AND — when a
    flight recorder is attached — by a flight record on the ``control``
    ring, in order, kind-for-kind. A counted-but-unrecorded decision (or
    an actuation the fleet state does not reflect) is a violation.
    """
    out: List[str] = []
    counters = gateway.metrics.snapshot()["counters"]
    actions = list(loop.actions)
    n = counters.get("control_actions", 0)
    if n != len(actions):
        out.append(
            f"control accounting: control_actions={n} but the loop "
            f"took {len(actions)} action(s)"
        )
    per_kind: Dict[str, int] = {}
    for a in actions:
        per_kind[a.kind] = per_kind.get(a.kind, 0) + 1
    for kind, ctr in (
        ("scale_out", "control_scale_out"),
        ("scale_in", "control_scale_in"),
        ("degrade_on", "control_degrade_on"),
        ("degrade_off", "control_degrade_off"),
        ("spec_k", "control_spec_k"),
    ):
        if counters.get(ctr, 0) != per_kind.get(kind, 0):
            out.append(
                f"control accounting: {ctr}={counters.get(ctr, 0)} but "
                f"{per_kind.get(kind, 0)} {kind} action(s) were taken"
            )
    # Actuation must be reflected in the fleet counters: every scale_out
    # spawned a worker, every scale_in retired one, and no migration may
    # have failed (a failed flip leaves routing on the source — correct,
    # but the autoscale smoke demands the clean path).
    if counters.get("workers_spawned", 0) != per_kind.get("scale_out", 0):
        out.append(
            f"control accounting: workers_spawned="
            f"{counters.get('workers_spawned', 0)} but "
            f"{per_kind.get('scale_out', 0)} scale_out action(s)"
        )
    if counters.get("workers_retired", 0) != per_kind.get("scale_in", 0):
        out.append(
            f"control accounting: workers_retired="
            f"{counters.get('workers_retired', 0)} but "
            f"{per_kind.get('scale_in', 0)} scale_in action(s)"
        )
    if counters.get("migration_failed", 0):
        out.append(
            f"control accounting: {counters.get('migration_failed', 0)} "
            "migration(s) failed"
        )
    if loop.errors:
        out.append(
            f"control accounting: {loop.errors} control tick(s) raised"
        )
    flight = gateway.flight
    if flight is None:
        if actions:
            out.append(
                f"control accounting: {len(actions)} action(s) with no "
                "flight recorder attached (decisions must be recorded)"
            )
        return out
    ring = (
        list(flight.snapshot("control")) if "control" in flight.keys() else []
    )
    recorded = [(r.get("action") or {}).get("kind") for r in ring]
    expect = [a.kind for a in actions]
    # Same oldest-first eviction semantics as shed records: with no
    # overflow the trail must match exactly; with overflow, the surviving
    # suffix must.
    if len(ring) < flight.capacity:
        if recorded != expect:
            out.append(
                f"control accounting: flight trail {recorded} does not "
                f"match actions {expect}"
            )
    elif recorded != expect[len(expect) - len(recorded):]:
        out.append(
            "control accounting: flight trail (overflowed) does not "
            "match the action suffix"
        )
    for r in ring:
        if "signals" not in r:
            out.append(
                "control accounting: flight record for "
                f"{(r.get('action') or {}).get('kind')} at t={r.get('t')} "
                "carries no signals snapshot"
            )
    return out


async def _warmup(
    gateway: Gateway, specs: Dict[str, dict], per_fleet: int, seed: int
) -> None:
    """Closed-loop warmup: cold solve + warm-layout compile per fleet,
    concurrent across fleets (the loadgen's barrier-phase convention) —
    the open-loop phase must measure serving, not jit."""
    from ..gateway.traces import make_fleet_from_spec

    async def _drive(fleet_id: str, events: list) -> None:
        for ev in events:
            await gateway.handle_event_async(fleet_id, ev)

    jobs = []
    for i, (fleet_id, spec) in enumerate(specs.items()):
        devices = make_fleet_from_spec(fleet_id, spec)
        events = generate_trace(
            "drift", per_fleet, seed=seed * 104729 + i, base_fleet=devices
        )
        jobs.append(_drive(fleet_id, events))
    await asyncio.gather(*jobs)


def run_openloop(
    model,
    specs: Dict[str, dict],
    items: Sequence[ScheduledEvent],
    n_workers: int,
    *,
    time_scale: float = 1.0,
    warmup_per_fleet: int = 2,
    warmup_seed: int = 0,
    k_candidates: Optional[Sequence[int]] = None,
    mip_gap: float = 1e-3,
    kv_bits: str = "4bit",
    scheduler_kwargs: Optional[dict] = None,
    max_queue_depth: Optional[int] = None,
    coalesce: bool = False,
    combine: bool = False,
    combine_policy=None,
    degrade_depth: Optional[int] = None,
    flight=None,
    tracer=None,
    slo_config=None,
    timeline=None,
    timeline_period_s: float = 0.05,
    settle_s: float = 0.0,
    worker_backend: str = "thread",
    scheduler_factory=None,
    autoscale=None,
    control_period_s: float = 0.25,
    capacity_probe_events: int = 0,
) -> dict:
    """One full open-loop arm: build, warm, fire, report, tear down.

    Admission is configured only AFTER the warmup phase (a cold compile
    behind a bounded queue would shed the warmup itself), then the whole
    schedule executes open-loop. The report merges the executor's numbers
    with the gateway's admission counters and — when a flight recorder is
    attached — the shed reconciliation verdict.

    SLO arm (``slo_config``, an ``obs.slo.SLOConfig``): a timeline
    sampler runs for the arm's whole life (evaluating the SLO engine on
    every tick), the executor feeds per-event scheduled-time latency into
    the timeline, and the report grows an ``slo`` block (status + the
    alert open/close sequence) plus ``timeline_samples``. ``settle_s``
    keeps sampling AFTER the schedule drains — the recovery window a
    burn-rate alert needs to clear, which is exactly what the smoke
    asserts. ``timeline`` alone (no config) just records, no alerting.

    Autoscale arm (``autoscale``, a ``control.ControlPolicy``): the
    gateway is built dynamic (spawn/retire/migrate enabled, backed by
    ``worker_backend`` — "thread" or "process"), a ``ControlLoop`` runs
    for the flood's whole life, and — unless the probe is skipped — a
    post-warmup closed-loop probe of ``capacity_probe_events`` per fleet
    populates the ``/signals`` headroom denominator, refreshed
    deterministically per worker-count change (no live re-probe inside
    the loop). The report grows a ``control`` block with the policy,
    every action taken, and the flight-record reconciliation verdict
    (``control_violations``).
    """
    kwargs = {
        "mip_gap": mip_gap,
        "kv_bits": kv_bits,
        "backend": "jax",
        "k_candidates": list(k_candidates) if k_candidates else None,
    }
    kwargs.update(scheduler_kwargs or {})
    gateway = Gateway(
        n_workers=n_workers, scheduler_kwargs=kwargs,
        scheduler_factory=scheduler_factory,
        flight=flight, tracer=tracer,
        worker_backend=worker_backend,
        dynamic=autoscale is not None,
    )
    engine = None
    sampler = None
    control_loop = None
    capacity_probe = None
    if (slo_config is not None or autoscale is not None) and timeline is None:
        from ..obs.timeline import Timeline

        timeline = Timeline()
    # Per-arm compile-ledger snapshot: when the process ledger is enabled
    # (bench compile section, serve --compile-ledger) every open-loop arm
    # reports its own compile delta — a flood arm that silently paid a
    # recompile storm would otherwise launder it into aggregate wall time.
    from ..obs import compile_ledger as _cl
    from ..obs import memory as _obs_memory

    _led = _cl.current()
    _led_tok = _led.seq() if _led is not None else 0
    # Same per-arm convention for the memory ledger: enabled process-wide
    # (serve --memory-ledger / the bench memory section), every open-loop
    # arm reports its own leak/watermark view.
    _mled = _obs_memory.current()
    try:
        from ..gateway.traces import make_fleet_from_spec

        for fleet_id, spec in specs.items():
            gateway.register_fleet(
                fleet_id, make_fleet_from_spec(fleet_id, spec), model
            )
        if slo_config is not None:
            from ..obs.slo import SLOEngine

            engine = SLOEngine(
                slo_config, timeline, metrics=gateway.metrics,
                tracer=tracer, flight=flight,
            )
        if timeline is not None:
            # engine may be None (timeline-only / autoscale-only arms):
            # the read surface still needs gateway.timeline wired so
            # /signals — and the control loop reading it — can build.
            gateway.attach_slo(engine, timeline)
            from ..obs.timeline import TimelineSampler

            sampler = gateway.attach_sampler(
                TimelineSampler(
                    timeline,
                    gateway.timeline_sample,
                    period_s=timeline_period_s,
                    metrics=gateway.metrics,
                    on_sample=(
                        None if engine is None
                        else (lambda _tl, now: engine.evaluate(now))
                    ),
                )
            )
            sampler.start()
        if warmup_per_fleet > 0:
            asyncio.run(
                _warmup(gateway, specs, warmup_per_fleet, warmup_seed)
            )
        if autoscale is not None and capacity_probe_events > 0 and (
            gateway.capacity_eps is None
        ):
            # Satellite: the /signals headroom denominator comes from a
            # closed-loop probe of THIS gateway (same fleets, same
            # workers), run while admission is still open — the probe is
            # warm-phase work, not flood traffic to be shed.
            # note_capacity keeps the per-worker quotient: capacity_eps
            # refreshes deterministically on every spawn/retire.
            capacity_probe = measure_closed_loop(
                gateway, specs, capacity_probe_events, warmup_seed
            )
            gateway.note_capacity(capacity_probe["events_per_sec"])
        gateway.configure_admission(
            max_queue_depth=max_queue_depth,
            coalesce=coalesce,
            combine=combine,
            combine_policy=combine_policy,
            degrade_depth=degrade_depth,
        )
        combine_warm = None
        if combine:
            # Combined traffic has its own compile surface (one vmapped
            # executable per committed bucket x lane shape): trace all of
            # it BEFORE the warm boundary, or the flood pays it live.
            combine_warm = gateway.warm_combine()
        if autoscale is not None:
            from ..control import Controller, ControlLoop

            control_loop = ControlLoop(
                gateway, Controller(autoscale), period_s=control_period_s
            )
            gateway.attach_controller(control_loop)
            control_loop.start()
        if _mled is not None:
            # The admission flip IS openloop's warm boundary: everything
            # before it (fleet registration, per-fleet warmup solves,
            # combined-executable tracing) is allowed to allocate; live
            # bytes must stay flat from here. Without this baseline the
            # arm's mem block reports ``leak: null`` forever.
            _mled.mark_warm()
        # Warm-phase compile token: the compile block reports the arm's
        # full delta AND the post-warm-boundary slice — the latter is the
        # zero-recompile gate's number (warmup compiles are the contract;
        # measured-phase compiles are the violation).
        _led_warm_tok = _led.seq() if _led is not None else 0
        report = asyncio.run(
            execute_openloop(
                gateway, items, time_scale=time_scale, timeline=timeline
            )
        )
        if settle_s > 0 and sampler is not None:
            # Recovery window: the schedule drained, the sampler keeps
            # watching — this is where a fired burn-rate alert clears
            # (windowed deltas go to zero once the burst slides out).
            deadline = time.monotonic() + settle_s
            while time.monotonic() < deadline:
                time.sleep(min(timeline_period_s, 0.05))
        snap = gateway.metrics_snapshot()
        totals = snap["shard_totals"]
        report.update(
            {
                "fleets": len(specs),
                "workers": n_workers,
                "time_scale": time_scale,
                "events_shed": snap["counters"].get("events_shed", 0),
                "events_coalesced": totals.get("events_coalesced", 0),
                "spec_near_hits": totals.get("spec_near_hit", 0),
                "shed_counts": gateway.shed_counts(),
                "admission": {
                    "max_queue_depth": max_queue_depth,
                    "coalesce": coalesce,
                    "combine": combine,
                    "degrade_depth": degrade_depth,
                },
            }
        )
        if combine:
            report["combine"] = dict(
                gateway._combiner.snapshot()
                if gateway._combiner is not None else {}
            )
            report["combine"]["warmup"] = combine_warm
            for ctr in (
                "combine_prepared", "combine_local",
                "combine_stale", "combine_fallback",
            ):
                report["combine"][ctr] = totals.get(ctr, 0)
        if flight is not None:
            report["shed_violations"] = shed_violations(gateway, flight)
        if control_loop is not None:
            control_loop.stop()
            report["control"] = {
                "policy": autoscale.model_dump(),
                "actions": [a.model_dump() for a in control_loop.actions],
                "workers_final": len(gateway.live_workers()),
                "worker_backend": worker_backend,
                "capacity_probe": capacity_probe,
                "capacity_eps": gateway.capacity_eps,
                "counters": {
                    k: int(v)
                    for k, v in sorted(snap["counters"].items())
                    if k.startswith("control_")
                    or k in (
                        "workers_spawned", "workers_retired",
                        "shards_migrated", "migration_parked",
                        "migration_failed",
                    )
                },
                "violations": control_violations(gateway, control_loop),
            }
        if _led is not None:
            arm_events = _led.events_since(_led_tok)
            warm_events = _led.events_since(_led_warm_tok)
            report["compile"] = {
                "events": len(arm_events),
                "cache_hits": sum(
                    1 for e in arm_events if e.get("cache") == "hit"
                ),
                "storm_flagged": sum(
                    1 for e in arm_events if e.get("storm")
                ),
                "entries": sorted({e["entry"] for e in arm_events}),
                "warm_phase_events": len(warm_events),
                "warm_phase_entries": sorted(
                    {e["entry"] for e in warm_events}
                ),
                # The combine zero-recompile gate's number: warm-phase
                # compiles of the BUCKET executable specifically. A
                # per-shard entry here (e.g. an uncertified lane's local
                # fallback re-solving with escalated search parameters)
                # is attributed under warm_phase_entries but is not a
                # committed-bucket-policy violation.
                "warm_phase_combine_events": sum(
                    1
                    for e in warm_events
                    if "_solve_batched" in str(e.get("entry", ""))
                ),
            }
        if _mled is not None:
            # Per-arm memory view (one forced end-of-arm sample — the
            # schedule has drained, so this is the flood's true residue):
            # a flood whose queued-up ticks silently pinned live arrays
            # would otherwise launder the growth into process-level RSS
            # noise.
            _mled.sample(force=True)
            report["mem"] = {
                "leak": _mled.leak_report(),
                "watermarks": _mled.summary()["watermarks"],
                "headroom_bytes": _mled.headroom_bytes(),
            }
        if engine is not None:
            report["slo"] = {
                "alerts_opened": snap["counters"].get("slo_alert_opened", 0),
                "alerts_closed": snap["counters"].get("slo_alert_closed", 0),
                "timeline_samples": snap["counters"].get(
                    "timeline_samples", 0
                ),
                "events": list(engine.events),
                "firing": engine.firing(),
                # The /signals payload as the live gateway would serve it
                # — the bench validates it against SignalsPayload so the
                # federation contract is schema-checked on every capture.
                "signals": gateway.signals(),
            }
        return report
    finally:
        # close() stops the attached sampler before the workers.
        gateway.close()


def measure_closed_loop(
    gateway: Gateway, specs: Dict[str, dict], events_per_fleet: int, seed: int
) -> dict:
    """Closed-loop capacity probe on an ALREADY-WARM gateway: the bench's
    sustainable-rate search needs a capacity estimate from the same
    fleets/workers the open-loop arms will stress, without paying a
    second set of cold solves. Thin wrapper over the loadgen's concurrent
    replayer with no warmup split."""
    from ..gateway.loadgen import replay_concurrent
    from ..gateway.traces import make_fleet_from_spec

    items = []
    per_fleet: Dict[str, list] = {}
    for i, (fleet_id, spec) in enumerate(specs.items()):
        devices = make_fleet_from_spec(fleet_id, spec)
        per_fleet[fleet_id] = generate_trace(
            "drift", events_per_fleet, seed=seed * 15485863 + i,
            base_fleet=devices,
        )
    for j in range(events_per_fleet):
        for fleet_id in specs:
            items.append((fleet_id, per_fleet[fleet_id][j]))
    return asyncio.run(
        replay_concurrent(gateway, items, {f: 0 for f in specs})
    )


def lateness_probe(items: Sequence[ScheduledEvent]) -> float:
    """Total schedule horizon in seconds (the last event's timestamp) —
    a convenience for sizing time_scale against a wall-clock budget."""
    return max((it.at_s for it in items), default=0.0)

"""Analytic memory model for the HALDA LP engines — ONE source of truth.

Until PR 15 the per-(M, engine) peak-working-set formulas lived inline in
``bench.py``'s fleet_scale section, where they decide whether the IPM arm
is even attempted (the M=4096 arm is skipped on the proxy alone) — an
*analytic, never-validated* guess steering a measurement. This module is
the factored-out model, shared by:

- ``bench.py`` fleet_scale (the skip decision and the per-M proxy rows —
  behavior unchanged, pinned by a parity test in tests/test_memory.py);
- ``bench.py``'s ``memory`` section, which CALIBRATES the model: the
  proxy is compared against XLA's measured ``memory_analysis()`` temp
  bytes for the real solve executables at two M sizes, and ``--against``
  gates the ratio inside a band (a proxy that drifts out of band stops
  being allowed to skip arms silently);
- the ``solver memory`` report, which prints analytic-vs-measured side
  by side;
- ROADMAP item 3's per-shard sizing (sharding the PDHG operators needs a
  bytes-per-device-row model before any mesh decision).

The model (dense HALDA standard form, see bench.py's original comment):
``m_rows = 6M + 3`` constraint rows (w/n/y blocks + cycle/memory/prefetch
+ couplers) and ``n_cols ~ 3M`` variables. The engines' unavoidable
per-iteration working sets differ structurally:

- **IPM**: ``beam`` batched dense (m, m) f32 normal matrices — the
  factorizing engine's quadratic wall (beam = the B&B LP batch width);
- **PDHG**: ONE shared (m, n) f32 operator — matrix-free in iterates,
  so the operator itself is the footprint (and the thing ROADMAP item 3
  shards away).

Stdlib-only at module level on purpose — but note the PACKAGE is not:
``import distilp_tpu.ops.memmodel`` still executes ``ops/__init__``,
which eagerly imports the jax kernels. Backend-free layers (obs/, the
CLI's offline paths) therefore import this lazily at call time — by
then a backend is in play anyway — and the formulas themselves never
touch one.
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "DENSE_BEAM",
    "F32_BYTES",
    "F64_BYTES",
    "ENGINES",
    "standard_form_dims",
    "ipm_peak_bytes",
    "pdhg_peak_bytes",
    "pdhg_shard_peak_bytes",
    "choose_mesh_shards",
    "dtype_bytes_of",
    "peak_bytes",
    "peak_gb",
    "ipm_memory_infeasible",
]

# The dense default_search_params beam — the IPM's LP batch size (see
# backend_jax's dense search-knob defaults; bench.py pinned the same 6).
DENSE_BEAM = 6
F32_BYTES = 4
F64_BYTES = 8


def dtype_bytes_of(pdhg_dtype: Optional[str]) -> int:
    """Bytes per element of a ``pdhg_dtype`` knob value (None = the f32
    search dtype the solver runs by default)."""
    if pdhg_dtype in (None, "f32"):
        return F32_BYTES
    if pdhg_dtype == "f64":
        return F64_BYTES
    raise ValueError(f"unknown pdhg_dtype {pdhg_dtype!r} (expected f32|f64)")

ENGINES = ("ipm", "pdhg")


def standard_form_dims(M: int) -> tuple:
    """(m_rows, n_cols) of the dense HALDA standard form at fleet size M:
    m = 6M+3 constraint rows, n ~ 3M variables."""
    if M < 1:
        raise ValueError(f"fleet size must be >= 1 (got {M})")
    return 6 * M + 3, 3 * M


def ipm_peak_bytes(
    M: int, beam: int = DENSE_BEAM, dtype_bytes: int = F32_BYTES
) -> int:
    """The IPM's peak working set: ``beam`` batched (m, m) normal
    matrices — the quadratic term that makes M=4096 memory-infeasible."""
    m_rows, _ = standard_form_dims(M)
    return beam * m_rows * m_rows * dtype_bytes


def pdhg_peak_bytes(M: int, dtype_bytes: int = F32_BYTES) -> int:
    """PDHG's peak working set: the ONE shared (m, n) operator (iterates
    are vectors; A is only touched through opA/opAT — the fleet-scale
    invariant PR 6 documented)."""
    m_rows, n_cols = standard_form_dims(M)
    return m_rows * n_cols * dtype_bytes


def pdhg_shard_peak_bytes(
    M: int, shards: int = 1, dtype_bytes: int = F32_BYTES
) -> int:
    """Per-DEVICE peak working set of the row-sharded PDHG engine
    (ops/meshlp.py): each shard holds an ``(ceil(m/S), n)`` block of the
    one shared operator — the row padding to a multiple of S is modeled
    exactly, since the pad rows are real zero rows in the block. Iterates
    are vectors (noise next to the block) and the f64 certificate is two
    matvec passes over the same block at 2x element width, both absorbed
    by the calibration band rather than modeled as separate terms — the
    same single-dominant-term shape as ``pdhg_peak_bytes``, which this
    reduces to at shards=1."""
    if shards < 1:
        raise ValueError(f"mesh_shards must be >= 1 (got {shards})")
    m_rows, n_cols = standard_form_dims(M)
    m_block = -(-m_rows // shards)  # ceil: the padded per-shard rows
    return m_block * n_cols * dtype_bytes


def choose_mesh_shards(
    M: int,
    per_device_budget_bytes: int,
    max_shards: int,
    dtype_bytes: int = F32_BYTES,
) -> Optional[int]:
    """Smallest shard count whose per-device operator block fits the
    budget — model-predicted, ledger-verified (the PR 15 calibration band
    is what licenses trusting this analytic answer). Returns None when
    even ``max_shards`` devices cannot fit a block: the caller should say
    so rather than OOM measuring it. shards=1 (no mesh) is preferred when
    it fits — the unsharded program has no collectives to pay for."""
    if max_shards < 1:
        raise ValueError(f"max_shards must be >= 1 (got {max_shards})")
    if per_device_budget_bytes < 1:
        raise ValueError("per_device_budget_bytes must be positive")
    for shards in range(1, max_shards + 1):
        if pdhg_shard_peak_bytes(M, shards, dtype_bytes) <= per_device_budget_bytes:
            return shards
    return None


def peak_bytes(M: int, engine: str, beam: int = DENSE_BEAM) -> int:
    """Per-(M, engine) analytic peak working set in bytes."""
    if engine == "ipm":
        return ipm_peak_bytes(M, beam=beam)
    if engine == "pdhg":
        return pdhg_peak_bytes(M)
    raise ValueError(f"unknown LP engine {engine!r} (expected ipm|pdhg)")


def peak_gb(M: int, engine: str, beam: int = DENSE_BEAM) -> float:
    """``peak_bytes`` in (decimal) gigabytes — the unit the fleet_scale
    section reports and caps in."""
    return peak_bytes(M, engine, beam=beam) / 1e9


def ipm_memory_infeasible(
    M: int, cap_gb: float, beam: int = DENSE_BEAM
) -> Optional[str]:
    """The fleet_scale skip decision: a human-readable reason when the
    IPM's proxy exceeds ``cap_gb``, else None. Centralized so the bench,
    the memory report and future per-shard sizing all phrase (and make)
    the call identically."""
    gb = peak_gb(M, "ipm", beam=beam)
    if gb > cap_gb:
        return (
            f"memory-infeasible (~{gb:.1f} GB batched "
            f"normal matrices > {cap_gb:g} GB cap)"
        )
    return None

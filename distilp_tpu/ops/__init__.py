"""Numerical kernels (JAX/XLA; Pallas where XLA fusion is not enough)."""

from .ipm import IPMResult, IPMWarmState, LPBatch, ipm_solve_batch
from .pdhg import PDHGWarmState, pdhg_solve_batch

__all__ = [
    "LPBatch",
    "IPMResult",
    "IPMWarmState",
    "PDHGWarmState",
    "ipm_solve_batch",
    "pdhg_solve_batch",
]

"""Numerical kernels (JAX/XLA; Pallas where XLA fusion is not enough)."""

from .ipm import IPMResult, LPBatch, ipm_solve_batch

__all__ = ["LPBatch", "IPMResult", "ipm_solve_batch"]

"""Numerical kernels (JAX/XLA; Pallas where XLA fusion is not enough).

``memmodel`` (the stdlib-only analytic memory model) is deliberately not
re-exported here: consumers import ``distilp_tpu.ops.memmodel`` lazily
(function scope) from backend-free layers — reaching it still executes
this package's jax imports, which is why obs/ and the CLI defer it to
call time, the same DLP013 idiom as every other backend-touching import.
"""

from .ipm import IPMResult, IPMWarmState, LPBatch, ipm_solve_batch
from .meshlp import pdhg_solve_batch_mp, pdhg_solve_batch_sharded
from .pdhg import PDHGWarmState, pdhg_solve_batch

__all__ = [
    "LPBatch",
    "IPMResult",
    "IPMWarmState",
    "PDHGWarmState",
    "ipm_solve_batch",
    "pdhg_solve_batch",
    "pdhg_solve_batch_sharded",
    "pdhg_solve_batch_mp",
]

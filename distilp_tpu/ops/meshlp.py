"""Row-partitioned PDHG across a device mesh — ROADMAP item 3's engine.

The matrix-free kernel in :mod:`distilp_tpu.ops.pdhg` only ever touches A
through ``opA``/``opAT`` (the PR 6 fleet-scale invariant), which is exactly
the property that lets the *operators* shard: partition the DEVICE ROWS of
the standard-form instance across a 1-D mesh axis, keep the primal iterate
(a column vector) replicated, and the whole restarted-Halpern iteration
runs shard-local except for one ``psum`` per ``opAT`` (the column-sum
A'y) plus the scalar reductions of the convergence/restart gauges — the
MPAX batched/distributed LP-in-JAX design (arXiv 2412.09734) applied to
the HALDA standard form, with HPR-LP's accelerator-resident first-order
loop + cheap high-precision certificate split (arXiv 2408.12179).

What each shard holds, for an ``(m, n)`` instance on ``S`` shards:

- an ``(m/S, n)`` block of A (the whole memory story: the shared operator
  is THE footprint at fleet scale — see ``ops/memmodel.py``'s per-shard
  model, which *chooses* S so a block fits the per-device budget);
- the matching slices of ``b``, the row equilibration/step vectors, and
  the dual iterate ``y``;
- a full (replicated) copy of the column data ``c``/``l``/``u``, the
  primal iterate, and every scalar of the restart control — so all shards
  take the same branch every step by construction.

``m`` is padded up to a multiple of ``S`` with all-zero rows, which the
kernel already treats as decoupled (their row scale never amplifies, their
step size is 0, their dual stays 0, and they contribute nothing to any
product or to the f64 certificate) — padding is exact, not approximate.

Everything is resolved through :mod:`distilp_tpu.utils.shardcompat`, so
this module runs on the jax 0.4.37 this image ships (where ``shard_map``
still lives in ``jax.experimental``) and on current jax unchanged. On a
CPU-only box a forced host mesh (``--xla_force_host_platform_device_count``)
exercises the full collective program — that is how the tests and
``make smoke-shard`` run it.

Warm states stay in the ORIGINAL full-array coordinates on both edges:
the sharded kernel slices ``y`` into blocks on entry and all-gathers the
final iterates on exit, so a :class:`~distilp_tpu.ops.pdhg.PDHGWarmState`
produced here is field-for-field the unsharded kernel's (and the IPM's) —
``dump_warm_state``/``load_warm_state`` round-trip it bit-exactly with no
shard-count in the blob, which is what lets a warm state dumped at one
mesh size restore at any other.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..obs.compile_ledger import instrument
from ..utils import shardcompat
from .ipm import IPMResult, LPBatch
from .pdhg import (
    DEFAULT_RESTART_TOL,
    PDHG_DEFAULT_CHUNK,
    PDHGWarmState,
    _default_tol_pdhg,
    _pdhg_single,
    resolve_pdhg_dtype,
)

__all__ = [
    "MESH_AXIS",
    "pad_rows_to",
    "pdhg_solve_batch_sharded",
    "pdhg_solve_batch_mp",
]

# The one mesh-axis name of the row partition. dlint DLP021 scopes its
# mesh-body checks to shard_map callees; keeping the axis a module constant
# keeps every collective call site greppable.
MESH_AXIS = "rows"


def pad_rows_to(m: int, shards: int) -> int:
    """Rows after padding ``m`` up to a multiple of ``shards``."""
    if shards < 1:
        raise ValueError(f"mesh_shards must be >= 1 (got {shards})")
    return int(-(-m // shards) * shards)


def _pad_axis(x, target: int, axis: int):
    """Zero-pad ``x`` along ``axis`` to length ``target`` (exact rows: the
    kernel treats all-zero rows as decoupled, see module docstring)."""
    pad = target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def sharded_pdhg(
    batch: LPBatch,
    mesh_shards: int,
    iters: int,
    tol,
    restart_tol,
    warm: Optional[PDHGWarmState] = None,
    skip: Optional[jax.Array] = None,
    chunk: int = PDHG_DEFAULT_CHUNK,
    trace: bool = False,
) -> IPMResult:
    """The traceable core: row-shard one LPBatch across ``mesh_shards``
    devices and run the mesh-aware PDHG kernel. Callable standalone or
    inside an enclosing jit (the fused B&B program calls it mid-trace;
    ``mesh_shards`` is static there, so the mesh is built at trace time).

    Returns the unsharded kernel's exact :class:`IPMResult` contract with
    every array fully replicated — the caller cannot tell, shape-wise,
    which engine ran.
    """
    P = shardcompat.partition_spec
    mesh = shardcompat.shard_mesh(mesh_shards, axis=MESH_AXIS)

    B = batch.b.shape[0]
    m = batch.b.shape[1]
    m_pad = pad_rows_to(m, mesh_shards)
    shared_a = batch.A.ndim == 2
    row_axis = 0 if shared_a else 1

    A_p = _pad_axis(batch.A, m_pad, row_axis)
    b_p = _pad_axis(batch.b, m_pad, 1)

    # Materialize the optional operands: a disabled warm state (ok=False is
    # pinned to behave exactly like no warm state) and an all-live skip
    # keep the shard_map signature static across call sites.
    if warm is None:
        warm = PDHGWarmState(
            v=jnp.zeros_like(batch.c),
            y=jnp.zeros((B, m), batch.b.dtype),
            z=jnp.zeros_like(batch.c),
            f=jnp.zeros_like(batch.c),
            ok=jnp.zeros((B,), bool),
        )
    wy_p = _pad_axis(jnp.asarray(warm.y, batch.b.dtype), m_pad, 1)
    if skip is None:
        skip = jnp.zeros((B,), bool)

    a_spec = P(MESH_AXIS, None) if shared_a else P(None, MESH_AXIS, None)
    rep2 = P(None, None)
    rep1 = P(None)

    def body(A_blk, b_blk, c, l, u, wv, wy_blk, wz, wf, wok, sk):
        def single(A1, b1, c1, l1, u1, wm, s1):
            return _pdhg_single(
                A1, b1, c1, l1, u1, iters, tol, restart_tol,
                warm=wm, skip=s1, chunk=chunk, trace=trace,
                axis_name=MESH_AXIS,
            )

        res = jax.vmap(
            single,
            in_axes=(None if shared_a else 0, 0, 0, 0, 0, 0, 0),
        )(
            A_blk, b_blk, c, l, u,
            PDHGWarmState(v=wv, y=wy_blk, z=wz, f=wf, ok=wok), sk,
        )
        # The dual block is the only row-sharded output; gather it so the
        # result contract is fully replicated (tiled: concatenate the
        # blocks back along the row axis in mesh order).
        y_full = jax.lax.all_gather(res.y_dual, MESH_AXIS, axis=1, tiled=True)
        return res._replace(y_dual=y_full)

    out_specs = IPMResult(
        v=rep2, bound=rep1, obj=rep1, rp_norm=rep1, rd_norm=rep1, mu=rep1,
        converged=rep1, reduced=rep2, y_dual=rep2, z_dual=rep2, f_dual=rep2,
        iters_run=rep1, trace_buf=P(None, None, None) if trace else None,
    )
    with jax.default_matmul_precision("highest"):
        res = shardcompat.shard_map(
            body,
            mesh,
            in_specs=(
                a_spec, P(None, MESH_AXIS), rep2, rep2, rep2,
                rep2, P(None, MESH_AXIS), rep2, rep2, rep1, rep1,
            ),
            out_specs=out_specs,
            # The replication checker cannot prove psum/all_gather-fed
            # replicated outputs on every jax this shim spans; the specs
            # above ARE the contract and the parity tests pin it.
            check_vma=False,
        )(
            A_p, b_p, batch.c, batch.l, batch.u,
            warm.v, wy_p, warm.z, warm.f, warm.ok, skip,
        )
    return res._replace(y_dual=res.y_dual[:, :m])


def _pdhg_sharded_entry(
    batch: LPBatch,
    tol=None,
    restart_tol=None,
    warm=None,
    skip=None,
    mesh_shards: int = 1,
    iters: int = 1000,
    chunk: int = PDHG_DEFAULT_CHUNK,
    trace: bool = False,
    dtype: Optional[str] = None,
) -> IPMResult:
    dt = resolve_pdhg_dtype(dtype)
    if dt is not None and dt != batch.A.dtype:
        batch = LPBatch(*(jnp.asarray(x).astype(dt) for x in batch))
    tol_v = _default_tol_pdhg(batch.A.dtype) if tol is None else tol
    rt_v = DEFAULT_RESTART_TOL if restart_tol is None else restart_tol
    return sharded_pdhg(
        batch, mesh_shards, iters, tol_v, rt_v,
        warm=warm, skip=skip, chunk=chunk, trace=trace,
    )


# Registered compile-ledger entry point (obs.compile_ledger; dlint DLP020):
# the sharded sibling of ops.pdhg.pdhg_solve_batch. `mesh_shards` is static
# — each shard count is its own executable, attributed by the ledger, and a
# warm streaming/bench loop at a fixed shard count must show ZERO warm-phase
# compiles here (the same bucket-scoped gate contract as PR 16).
_SHARDED_STATICS = ("mesh_shards", "iters", "chunk", "trace", "dtype")
pdhg_solve_batch_sharded = instrument(
    "ops.meshlp.pdhg_solve_batch_sharded",
    jax.jit(_pdhg_sharded_entry, static_argnames=_SHARDED_STATICS),
    static_argnames=_SHARDED_STATICS,
)


def pdhg_solve_batch_mp(
    batch: LPBatch,
    mesh_shards: int = 1,
    iters: int = 1000,
    tol: Optional[float] = None,
    restart_tol: Optional[float] = None,
    warm: Optional[PDHGWarmState] = None,
    skip: Optional[jax.Array] = None,
    chunk: int = PDHG_DEFAULT_CHUNK,
    trace: bool = False,
    dtype: str = "f32",
    f64_fallback: bool = True,
    fallback_report: Optional[dict] = None,
) -> IPMResult:
    """Mixed-precision sharded solve with the soundness escalation.

    Runs the (optionally sharded) PDHG at ``dtype`` iterate precision —
    f32 is the fleet-scale default: half the operator bytes per shard,
    with the f64 Lagrangian bound as the certificate either way. A batch
    element whose f32 run comes back non-finite or stalled (not converged)
    is re-solved on the f64 path and spliced in per element — the same
    shape as the warm-garbage→cold fallback inside the kernel: precision
    is an optimization that can cost a re-solve, never soundness.

    ``fallback_report`` (pass a dict) receives ``n_fallback`` — bench and
    tests read it to prove the fast path stayed fast.
    """
    res = pdhg_solve_batch_sharded(
        batch, tol=tol, restart_tol=restart_tol, warm=warm, skip=skip,
        mesh_shards=mesh_shards, iters=iters, chunk=chunk, trace=trace,
        dtype=dtype,
    )
    n_bad = 0
    if f64_fallback and dtype != "f64":
        import numpy as np

        bad = ~np.asarray(res.converged) | ~np.isfinite(np.asarray(res.bound))
        n_bad = int(bad.sum())
        if n_bad:
            res64 = pdhg_solve_batch_sharded(
                batch, tol=tol, restart_tol=restart_tol, warm=warm,
                skip=skip, mesh_shards=mesh_shards, iters=iters, chunk=chunk,
                trace=trace, dtype="f64",
            )
            badj = jnp.asarray(bad)

            def splice(a32, a64):
                if a32 is None:
                    return None
                sel = badj.reshape((-1,) + (1,) * (a32.ndim - 1))
                return jnp.where(sel, a64.astype(a32.dtype), a32)

            res = jax.tree.map(
                splice, res, res64,
                is_leaf=lambda x: x is None or isinstance(x, jax.Array),
            )
    if fallback_report is not None:
        fallback_report["n_fallback"] = n_bad
    return res

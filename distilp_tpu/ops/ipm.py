"""Batched primal-dual interior-point kernel for box-constrained LPs.

This is the accelerator replacement for the per-k HiGHS branch-and-cut call in
the reference solver (/root/reference/src/distilp/solver/halda_p_solver.py:340):
the LP relaxations of every k-candidate and every branch-and-bound node are
solved as ONE batched Mehrotra predictor-corrector run under ``vmap``.

Problem form (everything boxed — the HALDA assembler derives finite valid-at-
optimum upper bounds for the nominally free variables):

    min c'v   s.t.  A v = b,   l <= v <= u

shifted internally to  x = v - l in [0, r],  r = u - l.

Design notes, TPU-first:
- **Mixed precision.** The iteration runs in the *input dtype* — float32 in
  production, because TPU float64 is software-emulated and ~40x slower
  (measured on v5e: 30 IPM iterations on a 97x209 LP cost ~65 ms/instance in
  f64 vs ~1.5-4.5 ms/instance in f32). Certification does not suffer: the
  Lagrangian lower bound is valid for ANY dual vector, so it is *evaluated*
  in float64 from the float32 dual — two matvecs, not an iteration.
- Problems are tiny (m, n in the low hundreds) but numerous: dense normal
  equations with a batched Cholesky map straight onto the MXU; there is no
  sparse path on purpose.
- One factorization per iteration: predictor and corrector share the same
  normal matrix (A Theta A' + reg I), so it is factored once and back-solved
  twice.
- Branch-and-bound fixes variables by collapsing their box (l_j == u_j). A
  collapsed box has no barrier interior, so fixed columns are masked out of
  the KKT system (theta_j = 0) and their lower bounds are folded into the
  RHS; the iteration shapes never change, which is what keeps one compiled
  kernel serving every node of the search tree.
- Fixed iteration count with a convergence freeze (no data-dependent control
  flow under ``jit``); callers read the residual norms to judge convergence.
- ``bound`` is *rigorous* from ANY dual vector y (no dual-feasibility
  requirement) because every primal variable is boxed:
      L(y) = b'y + sum_j r_j * min(0, (c - A'y)_j)    (+ c'l shift)
  Branch-and-bound pruning relies on this, not on IPM convergence.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax

# The rigorous bound evaluation below is float64; without x64 every
# .astype(float64) silently downcasts to f32 and the certification
# precision is lost. Enable it here, not only in importers.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from ..obs.compile_ledger import instrument  # noqa: E402  - stdlib-only

BOUND_DTYPE = jnp.float64

# -- in-jit convergence trace (decoded by obs/convergence.py) ---------------
# With ``trace=True`` the solve loop records ONE row per executed ``chunk``
# boundary into a fixed-size buffer riding the while-loop carry:
#   [iters, rp_norm, rd_norm, gap, restarts, live]
# iters is the element's CUMULATIVE executed-iteration count at the
# boundary, rp/rd are the scaled-system residual inf-norms, gap is the
# engine's convergence gauge (complementarity mu here; the normalized
# duality gap in ops/pdhg.py), restarts is the cumulative count of chunks
# in which the Halpern anchor restarted (the restart cadence; always 0
# for the IPM — see ops/pdhg.py for why it is chunk-granular), and live
# flags whether the element was still iterating when the chunk STARTED —
# a decoded element's valid samples are exactly its live rows. The default path (``trace=False``)
# carries no buffer and compiles to the identical program (pinned by the
# bit-equality test in tests/test_convergence.py).
TRACE_COLS = 6
IPM_DEFAULT_CHUNK = 4


def n_trace_rows(iters: int, chunk: int) -> int:
    """Rows of the per-chunk trace buffer for an (iters, chunk) budget —
    the ONE copy of the kernel's chunk-count arithmetic, so the packed
    output decode in backend_jax can never disagree with the while-loop
    bound about how many rows were allocated."""
    chunk = max(1, min(int(chunk), int(iters)))
    return -(-int(iters) // chunk)


class LPBatch(NamedTuple):
    """One fleet instance's LP family: (shared or batched) A, batched b/c/l/u.

    A with shape (m, n) is shared across the batch (same constraint structure
    for every k and every branch-and-bound node — the dense HALDA case);
    shape (B, m, n) carries a per-instance matrix (the MoE case, where expert
    busy coefficients scale with 1/k). b/c/l/u always carry the per-instance
    variation.
    """

    A: jax.Array  # (m, n) shared or (B, m, n) batched
    b: jax.Array  # (B, m)
    c: jax.Array  # (B, n)
    l: jax.Array  # (B, n)
    u: jax.Array  # (B, n)


class IPMWarmState(NamedTuple):
    """Warm-start iterate for one LP family, in ORIGINAL coordinates.

    The internal iteration is column-equilibrated by the box width, and the
    box (hence the scaling) changes between a parent node and its children
    and between streaming ticks — so iterates are carried in original units
    and re-scaled on entry. ``ok`` gates each element: a False (or any
    non-finite component) falls back to the cold mid-box start, so a stale
    or garbage warm state can only cost iterations, never corrupt a solve.
    """

    v: jax.Array  # (B, n) primal point (original coordinates)
    y: jax.Array  # (B, m) row duals (scale-invariant)
    z: jax.Array  # (B, n) lower-box duals, original units
    f: jax.Array  # (B, n) upper-box duals, original units
    ok: jax.Array  # (B,) bool — element carries a usable iterate


class IPMResult(NamedTuple):
    v: jax.Array  # (B, n) primal point in original coordinates (l + x)
    bound: jax.Array  # (B,) rigorous lower bound on the LP optimum (float64)
    obj: jax.Array  # (B,) primal objective c'v at the returned point
    rp_norm: jax.Array  # (B,) primal residual inf-norm (scaled system)
    rd_norm: jax.Array  # (B,) dual residual inf-norm (scaled system)
    mu: jax.Array  # (B,) final complementarity measure
    converged: jax.Array  # (B,) bool
    reduced: jax.Array  # (B, n) float64 reduced costs c - A'y of the bound's dual
    # Final iterates in original units (see IPMWarmState) — what a caller
    # feeds back as the next solve's warm start.
    y_dual: jax.Array  # (B, m)
    z_dual: jax.Array  # (B, n)
    f_dual: jax.Array  # (B, n)
    iters_run: jax.Array  # (B,) int32 iterations actually executed
    # Per-chunk convergence trace, (B, n_trace_rows, TRACE_COLS) when the
    # solve ran with ``trace=True``; None (a leafless pytree slot — vmap
    # and jit cost nothing for it) on the default untraced path.
    trace_buf: Optional[jax.Array] = None


def _default_tol(dtype) -> float:
    return 1e-9 if dtype == jnp.float64 else 1e-5


def _default_reg(dtype) -> float:
    return 1e-10 if dtype == jnp.float64 else 1e-7


def _ipm_single(A, b, c, l, u, iters: int, tol, reg, warm=None, skip=None,
                chunk: int = IPM_DEFAULT_CHUNK, trace: bool = False):
    """Mehrotra predictor-corrector on one boxed LP. Runs under vmap.

    ``warm`` (an :class:`IPMWarmState` element) seeds the iteration from a
    previous solve's point — the branch-and-bound parent's iterate projected
    into this node's (tightened) box, or last streaming tick's root iterate.
    ``skip`` marks the element as already-done (its lanes freeze at once and
    stop gating the batch-wide early exit). The iteration budget is spent in
    ``chunk``-sized pieces of a ``lax.while_loop``: once every live batch
    element has converged (or frozen) the loop exits, so converged batches
    stop paying Cholesky factorizations — the bound stays rigorous because
    it is evaluated from WHATEVER dual the loop reached.
    """
    dtype = A.dtype
    n = A.shape[1]
    m = A.shape[0]

    r_raw = u - l
    active = r_raw > 0  # fixed (collapsed-box) columns leave the system
    b_hat = b - A @ l  # fold lower bounds (incl. fixed values) into the RHS

    # Column equilibration: scale every active column by its box width so the
    # shifted problem lives on [0, 1]^n. Branch-and-bound instances mix boxes
    # spanning 4 orders of magnitude (slack caps ~50, MoE expert counts up to
    # 256, binary-ish w splits) — unscaled, the f32 normal matrix conditioning
    # collapses and the iteration stalls with a garbage dual. The bound is
    # scale-invariant; v and the reduced costs are mapped back below.
    col_s = jnp.where(active, r_raw, 1.0)
    A_orig, c_orig = A, c
    A = A * col_s[None, :]
    c = c * col_s
    r = jnp.ones_like(r_raw)  # every active box is [0, 1] after scaling
    cm = jnp.where(active, c, 0.0)
    act = active.astype(dtype)
    n_active = jnp.maximum(act.sum(), 1.0)

    # Interior start: mid-box primal, unit duals.
    x0 = 0.5 * r
    w0 = r - x0
    z0 = jnp.ones(n, dtype)
    f0 = jnp.ones(n, dtype)
    y0 = jnp.zeros(m, dtype)

    if warm is not None:
        # Warm start: project the carried point into THIS box (children
        # tighten the parent's box; ticks drift it), re-scale to the
        # equilibrated [0, 1] coordinates, and pull strictly interior —
        # boundary iterates have no barrier interior and a vertex z/f can
        # be 0 or huge. Any non-finite component (or ok=False) falls back
        # to the cold start wholesale: garbage degrades, never corrupts.
        v_w, y_w, z_w, f_w, ok_w = warm
        fin = (
            ok_w
            & jnp.all(jnp.isfinite(v_w))
            & jnp.all(jnp.isfinite(y_w))
            & jnp.all(jnp.isfinite(z_w))
            & jnp.all(jnp.isfinite(f_w))
        )
        x_w = (jnp.clip(v_w.astype(dtype), l, u) - l) / col_s
        x_w = jnp.clip(x_w, 0.01, 0.99)
        z_sc = jnp.clip(z_w.astype(dtype) * col_s, 1e-2, 1e4)
        f_sc = jnp.clip(f_w.astype(dtype) * col_s, 1e-2, 1e4)
        x0 = jnp.where(fin, x_w, x0)
        w0 = jnp.where(fin, r - x0, w0)
        z0 = jnp.where(fin, z_sc, z0)
        f0 = jnp.where(fin, f_sc, f0)
        y0 = jnp.where(fin, y_w.astype(dtype), y0)

    b_scale = 1.0 + jnp.max(jnp.abs(b_hat))
    c_scale = 1.0 + jnp.max(jnp.abs(cm))
    eye = jnp.eye(m, dtype=dtype)

    def step(state, _):
        x, w, y, z, f, done, it = state
        it = it + (done <= 0.5).astype(jnp.int32)

        rp = b_hat - A @ (x * act)
        rd = cm - A.T @ y - z + f
        rd = rd * act
        ru = (r - x - w) * act
        mu = (jnp.vdot(x * act, z) + jnp.vdot(w * act, f)) / (2.0 * n_active)

        x_s = jnp.where(active, x, 1.0)
        w_s = jnp.where(active, w, 1.0)
        d = z / x_s + f / w_s
        theta = act / d

        # One normal-matrix factorization per iteration, shared by the
        # predictor and corrector back-solves.
        AT = A * theta[None, :]
        Mmat = AT @ A.T + reg * eye
        chol = jax.scipy.linalg.cho_factor(Mmat, lower=True)

        def directions(rc1, rc2):
            g = rd - rc1 / x_s + (rc2 - f * ru) / w_s
            rhs = rp + A @ (theta * g)
            dy = jax.scipy.linalg.cho_solve(chol, rhs)
            dx = theta * (A.T @ dy - g)
            dw = ru - dx
            dz = (rc1 - z * dx) / x_s
            df = (rc2 - f * dw) / w_s
            return dx, dw, dy, dz, df

        def max_step(v, dv):
            ratios = jnp.where(active & (dv < 0), -v / jnp.where(dv < 0, dv, -1.0), jnp.inf)
            return jnp.minimum(1.0, 0.9995 * jnp.min(ratios))

        # Predictor (pure Newton toward complementarity 0)
        dxa, dwa, dya, dza, dfa = directions(-x * z, -w * f)
        ap = jnp.minimum(max_step(x, dxa), max_step(w, dwa))
        ad = jnp.minimum(max_step(z, dza), max_step(f, dfa))
        mu_aff = (
            jnp.vdot((x + ap * dxa) * act, z + ad * dza)
            + jnp.vdot((w + ap * dwa) * act, f + ad * dfa)
        ) / (2.0 * n_active)
        tiny = jnp.asarray(1e-300 if dtype == jnp.float64 else 1e-30, dtype)
        sigma = jnp.clip((mu_aff / (mu + tiny)) ** 3, 0.0, 1.0)

        # Corrector (centering + Mehrotra second-order term)
        rc1 = sigma * mu - x * z - dxa * dza
        rc2 = sigma * mu - w * f - dwa * dfa
        dx, dw, dy, dz, df = directions(rc1, rc2)
        ap = jnp.minimum(max_step(x, dx), max_step(w, dw))
        ad = jnp.minimum(max_step(z, dz), max_step(f, df))

        # Numerical safety: near degeneracy the Newton system can blow up
        # (inf/NaN directions). A zero step keeps the iterate valid — the
        # instance simply stalls instead of corrupting its state, and the
        # caller's bound handling treats a stalled instance soundly. The
        # direction vectors must be zeroed too: 0 * inf = NaN would poison
        # the iterate through the update even with a zero step size.
        finite = (
            jnp.all(jnp.isfinite(dx))
            & jnp.all(jnp.isfinite(dw))
            & jnp.all(jnp.isfinite(dy))
            & jnp.all(jnp.isfinite(dz))
            & jnp.all(jnp.isfinite(df))
            & jnp.isfinite(ap)
            & jnp.isfinite(ad)
        )
        ap = jnp.where(finite, ap, 0.0)
        ad = jnp.where(finite, ad, 0.0)
        dx = jnp.where(finite, dx, 0.0)
        dw = jnp.where(finite, dw, 0.0)
        dy = jnp.where(finite, dy, 0.0)
        dz = jnp.where(finite, dz, 0.0)
        df = jnp.where(finite, df, 0.0)

        # Freeze converged instances with a select, not arithmetic masking:
        # post-convergence directions can be inf/NaN and 0*inf = NaN.
        frozen = done > 0.5
        x = jnp.where(frozen, x, x + ap * dx)
        w = jnp.where(frozen, w, w + ap * dw)
        y = jnp.where(frozen, y, y + ad * dy)
        z = jnp.where(frozen, z, z + ad * dz)
        f = jnp.where(frozen, f, f + ad * df)

        conv = (
            (mu < tol)
            & (jnp.max(jnp.abs(rp)) < tol * b_scale)
            & (jnp.max(jnp.abs(rd)) < tol * c_scale)
        )
        done = jnp.maximum(done, conv.astype(dtype))
        return (x, w, y, z, f, done, it), None

    done0 = jnp.zeros((), dtype)
    if skip is not None:
        # A skipped element (e.g. an inactive frontier row) freezes at once:
        # its lanes stop moving and stop gating the batch-wide early exit.
        done0 = jnp.where(skip, jnp.ones((), dtype), done0)
    init = (x0, w0, y0, z0, f0, done0, jnp.zeros((), jnp.int32))

    # The fixed iteration budget is spent chunk-by-chunk under a while loop
    # whose exit test is the batch-wide convergence flag (under vmap the
    # loop runs until EVERY element's cond is false): converged batches stop
    # paying factorizations instead of scanning out the full budget.
    chunk = max(1, min(int(chunk), iters))
    n_chunks = n_trace_rows(iters, chunk)

    def chunk_cond(carry):
        state, ci = carry[0], carry[1]
        return (ci < n_chunks) & (state[5] <= 0.5)

    def chunk_body(carry):
        state, ci = carry
        # convergence gate: the fixed-length inner scan is bounded by the
        # enclosing while_loop's batch-wide done test above, so converged
        # instances never pay more than one chunk of frozen iterations.
        state, _ = jax.lax.scan(step, state, None, length=chunk)
        return (state, ci + 1)

    def chunk_diag(state):
        """Trace-row diagnostics at a chunk boundary (scaled system, same
        quantities as the final-residual block below): two matvecs, paid
        only on the traced path."""
        x_s, w_s, y_s, z_s, f_s = state[0], state[1], state[2], state[3], state[4]
        rp_n = jnp.max(jnp.abs(b_hat - A @ (x_s * act)))
        rd_n = jnp.max(jnp.abs((cm - A.T @ y_s - z_s + f_s) * act))
        mu_n = (
            jnp.vdot(x_s * act, z_s) + jnp.vdot(w_s * act, f_s)
        ) / (2.0 * n_active)
        return rp_n, rd_n, mu_n

    def chunk_body_traced(carry):
        state, ci, tbuf = carry
        live = state[5] <= 0.5
        # convergence gate: same bound as chunk_body — the enclosing
        # while_loop's batch-wide done test ends the scan chunks.
        state, _ = jax.lax.scan(step, state, None, length=chunk)
        rp_n, rd_n, mu_n = chunk_diag(state)
        row = jnp.stack(
            [
                state[6].astype(dtype),  # cumulative iterations executed
                rp_n,
                rd_n,
                mu_n,
                jnp.zeros((), dtype),  # restarts: a Mehrotra IPM has none
                live.astype(dtype),
            ]
        )
        return (state, ci + 1, tbuf.at[ci].set(row))

    if trace:
        (x, w, y, z, f, done, it), _, tbuf = jax.lax.while_loop(
            chunk_cond,
            chunk_body_traced,
            (
                init,
                jnp.zeros((), jnp.int32),
                jnp.zeros((n_chunks, TRACE_COLS), dtype),
            ),
        )
    else:
        (x, w, y, z, f, done, it), _ = jax.lax.while_loop(
            chunk_cond, chunk_body, (init, jnp.zeros((), jnp.int32))
        )
        tbuf = None

    # Final residuals (iteration dtype, for diagnostics).
    rp = b_hat - A @ (x * act)
    rd = cm - A.T @ y - z + f
    mu = (jnp.vdot(x * act, z) + jnp.vdot(w * act, f)) / (2.0 * n_active)

    # The rigorous Lagrangian bound, evaluated in float64 in ORIGINAL units
    # (the equilibration above is internal to the iteration; the dual y is
    # the same for both scalings). Valid for ANY y, so the float32 iterate
    # only affects bound *tightness*, never soundness.
    A64 = A_orig.astype(BOUND_DTYPE)
    y64 = y.astype(BOUND_DTYPE)
    r64 = (r_raw * act).astype(BOUND_DTYPE)
    bh64 = b.astype(BOUND_DTYPE) - A64 @ l.astype(BOUND_DTYPE)
    reduced = c_orig.astype(BOUND_DTYPE) - A64.T @ y64
    # r64 is already 0 for inactive (fixed) columns, so no extra mask needed.
    bound = bh64 @ y64 + jnp.sum(r64 * jnp.minimum(0.0, reduced))
    # A non-finite dual vector carries no information: report -inf (the
    # vacuous-but-sound bound), never NaN, so callers can prune on `bound`
    # comparisons without a NaN silently acting like "proven bad".
    bound = jnp.where(jnp.isfinite(bound), bound, -jnp.inf)
    shift = c_orig.astype(BOUND_DTYPE) @ l.astype(BOUND_DTYPE)
    v = l + jnp.where(active, col_s * x, 0.0)

    return IPMResult(
        v=v,
        bound=bound + shift,
        obj=c_orig @ v,
        rp_norm=jnp.max(jnp.abs(rp)),
        rd_norm=jnp.max(jnp.abs(rd * act)),
        mu=mu,
        converged=done > 0,
        reduced=reduced,
        # Iterates back in original units (see IPMWarmState): y is shared
        # between the scalings, z/f divide the column equilibration out.
        y_dual=y,
        z_dual=jnp.where(active, z / col_s, 0.0),
        f_dual=jnp.where(active, f / col_s, 0.0),
        iters_run=it,
        trace_buf=tbuf,
    )


def ipm_solve_batch(
    batch: LPBatch,
    iters: int = 30,
    tol: Optional[float] = None,
    reg: Optional[float] = None,
    warm: Optional[IPMWarmState] = None,
    skip: Optional[jax.Array] = None,
    chunk: int = IPM_DEFAULT_CHUNK,
    trace: bool = False,
) -> IPMResult:
    """Solve a batch of boxed LPs (shared (m, n) or per-instance (B, m, n) A).

    Runs in the dtype of ``batch.A`` (float32 is the TPU production path);
    returns per-element primal points, objectives, and rigorous float64
    lower bounds. ``tol``/``reg`` default by dtype.

    ``warm`` carries per-element warm-start iterates (original coordinates;
    see :class:`IPMWarmState` — elements with ``ok=False`` or non-finite
    components start cold). ``skip`` (B,) freezes elements immediately so
    they stop gating the early exit. ``iters`` is the per-element budget,
    spent ``chunk`` iterations at a time with a batch-wide convergence test
    between chunks; ``iters_run`` in the result reports what was actually
    executed. ``trace`` (static) additionally records one convergence-trace
    row per executed chunk into ``trace_buf`` (see TRACE_COLS above); off by
    default, and the untraced program is bit-identical to the pre-trace one.
    """
    dtype = batch.A.dtype
    tol_v = _default_tol(dtype) if tol is None else tol
    reg_v = _default_reg(dtype) if reg is None else reg

    def single(A, b, c, l, u, wm, sk):
        return _ipm_single(
            A, b, c, l, u, iters, tol_v, reg_v, warm=wm, skip=sk, chunk=chunk,
            trace=trace,
        )

    # TPU matmuls default to bf16 multiplication for f32 inputs; an IPM loses
    # its dual (and with it the Lagrangian bound quality) at bf16. Force full
    # f32 accumulation — these matrices are tiny and latency-bound, so the
    # MXU throughput cost is irrelevant.
    with jax.default_matmul_precision("highest"):
        a_axis = 0 if batch.A.ndim == 3 else None
        axes = (
            a_axis, 0, 0, 0, 0,
            None if warm is None else 0,
            None if skip is None else 0,
        )
        return jax.vmap(single, in_axes=axes)(
            batch.A, batch.b, batch.c, batch.l, batch.u, warm, skip
        )


# Registered compile-ledger entry point (obs.compile_ledger; dlint DLP020):
# the wrapper is a passthrough while no ledger is enabled, and with one
# enabled it attributes this kernel's XLA compiles — every static below
# (`iters`/`chunk`/`trace`) mints a distinct executable, which is exactly
# what the ledger's static-arg-flip cause makes visible.
ipm_solve_batch = instrument(
    "ops.ipm.ipm_solve_batch",
    jax.jit(ipm_solve_batch, static_argnames=("iters", "chunk", "trace")),
    static_argnames=("iters", "chunk", "trace"),
)

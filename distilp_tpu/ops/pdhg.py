"""Batched restarted Halpern PDHG for box-constrained LPs — the matrix-free
fleet-scale sibling of :mod:`distilp_tpu.ops.ipm`.

Same problem family, same batch layout (:class:`~distilp_tpu.ops.ipm.LPBatch`),
same result contract (:class:`~distilp_tpu.ops.ipm.IPMResult`):

    min c'v   s.t.  A v = b,   l <= v <= u

The IPM factorizes a dense (m, m) normal matrix per iteration per batch
element — O(B·m²) memory and O(B·m³) FLOPs per round — which caps practical
fleet size at tens of devices (M=2048 dense HALDA has m≈12k rows: one f32
normal matrix is ~600 MB, and a beam of them does not fit anywhere). This
kernel is the first-order alternative the MPAX line of work (arXiv
2412.09734) shows is natural in JAX: primal-dual hybrid gradient with
Halpern anchoring and adaptive restarts (r²HPDHG, arXiv 2407.16144; HPR-LP,
arXiv 2408.12179). Every iteration is two operator applications (A x and
A' y) — no factorization, no fill-in, O(m·n) shared work per iteration with
O(B·(m+n)) per-element state. Dense-mode batches share ONE (m, n) A across
every branch-and-bound node, so fleet-scale memory is the matrix once plus
vectors per node.

Design notes, mirroring the IPM kernel so the two engines are drop-in
interchangeable behind ``backend_jax``:

- **Same coordinates.** The internal iteration is column-equilibrated by the
  box width (shifted to x in [0, 1]^n); warm states carry ORIGINAL
  coordinates and re-scale on entry, so :class:`PDHGWarmState` and
  ``IPMWarmState`` are field-for-field interchangeable — the SearchState
  node-iterate plumbing, the streaming root-warm path and ``HALDAResult.
  ipm_state`` persistence carry either engine's iterates unchanged.
- **Same certificate.** The rigorous float64 Lagrangian bound
  ``L(y) = b'y + sum_j r_j min(0, (c - A'y)_j)`` is valid for ANY dual
  vector, exactly as in the IPM — branch-and-bound certification logic
  consumes the result without knowing which engine produced it. The box
  duals reported for warm-state persistence are the sign-split of the
  reduced costs (``z - f = c - A'y`` with z, f >= 0), which is what an
  optimal PDHG dual implies and what the IPM accepts as a warm seed.
- **Same control flow.** The iteration budget is spent in ``chunk``-sized
  pieces of a ``lax.while_loop`` whose exit test is the batch-wide
  convergence flag; ``skip`` freezes elements immediately; a stalled or
  non-finite element degrades (bound -inf, converged False), never corrupts.
- **Halpern + restart.** Each step computes the plain PDHG operator T(z)
  and takes the Halpern average ``z+ = (t+1)/(t+2) T(z) + 1/(t+2) z_anchor``
  — the anchored sequence converges at the accelerated O(1/t) fixed-point
  rate. The normalized fixed-point residual ||z - T(z)|| (in the
  tau/sigma-weighted norm) doubles as the restart criterion: when it decays
  below ``restart_tol`` times the residual at the current anchor (or first
  exceeds it — the no-progress guard), the anchor is reset to the current
  iterate and the Halpern counter restarts. Step sizes are diagonal
  (Pock-Chambolle): ``tau_j = 0.9 / Σ_i |Ā_ij|``, ``sigma_i = 0.9 /
  Σ_j |Ā_ij|`` — valid for any matrix with no spectral-norm estimate, and
  far faster on HALDA's mixed-density rows than a scalar step throttled by
  the densest (cycle/memory) rows.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax

# Same rationale as ops/ipm.py: the f64 certificate evaluation below is
# meaningless if x64 silently downcasts. Enable here, not only in importers.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from ..obs.compile_ledger import instrument  # noqa: E402  - stdlib-only
from .ipm import (  # noqa: E402
    BOUND_DTYPE,
    TRACE_COLS,
    IPMResult,
    LPBatch,
    n_trace_rows,
)

# Default convergence-test granularity (iterations per while-loop chunk).
# Shared with the trace-row accounting: the packed-output decode in
# backend_jax sizes the root trace from this constant, so it must be THE
# value the kernel clamps against, not a copy.
PDHG_DEFAULT_CHUNK = 32


def _default_tol_pdhg(dtype) -> float:
    """First-order exit tolerance. The IPM's 1e-9 (f64) is a few Newton
    steps; for PDHG it is ~orders of magnitude more iterations spent long
    after the bound stopped moving at certification scale (mip_gap is
    1e-3/1e-4). 1e-7 relative leaves two decades of slack below the
    tightest gap anyone certifies at; f32 keeps the shared 1e-5 floor."""
    import jax.numpy as _jnp

    return 1e-7 if dtype == _jnp.float64 else 1e-5


# Sufficient-decay factor of the adaptive restart (arXiv 2407.16144 uses
# beta_sufficient ≈ 0.2): restart when the weighted fixed-point residual
# drops below restart_tol × the residual at the current anchor.
DEFAULT_RESTART_TOL = 0.2

# Iterate-precision knob values (the `pdhg_dtype` threading). 'f32' is the
# default everywhere — iterates and A blocks in f32, certificate in f64 via
# `preferred_element_type` accumulation (the mixed-precision contract) —
# and 'f64' is the soundness fallback a non-finite or stalled f32 run
# escalates to, the way warm-garbage already falls back to cold.
PDHG_DTYPES = ("f32", "f64")


def resolve_pdhg_dtype(name):
    """'f32'/'f64' (or None = keep the batch dtype) -> jnp dtype or None."""
    if name is None:
        return None
    if name == "f32":
        return jnp.float32
    if name == "f64":
        return jnp.float64
    raise ValueError(
        f"unknown pdhg_dtype {name!r}; expected one of {PDHG_DTYPES}"
    )


class PDHGWarmState(NamedTuple):
    """Warm-start iterate in ORIGINAL coordinates — field-for-field the same
    contract as :class:`distilp_tpu.ops.ipm.IPMWarmState`, so the two
    engines' warm states are interchangeable everywhere the solver carries
    one (B&B node iterates, streaming root warm state, ``ipm_state``
    persistence). ``z``/``f`` (box duals) are accepted for compatibility —
    PDHG re-derives its dual geometry from ``v``/``y`` alone — and are
    emitted on exit as the reduced-cost sign-split so an IPM consumer gets
    a usable barrier seed. ``ok`` gates each element; any non-finite
    component falls back to the cold start wholesale."""

    v: jax.Array  # (B, n) primal point (original coordinates)
    y: jax.Array  # (B, m) row duals (scale-invariant)
    z: jax.Array  # (B, n) lower-box duals, original units
    f: jax.Array  # (B, n) upper-box duals, original units
    ok: jax.Array  # (B,) bool — element carries a usable iterate


def _pdhg_single(A, b, c, l, u, iters: int, tol, restart_tol, warm=None,
                 skip=None, chunk: int = PDHG_DEFAULT_CHUNK,
                 trace: bool = False, axis_name=None):
    """Restarted Halpern PDHG on one boxed LP. Runs under vmap.

    Mirrors ``_ipm_single``'s contract: ``warm`` seeds from a previous
    solve's point (projected into THIS box), ``skip`` freezes the element
    immediately, the budget is spent ``chunk`` iterations at a time under a
    while loop whose exit is the batch-wide convergence flag, and the
    returned bound is the f64 Lagrangian bound — valid for whatever dual
    the iteration reached.

    ``axis_name`` (static) is the mesh-sharded mode (ops/meshlp.py): the
    caller hands each shard a DEVICE-ROW block of the instance — ``A``
    ``(m_blk, n)``, ``b`` ``(m_blk,)``, warm ``y`` ``(m_blk,)``; the
    column data ``c``/``l``/``u`` and the primal iterate replicated — and
    names the shard_map mesh axis here. Every cross-row reduction
    (column 1-norms, the dual's contribution to residuals/gap/certificate,
    the feasibility max) then closes over the mesh with a ``psum``/``pmax``
    at exactly those points; everything else — the per-row scalings, opA,
    the dual update, the restart control — is block-local. With
    ``axis_name=None`` every hook is the identity and the program is
    byte-for-byte the single-device kernel (the mesh_shards=1 bit-
    stability contract).
    """
    if axis_name is None:
        def _psum(v):
            return v

        _pmax = _psum
    else:
        def _psum(v):
            return jax.lax.psum(v, axis_name)

        def _pmax(v):
            return jax.lax.pmax(v, axis_name)

    dtype = A.dtype
    n = A.shape[1]
    m = A.shape[0]

    r_raw = u - l
    active = r_raw > 0  # fixed (collapsed-box) columns leave the system
    b_hat = b - A @ l  # fold lower bounds (incl. fixed values) into the RHS

    # Column equilibration by box width — identical to the IPM kernel, for
    # the identical reason: branch-and-bound boxes span orders of magnitude
    # and an unscaled first-order method stalls on the induced anisotropy.
    # Fixed columns get scale 0 so they contribute nothing to any product.
    col_s = jnp.where(active, r_raw, 1.0)
    cs_a = jnp.where(active, r_raw, 0.0)
    r = jnp.ones_like(r_raw)
    act = active.astype(dtype)
    cm = jnp.where(active, c * col_s, 0.0)

    # Row re-equilibration. The assembler's rows arrive max-normalized, but
    # the box-width column scaling above re-spreads them (a w column scaled
    # by W ~ 10^3 drags its rows with it): first-order steps — unlike the
    # IPM's normal equations — see that anisotropy directly as a huge,
    # lopsided ||A|| and crawl. One inf-norm row pass restores unit-scale
    # rows. The scaled dual y_s relates to the ORIGINAL-units dual (the one
    # the f64 certificate, the warm-state contract and the reduced costs
    # use) by y = row_s · y_s — applied at the warm entry and the exit.
    # (The abs·scale product fuses into the row reduction — nothing (m, n)
    # is materialized.)
    row_s = 1.0 / jnp.maximum(jnp.max(jnp.abs(A) * cs_a[None, :], axis=1), 1e-12)
    b_s = b_hat * row_s

    # THE fleet-scale invariant: both scalings stay VECTORS and A is only
    # ever touched through these two operator applications. A per-element
    # scaled copy (A · col_s · row_s) would be a (B, m, n) tensor — at
    # M=2048 with a beam of 6 that is ~8 GB, i.e. the exact memory wall
    # this engine exists to avoid — and it would also turn the batched
    # matvec into B separate A-streams. With A shared and unbatched under
    # vmap, XLA batches every opA/opAT into ONE (m, n) × (n, B) product:
    # the matrix streams once per application for the whole node batch.
    def opA(x):
        return row_s * (A @ (cs_a * x))

    # A'y spans every device row: in sharded mode each block contributes
    # its partial column sum and the psum closes it — the ONE collective a
    # PDHG iteration pays (opA is row-local because x is replicated).
    def opAT(y):
        return cs_a * _psum(A.T @ (row_s * y))

    # Diagonal (Pock-Chambolle) step sizes on the scaled operator Ā:
    # tau_j = θ / Σ_i |Ā_ij|, sigma_i = θ / Σ_j |Ā_ij| with θ = 0.9 — the
    # induced ||Σ^½ Ā T^½|| is ≤ 1 for ANY matrix, so the PDHG step-size
    # contract holds with no spectral-norm estimate, and each coordinate
    # moves at the pace its own coupling allows. On the HALDA LPs this is
    # the difference between converging and crawling: a scalar 0.9/||Ā||
    # step is throttled by the densest row (the cycle/memory rows touch
    # every device) while most columns are nearly decoupled. The 1-norms
    # are two reductions over |A| — shared across the batch like every
    # other touch of A, nothing per-element materialized.
    absA = jnp.abs(A)
    row_1n = row_s * (absA @ cs_a)
    col_1n = cs_a * _psum(absA.T @ row_s)
    # Decoupled coordinates (fixed columns; rows whose every column is
    # fixed) get step 0, not 0.9/eps: a huge pseudo-step on a zero-coupling
    # lane would just amplify roundoff (or overflow f32 on an inconsistent
    # empty row) without moving anything that matters.
    tau = jnp.where(col_1n > 1e-12, 0.9 / jnp.maximum(col_1n, 1e-12), 0.0)
    tau = jnp.where(active, tau, 0.0)
    sigma = jnp.where(row_1n > 1e-12, 0.9 / jnp.maximum(row_1n, 1e-12), 0.0)

    # Cold start: mid-box primal, zero dual (the IPM's start, minus the
    # barrier interior it does not need).
    x0 = 0.5 * r
    y0 = jnp.zeros(m, dtype)

    b_scale = 1.0 + _pmax(jnp.max(jnp.abs(b_s)))
    c_scale = 1.0 + jnp.max(jnp.abs(cm))

    def T(x, y):
        """One plain PDHG step: primal projected-gradient, dual ascent at
        the extrapolated primal. Two operator applications total."""
        x_new = jnp.clip(x - tau * (cm - opAT(y)), 0.0, r)
        y_new = y + sigma * (b_s - opA(2.0 * x_new - x))
        return x_new, y_new

    def weighted_res(dx, dy):
        # Fixed-point residual in the (diagonal) PDHG norm: Σ dx²/tau +
        # Σ dy²/sigma with the cross term dropped — the standard restart
        # gauge. Zero-step lanes never move (dx = dy = 0 there), so they
        # are excluded rather than divided by zero.
        # Sharded mode: dx is replicated (x updates through the psum'd
        # opAT), dy is block-local — only the dual half needs the psum.
        qx = jnp.sum(jnp.where(tau > 0, dx * dx, 0.0) / jnp.maximum(tau, 1e-30))
        qy = _psum(
            jnp.sum(jnp.where(sigma > 0, dy * dy, 0.0) / jnp.maximum(sigma, 1e-30))
        )
        return jnp.sqrt(qx + qy)

    def conv_stats(x, y):
        """Convergence = primal feasibility + relative duality gap at the
        CURRENT iterate, both in iteration precision. The f64 certificate
        is evaluated once at exit, like the IPM's. Also returns the
        trace-row diagnostics (rp/rd norms, normalized gap) — the untraced
        path consumes only the flag and XLA drops the rest.
        """
        rp = b_s - opA(x)
        obj = jnp.vdot(cm, x)
        red = cm - opAT(y)
        # b'y spans the row shards; the reduced-cost half is columnwise
        # and already replicated through the psum'd opAT.
        lag = _psum(jnp.vdot(b_s, y)) + jnp.vdot(act, jnp.minimum(0.0, red))
        gap = jnp.abs(obj - lag)
        conv = (_pmax(jnp.max(jnp.abs(rp))) < tol * b_scale) & (
            gap < tol * (b_scale + c_scale + jnp.abs(obj))
        )
        rd = red - jnp.minimum(0.0, red) * act
        return (
            conv,
            _pmax(jnp.max(jnp.abs(rp))),
            jnp.max(jnp.abs(rd)),
            gap / (b_scale + c_scale),
        )

    def conv_of(x, y):
        return conv_stats(x, y)[0]

    def step(state, _):
        x, y, xa, ya, res_a, t, done, it = state
        live = done <= 0.5
        it = it + live.astype(jnp.int32)

        Tx, Ty = T(x, y)
        res = weighted_res(Tx - x, Ty - y)

        # Halpern anchoring toward the restart anchor.
        t_f = t.astype(dtype)
        w_new = (t_f + 1.0) / (t_f + 2.0)
        x_h = w_new * Tx + (1.0 - w_new) * xa
        y_h = w_new * Ty + (1.0 - w_new) * ya

        # Adaptive restart: sufficient decay of the weighted fixed-point
        # residual vs the anchor (or a blow-up past it — the stall guard).
        do_restart = (res <= restart_tol * res_a) | (res > res_a)
        x_n = jnp.where(do_restart, Tx, x_h)
        y_n = jnp.where(do_restart, Ty, y_h)
        xa = jnp.where(do_restart, Tx, xa)
        ya = jnp.where(do_restart, Ty, ya)
        res_a = jnp.where(do_restart, res, res_a)
        t = jnp.where(do_restart, 0, t + 1)

        # Non-finite safety: a blown-up step keeps the previous iterate
        # (the element stalls honestly; the f64 bound of a stalled dual is
        # still valid, and a NaN dual reports -inf downstream). The dual
        # half is block-local in sharded mode, and the verdict must be
        # mesh-global — a shard keeping its x while another rolls back
        # would fork the replicated primal.
        finite = jnp.all(jnp.isfinite(x_n)) & (
            _pmax(jnp.any(~jnp.isfinite(y_n)).astype(dtype)) < 0.5
        )
        x_n = jnp.where(finite, x_n, x)
        y_n = jnp.where(finite, y_n, y)

        # Freeze converged/skipped elements with a select (0·inf = NaN).
        frozen = ~live
        x = jnp.where(frozen, x, x_n)
        y = jnp.where(frozen, y, y_n)
        return (x, y, xa, ya, res_a, t, done, it), None

    if warm is not None:
        # Warm gating, the first-order way. The IPM clips any finite warm
        # point into the barrier interior and recovers; PDHG has no such
        # taming — from a dual 1e5 away the O(1/t) Halpern rate needs ~1e5
        # iterations just to travel home. So the entry test is BEST-OF-TWO:
        # evaluate the weighted fixed-point residual at the (projected)
        # warm point and at the cold start, and keep whichever is closer to
        # a fixed point. A near-optimal carried iterate wins by orders of
        # magnitude; a stale/absurd one loses and costs exactly two extra
        # operator applications, never the solve. ok=False or ANY
        # non-finite component skips straight to cold, as in the IPM. z/f
        # ride along for plumbing compatibility but carry no PDHG state.
        v_w, y_w, z_w, f_w, ok_w = warm
        # y_w is the block-local slice in sharded mode; the gate must be
        # mesh-global or the shards would disagree on the warm entry.
        fin = (
            ok_w
            & jnp.all(jnp.isfinite(v_w))
            & (_pmax(jnp.any(~jnp.isfinite(y_w)).astype(dtype)) < 0.5)
            & jnp.all(jnp.isfinite(z_w))
            & jnp.all(jnp.isfinite(f_w))
        )
        x_w = (jnp.clip(v_w.astype(dtype), l, u) - l) / col_s
        x_w = jnp.clip(x_w, 0.0, 1.0)
        y_w = y_w.astype(dtype) / row_s
        Txw, Tyw = T(x_w, y_w)
        res_w = weighted_res(Txw - x_w, Tyw - y_w)
        Txc, Tyc = T(x0, y0)
        res_c = weighted_res(Txc - x0, Tyc - y0)
        res_w = jnp.where(jnp.isfinite(res_w), res_w, jnp.inf)
        use_w = fin & (res_w <= res_c)
        x0 = jnp.where(use_w, x_w, x0)
        y0 = jnp.where(use_w, y_w, y0)

    done0 = jnp.zeros((), dtype)
    if skip is not None:
        done0 = jnp.where(skip, jnp.ones((), dtype), done0)
    res0 = weighted_res(*(lambda p: (p[0] - x0, p[1] - y0))(T(x0, y0)))
    init = (
        x0, y0, x0, y0, jnp.maximum(res0, 1e-30),
        jnp.zeros((), jnp.int32), done0, jnp.zeros((), jnp.int32),
    )

    chunk = max(1, min(int(chunk), iters))
    n_chunks = n_trace_rows(iters, chunk)

    def chunk_cond(carry):
        state, ci = carry[0], carry[1]
        return (ci < n_chunks) & (state[6] <= 0.5)

    def chunk_body(carry):
        state, ci = carry
        # convergence gate: the fixed-length inner scan is bounded by the
        # enclosing while_loop's batch-wide done test above. Convergence is
        # tested ONCE per chunk, not per step — the test itself is two
        # operator applications, the same price as a whole iteration, so a
        # per-step test would double the engine's cost for the privilege of
        # exiting at most chunk-1 iterations earlier. Live elements may run
        # up to one chunk past convergence; over-iteration is harmless by
        # the same frozen-solution argument as the IPM's (pinned in tests).
        state, _ = jax.lax.scan(step, state, None, length=chunk)
        x, y, xa, ya, res_a, t, done, it = state
        done = jnp.maximum(done, conv_of(x, y).astype(dtype))
        return ((x, y, xa, ya, res_a, t, done, it), ci + 1)

    def chunk_body_traced(carry):
        state, ci, tbuf, nre = carry
        live = state[6] <= 0.5
        t_prev = state[5]
        # convergence gate: same bound as chunk_body — the enclosing
        # while_loop's batch-wide done test ends the scan chunks.
        state, _ = jax.lax.scan(step, state, None, length=chunk)
        conv, rp_n, rd_n, gap_n = conv_stats(state[0], state[1])
        done = jnp.maximum(state[6], conv.astype(dtype))
        state = state[:6] + (done,) + state[7:]
        # Restart flag from the Halpern anchor counter ALONE (zero
        # per-step cost, which is what keeps the traced kernel inside the
        # bench's 5% ceiling): a live chunk with no restart advances t by
        # exactly `chunk`, so any shortfall means the anchor reset at
        # least once this chunk. The trace column is therefore the
        # cumulative count of restart-CHUNKS — the restart cadence, exact
        # whenever restarts are rarer than one per chunk (they are, by
        # orders of magnitude, at the default sufficient-decay factor).
        restarted = live & (state[5] != t_prev + chunk)
        nre = nre + restarted.astype(jnp.int32)
        row = jnp.stack(
            [
                state[7].astype(dtype),  # cumulative iterations executed
                rp_n,
                rd_n,
                gap_n,
                nre.astype(dtype),  # cumulative restart chunks
                live.astype(dtype),
            ]
        )
        return (state, ci + 1, tbuf.at[ci].set(row), nre)

    if trace:
        (x, y, _, _, _, _, done, it), _, tbuf, _ = jax.lax.while_loop(
            chunk_cond,
            chunk_body_traced,
            (
                init,
                jnp.zeros((), jnp.int32),
                jnp.zeros((n_chunks, TRACE_COLS), dtype),
                jnp.zeros((), jnp.int32),
            ),
        )
    else:
        (x, y, _, _, _, _, done, it), _ = jax.lax.while_loop(
            chunk_cond, chunk_body, (init, jnp.zeros((), jnp.int32))
        )
        tbuf = None

    # Final residuals (iteration dtype, diagnostics only; scaled units).
    rp = b_s - opA(x)
    red32 = cm - opAT(y)
    rd = red32 - jnp.minimum(0.0, red32) * act  # dual infeas. of the split
    mu = jnp.abs(jnp.vdot(cm, x) - (
        _psum(jnp.vdot(b_s, y)) + jnp.vdot(act, jnp.minimum(0.0, red32))
    )) / (b_scale + c_scale)
    # Back to the original-units dual for the certificate and the warm
    # state (see the row re-equilibration note above).
    y = y * row_s

    # The rigorous f64 Lagrangian bound in ORIGINAL units — the SAME formula
    # and the same soundness argument as the IPM kernel: valid for any y,
    # so first-order dual quality moves bound tightness, never validity.
    # f64 ACCUMULATION without an f64 copy of A: `preferred_element_type`
    # widens the dot products over the f32 matrix in place — the f32 values
    # ARE the problem data (same as the IPM's cast; the rounding happened
    # upstream in the pack), and duplicating a fleet-scale A in f64 would
    # cost more memory than the whole iteration state.
    y64 = y.astype(BOUND_DTYPE)
    r64 = (r_raw * act).astype(BOUND_DTYPE)
    l64 = l.astype(BOUND_DTYPE)
    c64 = c.astype(BOUND_DTYPE)
    bh64 = b.astype(BOUND_DTYPE) - jnp.matmul(
        A, l, preferred_element_type=BOUND_DTYPE
    )
    # Sharded: each block contributes its rows' share of both cross-row
    # terms (the A'y partial and b̂'y); the f64 psum keeps the certificate
    # precision of the single-device kernel — accumulation order changes,
    # validity does not (the bound holds for ANY dual).
    reduced = c64 - _psum(
        jnp.matmul(A.T, y, preferred_element_type=BOUND_DTYPE)
    )
    bound = _psum(bh64 @ y64) + jnp.sum(r64 * jnp.minimum(0.0, reduced))
    bound = jnp.where(jnp.isfinite(bound), bound, -jnp.inf)
    shift = c64 @ l64
    v = l + jnp.where(active, col_s * x, 0.0)

    # Box duals for warm-state persistence: the sign-split of the reduced
    # costs (z - f = c - A'y, z·f-complementary by construction) in
    # ORIGINAL units — exactly what the IPM emits at optimality and accepts
    # (clipped into the barrier interior) as a warm seed.
    red_orig = reduced.astype(dtype)
    z_dual = jnp.where(active, jnp.maximum(red_orig, 0.0), 0.0)
    f_dual = jnp.where(active, jnp.maximum(-red_orig, 0.0), 0.0)

    return IPMResult(
        v=v,
        bound=bound + shift,
        obj=c @ v,
        rp_norm=_pmax(jnp.max(jnp.abs(rp))),
        rd_norm=jnp.max(jnp.abs(rd)),
        mu=mu,
        converged=done > 0,
        reduced=reduced,
        y_dual=y,
        z_dual=z_dual,
        f_dual=f_dual,
        iters_run=it,
        trace_buf=tbuf,
    )


def pdhg_solve_batch(
    batch: LPBatch,
    iters: int = 1000,
    tol: Optional[float] = None,
    restart_tol: Optional[float] = None,
    warm: Optional[PDHGWarmState] = None,
    skip: Optional[jax.Array] = None,
    chunk: int = PDHG_DEFAULT_CHUNK,
    trace: bool = False,
    dtype: Optional[str] = None,
) -> IPMResult:
    """Solve a batch of boxed LPs matrix-free (shared (m, n) or per-instance
    (B, m, n) A) — the call-compatible first-order sibling of
    :func:`distilp_tpu.ops.ipm.ipm_solve_batch`.

    Returns the same :class:`IPMResult` contract: per-element primal points,
    objectives, rigorous float64 Lagrangian lower bounds, and final iterates
    in original coordinates for cross-solve warm starting. ``warm`` accepts
    either a :class:`PDHGWarmState` or an ``IPMWarmState`` (identical
    fields). ``iters`` is the per-element budget, spent ``chunk`` iterations
    at a time with a batch-wide convergence test between chunks;
    ``restart_tol`` is the Halpern restart's sufficient-decay factor.
    ``trace`` (static) records one convergence-trace row per executed chunk
    — residual norms, normalized gap, the cumulative Halpern restart-chunk
    count — into ``trace_buf`` (see ops/ipm.py TRACE_COLS); the untraced
    program is bit-identical to the pre-trace one.

    ``dtype`` (static: 'f32'/'f64', None = the batch's own dtype) sets the
    ITERATION precision: the instance data and iterates are cast on entry,
    while the exit certificate stays the f64 Lagrangian bound either way —
    a cast only moves how fast a usable dual is reached (and the exit
    tolerance floor, see ``_default_tol_pdhg``), never bound validity.
    """
    dt = resolve_pdhg_dtype(dtype)
    if dt is not None and dt != batch.A.dtype:
        batch = LPBatch(*(jnp.asarray(x).astype(dt) for x in batch))
    dtype = batch.A.dtype
    tol_v = _default_tol_pdhg(dtype) if tol is None else tol
    rt_v = DEFAULT_RESTART_TOL if restart_tol is None else restart_tol

    def single(A, b, c, l, u, wm, sk):
        return _pdhg_single(
            A, b, c, l, u, iters, tol_v, rt_v, warm=wm, skip=sk, chunk=chunk,
            trace=trace,
        )

    # Full f32 accumulation for the same reason as the IPM kernel: a bf16
    # dual wrecks the Lagrangian bound quality that certification prices.
    with jax.default_matmul_precision("highest"):
        a_axis = 0 if batch.A.ndim == 3 else None
        axes = (
            a_axis, 0, 0, 0, 0,
            None if warm is None else 0,
            None if skip is None else 0,
        )
        return jax.vmap(single, in_axes=axes)(
            batch.A, batch.b, batch.c, batch.l, batch.u, warm, skip
        )


# Registered compile-ledger entry point (obs.compile_ledger; dlint DLP020):
# same contract as ops.ipm.ipm_solve_batch — the `iters`/`chunk`/`trace`
# statics each mint a distinct executable, and the ledger attributes them.
pdhg_solve_batch = instrument(
    "ops.pdhg.pdhg_solve_batch",
    jax.jit(
        pdhg_solve_batch, static_argnames=("iters", "chunk", "trace", "dtype")
    ),
    static_argnames=("iters", "chunk", "trace", "dtype"),
)

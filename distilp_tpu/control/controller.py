"""The closed-loop autoscaler: signals in, typed actions out.

Two halves, one brain:

- ``Controller`` is the brain — ``decide(signals, now, n_workers)`` is
  deterministic: the same signal sequence through the same policy
  produces the same action sequence, byte for byte. No clock reads, no
  randomness, no I/O. That determinism is what makes the closed-loop
  contract PINNABLE offline: ``Controller.replay(timeline, policy)``
  walks a dumped timeline exactly like ``SLOEngine.replay`` walks it
  (same step loop, same bounds clamp) and reproduces the live decision
  trail without spawning a process — the ``make smoke-autoscale``
  fixture is that replay's committed output.
- ``ControlLoop`` is the hands — a sampler-shaped thread (``stop()``
  idempotent, gateway ``close()`` stops it before the workers) that
  feeds the live ``/signals`` payload to the same ``decide`` and
  actuates each action on the gateway: spawn/retire process workers
  (ring rebalance migrates shards live), flip forced-degrade admission,
  set ``spec_k``. Every action is counted in ``METRIC_REGISTRY`` and
  flight-recorded on the ``control`` ring WITH the signals snapshot
  that justified it, so the trail reconciles record-by-record against
  counters and against the offline replay.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..obs.slo import SLOEngine, SignalsPayload, build_signals
from ..obs.timeline import Timeline
from .policy import Action, ControlPolicy

# Counter name per action kind — exact METRIC_REGISTRY entries (DLP019).
_KIND_COUNTERS = {
    "scale_out": "control_scale_out",
    "scale_in": "control_scale_in",
    "degrade_on": "control_degrade_on",
    "degrade_off": "control_degrade_off",
    "spec_k": "control_spec_k",
}


class Controller:
    """Pure decision core. State (cooldown clock, calm timer, lever
    positions) lives here and advances only through ``decide`` — single
    writer by contract: the live loop's thread or the replay loop, never
    both on one instance."""

    def __init__(self, policy: ControlPolicy):
        self.policy = policy
        self._last_scale_t: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._degraded = False
        self._spec_k_low = False
        self._holds = 0  # decisions suppressed by cooldown/band edges
        # High-water mark of signals.recovery["workers_quarantined"]:
        # the quarantine vote fires on the INCREASE (a breaker newly
        # opened), not on the standing count — cumulative counters would
        # otherwise re-vote every tick until max_workers.
        self._quarantined_seen = 0

    # -- the decision function --------------------------------------------

    def decide(
        self, signals: SignalsPayload, now: float, n_workers: int
    ) -> List[Action]:
        p = self.policy
        acts: List[Action] = []
        page_open = any("page" in s.firing for s in signals.slos)

        # Degrade lever first: it is instant and reversible, the bridge
        # that keeps serving degraded-but-certified placements while a
        # spawned worker warms.
        if p.degrade_on_page:
            if page_open and not self._degraded:
                self._degraded = True
                acts.append(
                    Action(
                        t=now, kind="degrade_on", reason="page alert open"
                    )
                )
            elif not page_open and self._degraded:
                self._degraded = False
                acts.append(
                    Action(
                        t=now,
                        kind="degrade_off",
                        reason="page alerts clear",
                    )
                )

        # Scale-out: any vote trips (hysteresis is asymmetric on
        # purpose — adding capacity late is an outage, removing it late
        # is a small bill).
        votes: List[str] = []
        if p.scale_out_on_page and page_open:
            votes.append("page alert open")
        if (
            p.headroom_min_frac is not None
            and signals.headroom_eps is not None
            and signals.max_sustainable_eps
        ):
            floor = p.headroom_min_frac * signals.max_sustainable_eps
            if signals.headroom_eps < floor:
                votes.append(
                    f"headroom {signals.headroom_eps:.1f} eps below "
                    f"{floor:.1f} eps floor"
                )
        if p.depth_high_per_worker is not None and n_workers > 0:
            per = signals.queue_depth_total / n_workers
            if per >= p.depth_high_per_worker:
                votes.append(
                    f"queue depth {per:.1f}/worker at or above "
                    f"{p.depth_high_per_worker:g}"
                )
        if p.trend_up_per_s is not None and any(
            w.queue_depth_trend_per_s is not None
            and w.queue_depth_trend_per_s >= p.trend_up_per_s
            for w in signals.workers
        ):
            votes.append("queue depth trending up")
        if p.scale_out_on_quarantine and signals.recovery is not None:
            q = int(signals.recovery.get("workers_quarantined", 0) or 0)
            if q > self._quarantined_seen:
                votes.append(
                    f"crash-loop breaker quarantined "
                    f"{q - self._quarantined_seen} worker(s)"
                )
            self._quarantined_seen = max(self._quarantined_seen, q)

        cooled = (
            self._last_scale_t is None
            or (now - self._last_scale_t) >= p.scale_cooldown_s
        )
        if votes:
            self._calm_since = None
            if n_workers < p.max_workers and cooled:
                self._last_scale_t = now
                acts.append(
                    Action(
                        t=now,
                        kind="scale_out",
                        target_workers=n_workers + 1,
                        reason="; ".join(votes),
                    )
                )
            else:
                self._holds += 1
        else:
            # Scale-in: EVERY calm condition, held for calm_hold_s.
            calm = (
                signals.alerts_open == 0
                and signals.queue_depth_total <= p.depth_low_total
                and (
                    signals.headroom_eps is None
                    or not signals.max_sustainable_eps
                    or signals.headroom_eps
                    >= p.headroom_scale_in_frac
                    * signals.max_sustainable_eps
                )
            )
            if calm and n_workers > p.min_workers:
                if self._calm_since is None:
                    self._calm_since = now
                elif (now - self._calm_since) >= p.calm_hold_s:
                    if cooled:
                        self._last_scale_t = now
                        self._calm_since = None
                        acts.append(
                            Action(
                                t=now,
                                kind="scale_in",
                                target_workers=n_workers - 1,
                                reason=(
                                    f"calm held {p.calm_hold_s:g}s "
                                    "(no alerts, queue drained, "
                                    "headroom recovered)"
                                ),
                            )
                        )
                    else:
                        self._holds += 1
            elif not calm:
                self._calm_since = None

        # spec_k memory lever: shrink the speculation bank under memory
        # squeeze, restore when headroom recovers.
        if (
            p.mem_low_bytes is not None
            and signals.mem_headroom_bytes is not None
        ):
            if (
                signals.mem_headroom_bytes < p.mem_low_bytes
                and not self._spec_k_low
            ):
                self._spec_k_low = True
                acts.append(
                    Action(
                        t=now,
                        kind="spec_k",
                        spec_k=p.spec_k_low,
                        reason=(
                            f"mem headroom "
                            f"{signals.mem_headroom_bytes:.0f}B below "
                            f"{p.mem_low_bytes:.0f}B floor"
                        ),
                    )
                )
            elif (
                signals.mem_headroom_bytes >= p.mem_low_bytes
                and self._spec_k_low
                and p.spec_k_normal is not None
            ):
                self._spec_k_low = False
                acts.append(
                    Action(
                        t=now,
                        kind="spec_k",
                        spec_k=p.spec_k_normal,
                        reason="mem headroom recovered",
                    )
                )
        return acts

    # -- decision accounting (live loop + harness share this) --------------

    def step(
        self,
        signals: SignalsPayload,
        now: float,
        n_workers: int,
        metrics=None,
        flight=None,
    ) -> List[Action]:
        """``decide`` + the accounting contract: every action counted
        (``control_actions`` + its per-kind counter) and flight-recorded
        on the ``control`` ring with the signals snapshot that justified
        it — the record the reconciliation audits."""
        holds_before = self._holds
        actions = self.decide(signals, now, n_workers)
        if metrics is not None:
            held = self._holds - holds_before
            for _ in range(held):
                metrics.inc("control_hold")
            for a in actions:
                metrics.inc("control_actions")
                metrics.inc(_KIND_COUNTERS[a.kind])
        if flight is not None:
            for a in actions:
                flight.record(
                    "control",
                    {
                        "t": now,
                        "action": a.model_dump(),
                        "signals": signals.model_dump(),
                    },
                )
        return actions

    # -- offline replay ----------------------------------------------------

    @classmethod
    def replay(
        cls,
        timeline: Timeline,
        policy: ControlPolicy,
        slo_config=None,
        step_s: float = 0.5,
        capacity_eps: Optional[float] = None,
        n_workers: Optional[int] = None,
    ) -> List[Action]:
        """Pure function of (timeline, policy, slo spec, step): walk the
        dumped timeline's own clock exactly like ``SLOEngine.replay``
        (same step loop, same bounds clamp), feeding the point-in-time
        ``/signals`` payload at each step into a fresh controller. Worker
        count starts from the timeline's ``queue_depth.w*`` series count
        (override via ``n_workers``) and then follows the replayed scale
        actions — the simulated fleet the decisions would have produced.
        No process is spawned, no clock is read: same inputs, same
        actions, byte for byte."""
        if step_s <= 0:
            raise ValueError("replay step must be > 0")
        engine = (
            SLOEngine(slo_config, timeline)
            if slo_config is not None
            else None
        )
        ctl = cls(policy)
        bounds = timeline.bounds()
        if bounds is None:
            return []
        t0, t1 = bounds
        if n_workers is None:
            prefix = "queue_depth.w"
            n_workers = sum(
                1
                for name in timeline.names()
                if name.startswith(prefix)
                and name[len(prefix):].isdigit()
            ) or 1
        n = max(1, int(n_workers))
        out: List[Action] = []
        steps = int((t1 - t0) / step_s) + 1
        for i in range(steps + 1):
            now = min(t0 + i * step_s, t1)
            if engine is not None:
                engine.evaluate(now)
            sig = build_signals(
                timeline,
                engine=engine,
                capacity_eps=capacity_eps,
                now=now,
            )
            for a in ctl.decide(sig, now=now, n_workers=n):
                if a.kind in ("scale_out", "scale_in"):
                    n = int(a.target_workers)
                out.append(a)
            if now >= t1:
                break
        return out


class ControlLoop:
    """The actuation thread: sampler-shaped (``stop()`` idempotent, the
    gateway stops it with the samplers, BEFORE the workers — an
    actuation mid-close must never land on a stopping worker)."""

    def __init__(
        self,
        gateway,
        controller: Controller,
        period_s: float = 0.25,
        clock=time.monotonic,
    ):
        self.gateway = gateway
        self.controller = controller
        self.period_s = period_s
        self.clock = clock
        self.actions: List[Action] = []  # the live trail, arrival order
        self.errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ControlLoop":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="control-loop"
        )
        self._thread.start()
        return self

    def stop(self, join: bool = True, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and join:
            t.join(timeout=timeout)

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.step()
            except Exception:
                # A failed control tick must not kill the loop: the
                # fleet keeps serving on its current topology and the
                # failure is visible in counters.
                self.errors += 1
                self.gateway.metrics.inc("control_errors")

    def step(self, now: Optional[float] = None) -> List[Action]:
        """One control tick: read signals, decide, actuate, account."""
        gw = self.gateway
        if gw.timeline is None:
            return []
        if now is None:
            now = self.clock()
        sig = build_signals(
            gw.timeline,
            engine=gw.slo_engine,
            capacity_eps=gw.capacity_eps,
            combine=None,
            now=now,
        )
        n_live = len(gw.live_workers())
        actions = self.controller.step(
            sig, now=now, n_workers=n_live, metrics=gw.metrics,
            flight=gw.flight,
        )
        for a in actions:
            self._actuate(a)
        self.actions.extend(actions)
        if gw.timeline is not None:
            gw.timeline.record(
                "control.workers", now, float(len(gw.live_workers()))
            )
        return actions

    def _actuate(self, action: Action) -> None:
        gw = self.gateway
        if action.kind == "scale_out":
            gw.spawn_worker()
        elif action.kind == "scale_in":
            gw.retire_worker()
        elif action.kind == "degrade_on":
            gw.force_degrade(True)
        elif action.kind == "degrade_off":
            gw.force_degrade(False)
        elif action.kind == "spec_k":
            gw.set_spec_k(int(action.spec_k))

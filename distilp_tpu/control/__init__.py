"""Closed-loop fleet control: the layer that ACTS on ``/signals``.

PRs 10-15 built the measurement substrate (timeline, SLO burn rates,
the versioned signals payload, compile/memory ledgers); this package
spends it. ``ControlPolicy`` (declarative JSON: target bands +
hysteresis + cooldown) drives ``Controller.decide`` — a deterministic
function from signal sequence to typed ``Action`` sequence — and
``ControlLoop`` actuates those actions on a dynamic gateway: spawn or
retire process workers (the consistent-hash ring rebalance migrates
shards live, warm, zero cold ticks), flip forced-degrade admission,
adapt ``spec_k``. ``Controller.replay`` reproduces any live decision
trail offline from a dumped timeline — the purity ``make
smoke-autoscale`` pins.
"""

from .controller import ControlLoop, Controller
from .policy import Action, ControlPolicy, actions_to_jsonl

__all__ = [
    "Action",
    "ControlLoop",
    "ControlPolicy",
    "Controller",
    "actions_to_jsonl",
]

"""The declarative autoscaling policy + the typed action vocabulary.

Same spirit as the PR 13 alert ladder: a committed JSON document, not
code, decides when the fleet moves. The policy is target bands plus the
two stabilizers every production autoscaler needs — **hysteresis**
(scale-out trips on any one vote the moment it fires; scale-in needs
EVERY calm condition to hold for ``calm_hold_s`` straight) and a
**cooldown** (at most one scale action per ``scale_cooldown_s``, so a
burst cannot ping-pong the fleet). Actions are a closed, versioned
vocabulary: the flight trail, the offline replay fixture and the live
actuator all speak exactly these shapes, so a decision recorded live
can be diffed byte-for-byte against its offline reproduction.
"""

from __future__ import annotations

import json
from typing import List, Literal, Optional

from pydantic import BaseModel, ConfigDict, Field


class Action(BaseModel):
    """One controller decision, exactly as flight-recorded.

    kind            lever
    --------------  -----------------------------------------------
    ``scale_out``   spawn one worker; ring rebalance migrates shards
    ``scale_in``    retire one worker; its slices migrate off first
    ``degrade_on``  force PRESSURE serving (spec_near admission)
    ``degrade_off`` restore the static admission verdict
    ``spec_k``      set the speculation bank width on every shard
    """

    model_config = ConfigDict(extra="forbid")

    version: Literal[1] = 1
    t: float
    kind: Literal[
        "scale_out", "scale_in", "degrade_on", "degrade_off", "spec_k"
    ]
    target_workers: Optional[int] = None
    spec_k: Optional[int] = None
    reason: str


class ControlPolicy(BaseModel):
    """Target bands + hysteresis + cooldown, committed as JSON."""

    model_config = ConfigDict(extra="forbid")

    version: Literal[1] = 1
    min_workers: int = Field(1, ge=1)
    max_workers: int = Field(4, ge=1)
    # At most one scale action (either direction) per cooldown window.
    scale_cooldown_s: float = Field(10.0, ge=0.0)

    # -- scale-out votes: ANY one trips (subject to cooldown/max) --------
    # A page-severity SLO alert is the loudest vote.
    scale_out_on_page: bool = True
    # headroom_eps below this fraction of capacity (needs the capacity
    # probe — satellite: auto-populated post-warmup when unset).
    headroom_min_frac: Optional[float] = 0.10
    # Mean queue depth per worker at or above this trips.
    depth_high_per_worker: Optional[float] = 8.0
    # Any worker's depth trend (slope, units/s) at or above this trips.
    trend_up_per_s: Optional[float] = None
    # A NEWLY quarantined worker (crash-loop breaker opened; the signals
    # payload's ``recovery`` block, supervised process tier only) votes
    # scale-out: the ring just lost a slice for good, and respawn cannot
    # win it back. Inert when signals carry no recovery block, so
    # committed replay fixtures from unsupervised captures are unchanged.
    scale_out_on_quarantine: bool = True

    # -- scale-in: ALL calm conditions, sustained --------------------------
    calm_hold_s: float = Field(15.0, ge=0.0)
    depth_low_total: float = 1.0
    headroom_scale_in_frac: float = 0.50

    # -- admission degrade lever ------------------------------------------
    # Instant, reversible: force spec_near serving while a page is open
    # (scale-out takes effect over seconds; degrade takes effect now).
    degrade_on_page: bool = True

    # -- spec_k memory lever ----------------------------------------------
    # When mem_headroom_bytes drops below the floor, shrink the
    # speculation bank to this width; restore when headroom recovers.
    mem_low_bytes: Optional[float] = None
    spec_k_low: int = Field(1, ge=0)
    spec_k_normal: Optional[int] = None

    @classmethod
    def from_json(cls, path) -> "ControlPolicy":
        with open(path) as fh:
            return cls.model_validate(json.load(fh))


def actions_to_jsonl(actions: List[Action]) -> str:
    """One action per line, key-sorted — byte-stable for a given decision
    sequence, so the committed fixture pins ``Controller.replay``
    regeneration exactly (the ``slo_expected_alerts`` convention)."""
    return "".join(
        json.dumps(a.model_dump(), sort_keys=True) + "\n" for a in actions
    )

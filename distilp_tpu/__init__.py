"""distilp_tpu: TPU-native heterogeneous LLM placement framework.

Capabilities (matching and extending firstbatchxyz/distilp):

- ``distilp_tpu.common``   — profile schemas (the JSON contract).
- ``distilp_tpu.solver``   — HALDA layer/GPU-offload assignment: CPU (scipy/HiGHS)
  oracle backend plus a JAX backend where the per-k LP relaxations run as a
  vmapped interior-point kernel and branch-and-bound is batched on device.
- ``distilp_tpu.profiler`` — device microbenchmarks (JAX) and analytic model
  profiling straight from HF ``config.json`` metadata (no Metal/MLX needed).
- ``distilp_tpu.parallel`` — device-mesh utilities and the ICI/DCN
  communication cost model.
- ``distilp_tpu.sched``    — the solver run as a long-lived scheduler service:
  churn events in, certified placements out, warm solver state pooled
  across replans (see ``sched.Scheduler`` and ``solver serve --trace``).
"""

__version__ = "0.1.0"

from .common import (
    DeviceProfile,
    ModelProfile,
    ModelProfilePhased,
    ModelProfileSplit,
    ModelPhase,
    QuantizationLevel,
)

__all__ = [
    "DeviceProfile",
    "ModelProfile",
    "ModelProfilePhased",
    "ModelProfileSplit",
    "ModelPhase",
    "QuantizationLevel",
    "__version__",
]

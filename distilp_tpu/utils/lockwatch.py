"""Runtime lock sanitizer: the dynamic half of dlint's DLP032.

``make_lock(name, kind)`` is the one factory the gateway/sched/obs stack
uses for every lock that participates in cross-thread protocols. With
``DLP_LOCKWATCH`` unset (the default, and the production path) it returns
the plain ``threading`` primitive — zero wrappers, zero overhead. With
``DLP_LOCKWATCH=1`` it returns an instrumented wrapper that records, per
thread, the stack of held locks and every *acquisition-order edge* ("B
acquired while A held"), and checks each new edge against the
already-observed graph: a new edge that closes a cycle is a lock-order
violation witness — the exact interleaving dlint's static DLP032 rule
predicts deadlocks from, caught in a real execution.

The observed graph is the runtime's answer to the static one:
``python -m tools.dlint --check-lockwatch out.json`` asserts that every
observed edge appears in the static acquisition graph (the analyzer saw
every real nesting) and that zero cycle witnesses fired. The smoke
target ``make smoke-lockwatch`` runs the gateway overload drill under
the sanitizer and applies exactly that check.

Names are type-granular (every ``LatencyHist`` shares ``metrics.hist``),
matching the static graph's node identity, so the two compare edge for
edge. The cost of that choice: a cycle witness between two *instances*
of one class is indistinguishable from a self-deadlock — same as the
static rule, which hedges the same way.

Env contract:

- ``DLP_LOCKWATCH=1``     — instrument locks created by ``make_lock``.
- ``DLP_LOCKWATCH_OUT``   — write the JSON report here at process exit.
- ``DLP_LOCKWATCH_DIR``   — dump cycle witnesses through the flight
  recorder (PR 8 post-mortem machinery) into this directory.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "make_lock",
    "enabled",
    "report",
    "reset",
    "WatchedLock",
    "WatchedCondition",
]


def enabled() -> bool:
    return os.environ.get("DLP_LOCKWATCH") == "1"


class _PerThread(threading.local):
    def __init__(self):
        self.held: List[str] = []   # acquisition order, innermost last
        self.in_hook: bool = False  # reentrancy guard: the witness dump
        #                             path may itself take watched locks


_tls = _PerThread()


class _Graph:
    """The process-wide observed graph. Its own mutex is a RAW
    threading.Lock — never watched, never part of any recorded edge."""

    def __init__(self):
        self.mu = threading.Lock()
        self.locks: Set[str] = set()
        self.edges: Dict[Tuple[str, str], int] = {}
        self.adj: Dict[str, Set[str]] = {}
        self.witnesses: List[dict] = []


_GRAPH = _Graph()
_MAX_WITNESSES = 64


def _find_path(adj: Dict[str, Set[str]], start: str, goal: str) -> Optional[List[str]]:
    """A path start -> ... -> goal in the observed graph (DFS), or None."""
    stack = [(start, [start])]
    seen = {start}
    while stack:
        node, path = stack.pop()
        for nxt in sorted(adj.get(node, ())):
            if nxt == goal:
                return path + [goal]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquire(name: str) -> None:
    """Record edges held -> name, then push name onto this thread's held
    stack. Bookkeeping (not the lock itself) is skipped while the witness
    dump path runs — its own lock acquisitions must not recurse here."""
    if not _tls.in_hook:
        _tls.in_hook = True
        try:
            witness = None
            with _GRAPH.mu:
                _GRAPH.locks.add(name)
                for h in _tls.held:
                    if h == name:
                        continue
                    edge = (h, name)
                    count = _GRAPH.edges.get(edge, 0)
                    _GRAPH.edges[edge] = count + 1
                    if count == 0:
                        # New edge: does name already reach h? Then
                        # h -> name closes a cycle.
                        back = _find_path(_GRAPH.adj, name, h)
                        _GRAPH.adj.setdefault(h, set()).add(name)
                        if back is not None and len(_GRAPH.witnesses) < _MAX_WITNESSES:
                            witness = {
                                "kind": "lock-order-cycle",
                                "edge": [h, name],
                                "cycle": [h] + back,
                                "held": list(_tls.held),
                                "thread": threading.current_thread().name,
                            }
                            _GRAPH.witnesses.append(witness)
            if witness is not None:
                _dump_witness(witness)
        finally:
            _tls.in_hook = False
    _tls.held.append(name)


def _note_release(name: str) -> None:
    held = _tls.held
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


_FLIGHT = None


def _dump_witness(witness: dict) -> None:
    """Ship a cycle witness through the flight recorder (post-mortem
    rings + on-disk dump when ``DLP_LOCKWATCH_DIR`` is set). Runs with
    the reentrancy guard up: any watched lock the recorder takes is left
    out of the observed graph."""
    global _FLIGHT
    try:
        from ..obs.flight import FlightRecorder  # lazy: avoid import cycle

        if _FLIGHT is None:
            _FLIGHT = FlightRecorder(
                capacity=_MAX_WITNESSES,
                dump_dir=os.environ.get("DLP_LOCKWATCH_DIR") or None,
            )
        _FLIGHT.record("lockwatch", witness)
        _FLIGHT.trigger("lockwatch", "lock-order-cycle", witness)
    except Exception:
        pass  # the sanitizer must never take the process down


class WatchedLock:
    """Instrumented Lock/RLock: delegates to the real primitive, records
    held-set and acquisition-order edges around it."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, *args, **kwargs) -> bool:
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            _note_acquire(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _note_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r} over {self._inner!r}>"


class WatchedCondition(WatchedLock):
    """Instrumented Condition. ``wait`` RELEASES the underlying lock, so
    the held stack pops for the duration and re-pushes on wakeup — a
    nested acquisition during someone else's wait must not look like an
    ordering edge through this condition."""

    def wait(self, timeout: Optional[float] = None):
        _note_release(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            _note_acquire(self.name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _note_release(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _note_acquire(self.name)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


_KINDS = {
    "lock": threading.Lock,
    "rlock": threading.RLock,
    "condition": threading.Condition,
}


def make_lock(name: str, kind: str = "lock"):
    """THE lock factory for cross-thread protocols.

    ``name`` is the lock's node id in both the static (dlint DLP032) and
    observed (this module) acquisition graphs — dlint reads the literal
    out of the call site, so it must be a string literal. Returns the
    plain ``threading`` primitive unless ``DLP_LOCKWATCH=1``.
    """
    inner = _KINDS[kind]()
    if not enabled():
        return inner
    if kind == "condition":
        return WatchedCondition(name, inner)
    return WatchedLock(name, inner)


def report() -> dict:
    """The observed graph as a JSON-able dict (what
    ``DLP_LOCKWATCH_OUT`` receives at exit, and what
    ``python -m tools.dlint --check-lockwatch`` validates)."""
    with _GRAPH.mu:
        return {
            "enabled": enabled(),
            "locks": sorted(_GRAPH.locks),
            "edges": [
                {"from": a, "to": b, "count": c}
                for (a, b), c in sorted(_GRAPH.edges.items())
            ],
            "witnesses": list(_GRAPH.witnesses),
        }


def reset() -> None:
    """Clear the observed graph (test isolation)."""
    with _GRAPH.mu:
        _GRAPH.locks.clear()
        _GRAPH.edges.clear()
        _GRAPH.adj.clear()
        _GRAPH.witnesses.clear()


@atexit.register
def _write_report_at_exit() -> None:
    out = os.environ.get("DLP_LOCKWATCH_OUT")
    if not out or not enabled():
        return
    try:
        with open(out, "w") as fh:
            json.dump(report(), fh, indent=2, sort_keys=True)
            fh.write("\n")
    except OSError:
        pass

"""Utilities: synthetic fleets, lock instrumentation, logging/timing helpers."""

__all__ = ["make_synthetic_fleet", "stretch_model_for_fleet", "make_lock"]


def __getattr__(name):
    # PEP 562 lazy exports: synthetic pulls in numpy, and the gateway's
    # `from ..utils.lockwatch import make_lock` must not pay for it (the
    # serving path imports this package long before any fleet synthesis).
    if name in ("make_synthetic_fleet", "stretch_model_for_fleet"):
        from . import synthetic

        return getattr(synthetic, name)
    if name == "make_lock":
        from .lockwatch import make_lock

        return make_lock
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

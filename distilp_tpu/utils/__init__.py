"""Utilities: synthetic fleets, logging/timing helpers."""

from .synthetic import make_synthetic_fleet, stretch_model_for_fleet

__all__ = ["make_synthetic_fleet", "stretch_model_for_fleet"]

"""Utilities: synthetic fleets, logging/timing helpers."""

from .synthetic import make_synthetic_fleet

__all__ = ["make_synthetic_fleet"]

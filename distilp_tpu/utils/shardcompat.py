"""Capability shim for ``shard_map`` across the jax versions this repo meets.

This image ships jax 0.4.37, where ``jax.shard_map`` does not exist — the
module-level ``__getattr__`` raises AttributeError; the API was promoted
out of ``jax.experimental.shard_map`` only in later releases — and the
experimental signature spells the replication-check knob ``check_rep``
where the promoted API spells it ``check_vma``. Every mesh-partitioned
program in this repo (the sharded PDHG engine in ``ops/meshlp.py``, the
profiler's interconnect collectives in ``profiler/topology.py``) resolves
``shard_map`` through this module instead of touching either spelling
directly, so the call sites read like current jax and keep working
unchanged when the environment upgrades.

Also centralized here: the small mesh bookkeeping every caller repeats —
a 1-D mesh over the first N local devices, replicated/sharded
``NamedSharding`` helpers, and the CPU-mesh recipe for tests and bench
runs (``--xla_force_host_platform_device_count``, which must land in
``XLA_FLAGS`` *before* the backend initializes — see ``host_device_hint``).

Import cost: jax is imported lazily inside each function, so backend-free
layers (the CLI's argument parsing, dlint) can import this module without
initializing a backend.
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "have_shard_map",
    "shard_map",
    "shard_mesh",
    "named_sharding",
    "partition_spec",
    "host_device_hint",
    "force_host_devices",
]

# The XLA flag that splits one host backend into N virtual devices — the
# only way to exercise a real multi-device mesh on a CPU-only box. It is
# consumed at backend initialization, so it must be in the environment
# before the first jax device query (conftest.py sets it for the suite;
# the CLI sets it in main() before any backend import when --mesh-shards
# asks for more devices than one).
HOST_COUNT_FLAG = "--xla_force_host_platform_device_count"


def have_shard_map() -> bool:
    """True when SOME spelling of ``shard_map`` is importable — the
    capability the profiler's collective microbenchmarks (and their
    tests) actually need, as opposed to the ``jax.shard_map`` attribute
    check that pinned them to jax versions this image does not have."""
    try:
        _resolve()
        return True
    except Exception:
        return False


def _resolve():
    """The raw shard_map callable from whichever namespace has it."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map as fn  # type: ignore

    return fn


def shard_map(f, mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """``jax.shard_map`` with the current-jax signature, on any jax.

    ``check_vma`` (the promoted API's name; the experimental API calls it
    ``check_rep``) disables the output-replication proof — shard bodies
    whose replicated outputs come from psum'd values that the checker
    cannot prove replicated (e.g. an all-gather feeding a replicated
    out_spec) pass ``check_vma=False`` exactly as they would on a current
    jax, and the shim maps the kwarg to whatever this jax spells it.
    """
    import inspect

    fn = _resolve()
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if check_vma is not None:
        params = inspect.signature(fn).parameters
        if "check_vma" in params:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in params:
            kwargs["check_rep"] = check_vma
        # Neither spelling: the jax at hand dropped the knob; the call is
        # still correct, just unchecked/checked per its default.
    return fn(f, **kwargs)


def shard_mesh(n_shards: int, axis: str = "rows"):
    """1-D mesh over the first ``n_shards`` local devices.

    Raises with the CPU-mesh recipe when the backend has fewer devices —
    the one operational mistake everyone makes once (the flag must be set
    before the backend initializes, so a running process cannot fix it).
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_shards > len(devices):
        raise ValueError(
            f"mesh_shards={n_shards} but only {len(devices)} device(s) "
            f"visible; on a CPU host export "
            f"XLA_FLAGS='{HOST_COUNT_FLAG}={n_shards}' (or more) BEFORE "
            f"the first jax import — see {__name__}.force_host_devices"
        )
    return Mesh(np.array(devices[:n_shards]), (axis,))


def named_sharding(mesh, *axes):
    """``NamedSharding(mesh, P(*axes))`` — the one-liner every placement
    site repeats."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(*axes))


def partition_spec(*axes):
    from jax.sharding import PartitionSpec as P

    return P(*axes)


def host_device_hint(n: int) -> str:
    """The XLA_FLAGS value that makes ``n`` virtual host devices."""
    return f"{HOST_COUNT_FLAG}={n}"


def force_host_devices(n: int) -> bool:
    """Best-effort: append the host-device-count flag to ``XLA_FLAGS`` if
    no such flag is present yet. Returns True when the environment was
    changed. MUST run before the first backend touch to have any effect —
    callers that cannot guarantee that (a library user mid-process)
    should treat False-with-too-few-devices as a hard config error, which
    is what ``shard_mesh`` raises.
    """
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if HOST_COUNT_FLAG in flags:
        return False
    os.environ["XLA_FLAGS"] = (flags + " " + host_device_hint(n)).strip()
    return True

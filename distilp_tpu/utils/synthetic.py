"""Synthetic heterogeneous fleets for tests and benchmarks.

The golden fixtures top out at two devices; the north-star workloads
(BASELINE.md) are 16-32 device heterogeneous swarms. This generator produces
deterministic, plausible ``DeviceProfile`` fleets — a mix of Apple-silicon
laptops (mac_metal, unified memory), CUDA linux boxes and CPU-only
linux/android nodes — spanning roughly an order of magnitude in compute,
memory and disk speed.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..common import ALL_QUANT_LEVELS, DeviceProfile

# Relative throughput of each quant level vs F32 on typical hardware
# (coarse model: quantized kernels trade FLOPs for dequant work).
_QUANT_REL = {
    "Q4_K": 0.25,
    "Q5_K": 0.31,
    "Q6_K": 0.37,
    "Q8_0": 0.50,
    "F16": 1.15,
    "BF16": 1.15,
    "F32": 1.0,
}


def _throughput_table(f32_flops: float, batches=(1, 2, 4)) -> dict:
    return {
        q: {f"b_{b}": f32_flops * _QUANT_REL[q] * (1.0 + 0.02 * i) for i, b in enumerate(batches)}
        for q in ALL_QUANT_LEVELS
    }


def make_synthetic_fleet(
    M: int, seed: int = 0, pool_bytes: int = 0
) -> List[DeviceProfile]:
    """Deterministic heterogeneous fleet of M devices; device 0 is the head.

    ``pool_bytes > 0`` raises every memory pool (RAM and, where present,
    Metal/CUDA) to that capacity — MoE instances need fleets that can
    physically hold the resident expert set (expert residency is
    hard-capped; see ``solver.moe``).
    """
    rng = np.random.default_rng(seed)
    devices: List[DeviceProfile] = []
    kinds = ["mac_metal", "linux_cuda", "linux_cpu", "android"]
    for i in range(M):
        kind = kinds[i % len(kinds)]
        # Per-device scale factor: order-of-magnitude heterogeneity.
        scale = float(10 ** rng.uniform(-0.5, 0.5))
        cpu_f32 = 1.5e12 * scale
        ram = int(8e9 * scale)
        disk = 2.5e9 * scale
        t_comm = float(rng.uniform(0.02, 0.09))

        common = dict(
            name=f"synth-{kind}-{i}",
            is_head=(i == 0),
            scpu=_throughput_table(cpu_f32),
            T_cpu=4.5e10 * scale,
            t_kvcpy_cpu=5e-8,
            t_kvcpy_gpu=5e-8,
            t_comm=t_comm,
            s_disk=disk,
            d_avail_ram=ram,
            c_cpu=0,
            c_gpu=0,
        )
        if kind == "mac_metal":
            dev = DeviceProfile(
                os_type="mac_metal",
                is_unified_mem=True,
                has_metal=True,
                sgpu_metal=_throughput_table(2.6e12 * scale),
                T_metal=2.1e11 * scale,
                d_avail_metal=ram,
                **common,
            )
        elif kind == "linux_cuda":
            dev = DeviceProfile(
                os_type="linux",
                has_cuda=True,
                sgpu_cuda=_throughput_table(9e12 * scale),
                T_cuda=6e11 * scale,
                d_avail_cuda=int(1.2e10 * scale),
                t_ram2vram=2e-4,
                t_vram2ram=2e-4,
                **common,
            )
        elif kind == "android":
            dev = DeviceProfile(
                os_type="android",
                d_bytes_can_swap=2 << 30,
                d_swap_avail=1 << 30,
                **common,
            )
        else:
            dev = DeviceProfile(os_type="linux", **common)
        devices.append(dev)
    if pool_bytes > 0:
        for d in devices:
            d.d_avail_ram = int(pool_bytes)
            if d.d_avail_metal is not None:
                d.d_avail_metal = int(pool_bytes)
            if d.d_avail_cuda is not None:
                d.d_avail_cuda = int(pool_bytes)
    return devices


def stretch_model_for_fleet(model, M: int):
    """Fleet-scale synthetic instance from a profiled model: stretch the
    typical-layer scalars to ``L = 2·M`` layers. HALDA places every device
    (``w_i >= 1``), so an M-device instance needs a model at least as deep
    as the fleet; 2M keeps two k candidates feasible so the sweep still
    searches. Per-layer columns are dropped — the typical-layer scalars
    price every stretched layer. The ONE recipe shared by bench.py's
    ``fleet_scale`` section and the walkthrough's fleet-scale step, so the
    two always measure the same instance family."""
    return model.model_copy(update=dict(
        L=2 * M, b_layers=None, b_i_layers=None, b_o_layers=None,
        f_q_layers=None,
    ))

"""Gateway: the horizontally scalable multi-fleet serving tier.

``distilp_tpu.sched`` turned the solver into ONE fleet's long-lived
daemon; this package turns that daemon into infrastructure that serves
MANY fleets at once (ROADMAP open item 2):

- ``router``   — consistent-hash shard ownership: each (fleet, model)
  shard belongs to exactly one solve worker, deterministically, with
  ~1/N churn when the worker count changes;
- ``worker``   — the solve worker: one thread, N shards, every shard's
  ``Scheduler`` run unchanged (so PR 5's quarantine/deadline/breaker/
  HealthState machinery applies per shard, isolated);
- ``gateway``  — the tier itself: sync + asyncio ingest, per-shard
  routing, aggregated health/metrics, drain + warm snapshot;
- ``snapshot`` — ``GatewaySnapshot``: every shard's warm state (fleet,
  incumbents, duals, IPM/PDHG iterates, margin anchors, health) as one
  JSON file; restore resumes with warm ticks, zero cold re-solves;
- ``http``     — minimal stdlib HTTP/1.1 JSON API (POST /events,
  GET /placement/<fleet>, /healthz, /metrics);
- ``traces``   — fleet-tagged JSONL traces (the multi-fleet replay
  format) and deterministic synthetic-fleet specs;
- ``loadgen``  — the throughput harness behind ``bench.py``'s gateway
  section (K fleets × N workers, events/sec + latency quantiles);
- ``procworker`` — process-backed workers: the same ShardWorker
  contract with the schedulers hosted in a dedicated subprocess (own
  GIL, own XLA runtime) behind a length-prefixed unix-socket RPC — the
  backend the closed-loop autoscaler (``distilp_tpu.control``) spawns
  and retires, migrating shards live and warm.

Stdlib + the existing solver stack only — no new dependencies.
"""

from .gateway import (
    FleetReadView,
    Gateway,
    QueueFull,
    ShardFacade,
    view_to_dict,
)
from .http import GatewayHTTPServer
from .loadgen import run_loadgen
from .router import ConsistentHashRouter, shard_key
from .snapshot import (
    GatewaySnapshot,
    ShardSnapshot,
    load_snapshot,
    save_snapshot,
    snapshot_path,
)
from .traces import (
    is_gateway_trace,
    make_fleet_from_spec,
    read_gateway_trace,
    write_gateway_trace,
)
from .worker import ShardWorker, WorkerQueueFull


def __getattr__(name):
    # Lazy on purpose: the worker CHILD process runs `python -m
    # distilp_tpu.gateway.procworker`, which imports this package first;
    # an eager `from .procworker import …` here would double-import the
    # child's own entry module (runpy's sys.modules warning).
    if name in ("ProcShardWorker", "SchedulerProxy"):
        from . import procworker

        return getattr(procworker, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "Gateway",
    "QueueFull",
    "FleetReadView",
    "WorkerQueueFull",
    "ShardFacade",
    "view_to_dict",
    "GatewayHTTPServer",
    "run_loadgen",
    "ConsistentHashRouter",
    "shard_key",
    "GatewaySnapshot",
    "ShardSnapshot",
    "load_snapshot",
    "save_snapshot",
    "snapshot_path",
    "is_gateway_trace",
    "make_fleet_from_spec",
    "read_gateway_trace",
    "write_gateway_trace",
    "ShardWorker",
    "ProcShardWorker",
    "SchedulerProxy",
]

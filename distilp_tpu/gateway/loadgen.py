"""Load generator: K synthetic fleets replayed through N solve workers.

The gateway exists to keep many fleets' replanning concurrent; this
module measures exactly that. ``run_loadgen`` builds K deterministic
synthetic fleets (one shard each), generates a seeded drift trace per
fleet, warms every shard (first event = cold solve + jit compile,
excluded from the steady-state numbers, same convention as the
single-fleet scheduler bench), then replays the remaining events with
every fleet's stream concurrent — per-fleet order preserved (shard
serialization), cross-fleet parallelism bounded only by the workers.

Reported: sustained ``events_per_sec`` over the timed phase, p50/p99
event→placement latency (queue wait INCLUDED — it is what a client
sees), per-worker event counts, and failure/certification tallies.

This harness is CLOSED-loop by construction: each fleet's next event
waits for the previous placement, so offered load can never exceed
capacity and the numbers here are throughput at-or-below saturation.
The OPEN-loop side — timestamped arrival schedules fired regardless of
completion, against the gateway's admission control — lives in
``distilp_tpu.traffic`` (``execute_openloop`` reuses this module's
``replay_concurrent`` for its closed-loop capacity probe).
``bench.py``'s gateway section runs this at K ∈ {10, 100} through
1/2/4 workers and derives the scaling ratio; on a box with C cores the
honest ceiling is min(workers, C)×, so read the ratio next to the
machine, not in the abstract.

Runnable directly:

    python -m distilp_tpu.gateway.loadgen --fleets 10 --workers 2 \
        --events 5 --profile tests/profiles/llama_3_70b/online
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..sched.metrics import _quantile
from ..sched.sim import generate_trace
from .gateway import Gateway
from .traces import make_fleet_from_spec


class PromScraper:
    """Background Prometheus-exposition scrape loop against one gateway.

    The bench's "observability on" arms run this as the realistic
    sidecar load: every period the full labeled exposition renders, its
    per-worker round trips queueing behind live solves exactly like an
    external scraper hitting ``GET /metrics``.

    Lifecycle contract: ``stop()`` is idempotent and joins the thread,
    and the scraper registers itself with the gateway
    (``gateway.attach_sampler``), so ``Gateway.close()`` stops it BEFORE
    stopping the workers — a scrape can therefore never land on a
    stopping worker and count a ``prom_scrape_error`` on a clean
    shutdown (the PR 8 bench gotcha every harness used to re-learn,
    pinned by the close-during-scrape test in tests/test_obs.py).
    """

    def __init__(self, gateway: Gateway, period_s: float):
        if period_s <= 0:
            raise ValueError("scrape period must be > 0")
        self.gateway = gateway
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scrapes = 0
        gateway.attach_sampler(self)

    def start(self) -> "PromScraper":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="prom-scrape"
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.gateway.prometheus_text()
                self.scrapes += 1
            except Exception:
                # The scrape must never kill the arm; a failure is a
                # real observability signal, so it is counted.
                self.gateway.metrics.inc("prom_scrape_error")

    def stop(self, join: bool = True, timeout: float = 2.0) -> None:
        self._stop.set()
        thread = self._thread
        if join and thread is not None and thread.is_alive():
            thread.join(timeout=timeout)


def make_fleet_specs(
    n_fleets: int, fleet_size: int = 3, seed: int = 0
) -> Dict[str, dict]:
    """K deterministic synthetic-fleet specs (traces.py spec-line shape)."""
    return {
        f"f{i:03d}": {"m": fleet_size, "seed": seed * 1000 + i}
        for i in range(n_fleets)
    }


def make_loadgen_trace(
    specs: Dict[str, dict],
    events_per_fleet: int,
    seed: int = 0,
    scenario: str = "drift",
) -> List[Tuple[str, object]]:
    """Interleaved (fleet_id, event) items, round-robin across fleets.

    Drift-only by default: every post-warmup tick should ride the warm
    path, so the measured rate is the steady-state replanning rate, not a
    mixture with cold identity changes.
    """
    per_fleet: Dict[str, list] = {}
    for i, (fleet_id, spec) in enumerate(specs.items()):
        devices = make_fleet_from_spec(fleet_id, spec)
        per_fleet[fleet_id] = generate_trace(
            scenario, events_per_fleet, seed=seed * 7919 + i,
            base_fleet=devices,
        )
    items: List[Tuple[str, object]] = []
    for j in range(events_per_fleet):
        for fleet_id in specs:
            items.append((fleet_id, per_fleet[fleet_id][j]))
    return items


async def replay_concurrent(
    gateway: Gateway,
    items: Sequence[Tuple[str, object]],
    measure_from: Dict[str, int],
    on_timed_start=None,
) -> dict:
    """Replay items with one sequential task per fleet, all concurrent.

    ``measure_from[fleet]`` is the per-fleet index (0-based) of the first
    MEASURED event. The warmup prefix runs as its own concurrent phase
    with a barrier before the timed phase: cold solves AND the first warm
    tick's jit compile land entirely in warmup (a compile leaking into
    any arm's timed phase would make the first arm of a bench sweep look
    ~50x slower than the rest), and the reported wall clock covers only
    measured events.
    """
    per_fleet: Dict[str, list] = {}
    for fleet_id, ev in items:
        per_fleet.setdefault(fleet_id, []).append(ev)
    latencies: List[float] = []
    failures = {"tick_failed": 0, "uncertified": 0}

    async def _drive(fleet_id: str, events: list, record: bool) -> None:
        for ev in events:
            t0 = time.perf_counter()
            view = await gateway.handle_event_async(fleet_id, ev)
            ms = (time.perf_counter() - t0) * 1e3
            if record:
                latencies.append(ms)
                # getattr-tolerant: stub schedulers (process-worker test
                # factory) serve plain dicts, not PlacementViews.
                if getattr(view, "events_behind", 0) > 0:
                    failures["tick_failed"] += 1
                elif not getattr(
                    getattr(view, "result", None), "certified", True
                ):
                    failures["uncertified"] += 1

    split = {f: measure_from.get(f, 0) for f in per_fleet}
    await asyncio.gather(
        *(
            _drive(f, evs[: split[f]], record=False)
            for f, evs in per_fleet.items()
        )
    )
    if on_timed_start is not None:
        # The warmup barrier IS the cold/warm boundary: the compile
        # ledger's bench arm snapshots its event seq here, so compiles
        # after this callback are warm-phase compiles by construction.
        on_timed_start()
    t_start = time.perf_counter()
    await asyncio.gather(
        *(
            _drive(f, evs[split[f]:], record=True)
            for f, evs in per_fleet.items()
        )
    )
    wall_s = time.perf_counter() - t_start
    srt = sorted(latencies)
    return {
        "events": len(latencies),
        "wall_s": round(wall_s, 3),
        "events_per_sec": round(len(latencies) / wall_s, 2) if wall_s else 0.0,
        "p50_ms": round(_quantile(srt, 0.50), 3),
        "p99_ms": round(_quantile(srt, 0.99), 3),
        **failures,
    }


def run_loadgen(
    model,
    n_fleets: int,
    n_workers: int,
    events_per_fleet: int = 5,
    fleet_size: int = 3,
    seed: int = 0,
    # Two warmup events per fleet: the first pays the cold solve (+ the
    # cold layout's jit compile), the second the first warm tick (+ the
    # WARM layout's compile — a distinct program). Both must precede the
    # timed phase or the first arm of a sweep eats a compile bill the
    # later arms don't.
    warmup_per_fleet: int = 2,
    k_candidates: Optional[Sequence[int]] = None,
    mip_gap: float = 1e-3,
    kv_bits: str = "4bit",
    scenario: str = "drift",
    scheduler_kwargs: Optional[dict] = None,
    tracer=None,
    prom_scrape_s: Optional[float] = None,
    timeline_period_s: Optional[float] = None,
    compile_ledger: bool = False,
    memory_ledger: bool = False,
    worker_backend: str = "thread",
) -> dict:
    """One full loadgen arm: build fleets, replay, report, tear down.

    The same (n_fleets, seed, events) always produces the same trace set,
    so arms at different worker counts compare like for like — the bench's
    scaling ratio divides two runs of the IDENTICAL workload.

    ``tracer`` (an ``obs.Tracer``) instruments the whole arm;
    ``prom_scrape_s`` additionally runs a background thread rendering the
    Prometheus exposition at that period for the arm's duration — together
    they are the "observability on" arm of the bench's overhead gate (the
    scrape thread is a real scrape: its per-worker round trips queue
    behind live solves, exactly like a sidecar hitting ``/metrics``).
    """
    total_events = events_per_fleet + warmup_per_fleet
    specs = make_fleet_specs(n_fleets, fleet_size=fleet_size, seed=seed)
    items = make_loadgen_trace(specs, total_events, seed=seed, scenario=scenario)
    kwargs = {
        "mip_gap": mip_gap,
        "kv_bits": kv_bits,
        "backend": "jax",
        "k_candidates": list(k_candidates) if k_candidates else None,
    }
    kwargs.update(scheduler_kwargs or {})
    # Compile-ledger arm (bench `compile` section): reuse the process
    # ledger if one is already enabled, otherwise enable for this arm and
    # disable after — the interleaved ledger-OFF arms must run the true
    # passthrough path or the overhead measurement lies. Enabled BEFORE
    # the Gateway exists: process workers inherit the ledger decision at
    # spawn time (the child gets --compile-ledger only if the parent's
    # ledger is live when _make_worker runs).
    led = led_owned = None
    warm_tok: dict = {"seq": None}
    if compile_ledger:
        from ..obs import compile_ledger as _cl

        led = _cl.current()
        if led is None:
            led = led_owned = _cl.enable()
    # Memory-ledger arm (bench `memory` section): same reuse-or-own
    # contract as the compile ledger — the interleaved OFF arms must run
    # the true passthrough path or the overhead measurement lies.
    mled = mled_owned = None
    if memory_ledger:
        from ..obs import memory as _mem

        mled = _mem.current()
        if mled is None:
            mled = mled_owned = _mem.enable()
    gateway = Gateway(
        n_workers=n_workers,
        scheduler_kwargs=kwargs,
        tracer=tracer,
        worker_backend=worker_backend,
    )
    scraper = None
    if prom_scrape_s is not None:
        # Self-attaching: Gateway.close() stops it before the workers,
        # so the harness needs no stop-ordering knowledge of its own.
        scraper = PromScraper(gateway, prom_scrape_s)
    sampler = None
    if timeline_period_s is not None:
        # The bench's slo-overhead arm: a live timeline sampler at the
        # given cadence, each tick one metrics round trip per worker —
        # the cost the <= 5% gate measures. Attached, so close() stops it.
        from ..obs.timeline import Timeline, TimelineSampler

        sampler = gateway.attach_sampler(
            TimelineSampler(
                Timeline(),
                gateway.timeline_sample,
                period_s=timeline_period_s,
                metrics=gateway.metrics,
            )
        )
    try:
        for fleet_id, spec in specs.items():
            gateway.register_fleet(
                fleet_id, make_fleet_from_spec(fleet_id, spec), model
            )
        if scraper is not None:
            scraper.start()
        if sampler is not None:
            sampler.start()
        arm_tok = led.seq() if led is not None else 0
        # Per-CHILD warm baselines on the process backend: each worker
        # subprocess runs its own compile ledger, and the federation
        # bench's zero-recompile gate is per process, not per parent.
        proc_warm_base: Dict[int, Optional[int]] = {}

        def _on_timed_start() -> None:
            # The warmup barrier is BOTH ledgers' warm boundary: compile
            # events after it are warm-phase compiles, live-array growth
            # after it is a leak.
            if led is not None:
                warm_tok["seq"] = led.seq()
            if mled is not None:
                mled.mark_warm()
            if worker_backend == "process":
                for w in gateway.live_workers():
                    c = w.ledger_counters()
                    proc_warm_base[w.worker_id] = (
                        c.get("compiles", 0) if c else None
                    )

        measure_from = {f: warmup_per_fleet for f in specs}
        report = asyncio.run(
            replay_concurrent(
                gateway,
                items,
                measure_from,
                on_timed_start=(
                    None
                    if (
                        led is None
                        and mled is None
                        and worker_backend != "process"
                    )
                    else _on_timed_start
                ),
            )
        )
        snap = gateway.metrics_snapshot()
        report.update(
            {
                "fleets": n_fleets,
                "workers": n_workers,
                "worker_backend": worker_backend,
                "events_per_fleet": events_per_fleet,
                "warmup_per_fleet": warmup_per_fleet,
                "shard_totals": snap["shard_totals"],
                "worker_events": [
                    snap["counters"].get(f"worker_{i}_events", 0)
                    for i in range(n_workers)
                ],
            }
        )
        if worker_backend == "process":
            # Per-child compile view: total compiles and the timed-phase
            # delta against the warm baseline (None when the child runs
            # without a ledger).
            per_proc: Dict[str, dict] = {}
            for w in gateway.live_workers():
                c = w.ledger_counters()
                base = proc_warm_base.get(w.worker_id)
                total = c.get("compiles", 0) if c else None
                per_proc[f"w{w.worker_id}"] = {
                    "compiles": total,
                    "warm_phase_compiles": (
                        total - base
                        if total is not None and base is not None
                        else None
                    ),
                }
            report["proc_workers"] = per_proc
        if prom_scrape_s is not None:
            report["prom_scrape_errors"] = snap["counters"].get(
                "prom_scrape_error", 0
            )
        if sampler is not None:
            report["timeline_samples"] = snap["counters"].get(
                "timeline_samples", 0
            )
            report["timeline_sample_errors"] = snap["counters"].get(
                "timeline_sample_error", 0
            )
        if led is not None:
            # The arm's compile view, split at the warmup barrier: cold
            # compiles paid during warmup vs compiles during the TIMED
            # phase — the latter is the bench's zero-recompile headline.
            arm_events = led.events_since(arm_tok)
            boundary = warm_tok["seq"]
            warm_events = [
                e for e in arm_events
                if boundary is not None and e["seq"] > boundary
            ]
            report["compile"] = {
                "cold_compiles": len(arm_events) - len(warm_events),
                "warm_phase_compiles": len(warm_events),
                "cache_hits": sum(
                    1 for e in arm_events if e.get("cache") == "hit"
                ),
                "entries": sorted({e["entry"] for e in arm_events}),
                "unregistered": sorted(
                    {
                        e["entry"]
                        for e in arm_events
                        if e["entry"] == "(unregistered)"
                    }
                ),
                "warm_entries": sorted({e["entry"] for e in warm_events}),
            }
        if mled is not None:
            # One forced end-of-arm sample (the gateway is quiescent
            # here: every fleet's last event resolved), so the leak
            # verdict compares the warm baseline against the arm's true
            # final live bytes, not a stale mid-phase throttle hit.
            mled.sample(force=True)
            report["mem"] = {
                "leak": mled.leak_report(),
                "watermarks": mled.summary()["watermarks"],
                "entries_analyzed": sum(
                    1 for r in mled.analyses.values() if r.get("memory")
                ),
            }
        return report
    finally:
        # close() stops the attached scraper first, then the workers —
        # the ordering lives in Gateway.close now, not per harness.
        gateway.close()
        if led_owned is not None:
            from ..obs import compile_ledger as _cl

            _cl.disable()
        if mled_owned is not None:
            from ..obs import memory as _mem

            _mem.disable()


def main(argv=None) -> int:
    import argparse
    import json
    import sys
    from pathlib import Path

    from ..axon_guard import force_cpu_if_env_requested

    force_cpu_if_env_requested()

    p = argparse.ArgumentParser(
        prog="python -m distilp_tpu.gateway.loadgen",
        description="replay K synthetic fleets through N gateway workers "
        "and report sustained events/sec + latency quantiles",
    )
    p.add_argument("--fleets", type=int, default=10)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--events", type=int, default=5, help="measured events per fleet")
    p.add_argument("--fleet-size", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--profile", "-p", required=True, help="profile folder (model_profile.json)")
    p.add_argument("--k-candidates", default="8,10")
    p.add_argument("--mip-gap", type=float, default=1e-3)
    args = p.parse_args(argv)

    from ..common import load_model_profile

    folder = Path(args.profile)
    model_path = folder / "model_profile.json" if folder.is_dir() else folder
    if not model_path.is_file():
        print(f"error: no model profile at {model_path}", file=sys.stderr)
        return 2
    model = load_model_profile(model_path)
    ks = [int(x) for x in args.k_candidates.split(",") if x.strip()] or None
    report = run_loadgen(
        model,
        n_fleets=args.fleets,
        n_workers=args.workers,
        events_per_fleet=args.events,
        fleet_size=args.fleet_size,
        seed=args.seed,
        k_candidates=ks,
        mip_gap=args.mip_gap,
    )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

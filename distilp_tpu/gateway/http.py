"""Minimal HTTP/1.1 JSON API over the gateway (stdlib asyncio only).

Five routes:

    POST /events                 {"fleet": <id>, "event": {<sched.events>}}
                                 -> 200 {"view": {...}} after the shard
                                 ticks (the response IS the placement), OR
                                 429 + Retry-After when admission control
                                 sheds the event (bounded worker queue
                                 full; see README "Overload & admission
                                 control")
    GET  /placement/<fleet_id>   -> 200 {"view": {...}} (latest, no solve)
    GET  /healthz                -> 200/503 per-shard health + overall
    GET  /metrics                -> 200 gateway metrics snapshot (JSON), OR
                                 Prometheus v0.0.4 text when the client
                                 content-negotiates it (``Accept:
                                 text/plain`` or ``?format=prom``) — the
                                 labeled per-shard exposition
    GET  /debug/flight/<fleet>   -> 200 the fleet's live flight-recorder
                                 ring (404 unless serving with a recorder)
    GET  /slo                    -> 200 live SLO status: per-objective
                                 budget, per-window burn rates, open
                                 alerts (404 unless serving with --slo)
    GET  /signals                -> 200 the versioned autoscaling payload
                                 (obs.slo.SignalsPayload: per-worker
                                 queue depth + trend, burn rates,
                                 headroom vs max-sustainable-eps; 404
                                 unless a metrics timeline is attached —
                                 serve --slo or --timeline-dir; the
                                 burn-rate block needs --slo)

One connection = one request (``Connection: close``): the serving tier's
clients are schedulers and probes, not browsers, and the parser stays ~50
lines. The asyncio loop only ever PARSES and ROUTES — every blocking step
(shard ticks, worker round trips) happens on the shard workers' threads,
reached through ``handle_event_async``'s future bridge or the default
executor, so one slow fleet's solve never stalls another fleet's ingest.
That invariant is mechanically enforced: dlint DLP018 forbids blocking
calls inside ``async def`` bodies in this package.

Tracing: with a tracer on the gateway, every POST /events gets an
``http.request`` root span whose context rides into ``handle_event_async``
as the explicit parent — so a traced event's tree starts at HTTP parse,
not at ingest.
"""

from __future__ import annotations

import asyncio
import json
from math import ceil
from typing import Optional, Tuple

from ..obs.trace import now_ms
from .gateway import Gateway, QueueFull, view_to_dict
from .procworker import WorkerCrashed

_MAX_BODY = 8 * 1024 * 1024  # a DeviceJoin carries a full profile; 8 MB is generous
_MAX_HEADER_LINES = 64
_JSON = "application/json"
# The exposition content type the Prometheus scraper expects.
_PROM = "text/plain; version=0.0.4; charset=utf-8"


def _response(
    status: int,
    payload,
    content_type: str = _JSON,
    extra_headers: Optional[dict] = None,
) -> bytes:
    if isinstance(payload, (dict, list)):
        body = json.dumps(payload).encode()
    elif isinstance(payload, bytes):
        body = payload
    else:
        body = str(payload).encode()
    reason = {
        200: "OK", 400: "Bad Request", 404: "Not Found",
        405: "Method Not Allowed", 409: "Conflict", 413: "Payload Too Large",
        429: "Too Many Requests",
        500: "Internal Server Error", 503: "Service Unavailable",
    }.get(status, "OK")
    extras = "".join(
        f"{k}: {v}\r\n" for k, v in (extra_headers or {}).items()
    )
    head = (
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: {content_type}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"{extras}"
        f"Connection: close\r\n\r\n"
    )
    return head.encode() + body


class GatewayHTTPServer:
    """asyncio HTTP front end for one ``Gateway``."""

    def __init__(self, gateway: Gateway, host: str = "127.0.0.1", port: int = 0):
        self.gateway = gateway
        self.host = host
        self.port = port  # 0 = ephemeral; replaced by the bound port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- request handling --------------------------------------------------

    async def _handle_conn(self, reader, writer) -> None:
        ctype = _JSON
        headers = None
        try:
            status, payload, ctype = await self._dispatch(reader)
        except QueueFull as e:
            # Load shed at the admission gate (bounded worker queue full).
            # The 429 contract: a parseable integer Retry-After header
            # (RFC delta-seconds, ceiling of the gateway's drain
            # estimate) plus the precise float in the JSON body. The shed
            # itself was already counted and flight-recorded inside the
            # gateway before the exception reached this tier.
            self.gateway.metrics.inc("http_too_many_requests")
            status, payload = 429, {
                "error": str(e),
                "fleet": e.fleet_id,
                "depth": e.depth,
                "retry_after_s": e.retry_after_s,
            }
            headers = {"Retry-After": str(max(1, ceil(e.retry_after_s)))}
        except (EOFError, ConnectionError) as e:
            # IncompleteReadError (an EOFError) = the client closed before
            # its advertised body arrived: a client fault, not a server
            # one — it must not inflate the internal-error signal.
            self.gateway.metrics.inc("http_client_gone")
            status, payload = 400, {"error": f"{type(e).__name__}: {e}"}
        except (ValueError, json.JSONDecodeError) as e:
            self.gateway.metrics.inc("http_bad_request")
            status, payload = 400, {"error": f"{type(e).__name__}: {e}"}
        except (KeyError, FileNotFoundError) as e:
            self.gateway.metrics.inc("http_not_found")
            status, payload = 404, {"error": str(e)}
        except WorkerCrashed as e:
            # A child died under this request and the supervised retry
            # budget (read-only RPCs retry once against the respawn;
            # mutating calls never retry) is spent. 503, not 500: the
            # gateway itself is fine, the shard is mid-recovery — the
            # client should back off and retry.
            self.gateway.metrics.inc("http_worker_crashed")
            status, payload = 503, {
                "error": str(e),
                "worker": e.worker_id,
                "op": e.op,
            }
            headers = {"Retry-After": "1"}
        except RuntimeError as e:
            # e.g. "no placement published yet" — the shard exists but has
            # nothing servable; a retriable condition, not a client error.
            self.gateway.metrics.inc("http_conflict")
            status, payload = 409, {"error": str(e)}
        except Exception as e:
            self.gateway.metrics.inc("http_internal_error")
            status, payload = 500, {"error": f"{type(e).__name__}: {e}"}
        try:
            writer.write(_response(status, payload, ctype, headers))
            await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            self.gateway.metrics.inc("http_client_gone")
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                self.gateway.metrics.inc("http_client_gone")

    async def _read_request(self, reader) -> Tuple[str, str, bytes, str]:
        request_line = (await reader.readline()).decode("latin-1").strip()
        if not request_line:
            raise ValueError("empty request")
        parts = request_line.split()
        if len(parts) != 3:
            raise ValueError(f"malformed request line {request_line!r}")
        method, target, _version = parts
        content_length = 0
        accept = ""
        for _ in range(_MAX_HEADER_LINES):
            line = (await reader.readline()).decode("latin-1")
            if line in ("\r\n", "\n", ""):
                break
            name, _, value = line.partition(":")
            name = name.strip().lower()
            if name == "content-length":
                content_length = int(value.strip())
            elif name == "accept":
                accept = value.strip().lower()
        else:
            raise ValueError("too many header lines")
        if content_length > _MAX_BODY:
            raise ValueError(f"body exceeds {_MAX_BODY} bytes")
        body = (
            await reader.readexactly(content_length) if content_length else b""
        )
        return method, target, body, accept

    async def _dispatch(self, reader) -> Tuple[int, object, str]:
        t_req = now_ms()  # request arrival: the http.request span starts HERE
        method, target, body, accept = await self._read_request(reader)
        loop = asyncio.get_running_loop()
        path, _, query = target.partition("?")
        if method == "POST" and path == "/events":
            data = json.loads(body or b"{}")
            fleet_id = data.get("fleet")
            if not fleet_id:
                raise ValueError("POST /events needs a 'fleet' field")
            if "event" not in data:
                raise ValueError("POST /events needs an 'event' object")
            from ..sched.events import event_from_dict

            event = event_from_dict(data["event"])
            # The trace root for an HTTP-ingested event: parse+route+wait
            # +tick all under one request span (explicit parent — the loop
            # thread is shared, ambient context would cross coroutines).
            # Backdated to request arrival so header/body reads and the
            # JSON/event parse — which all happened above — are INSIDE the
            # span: "HTTP parse?" is one of the questions a trace answers.
            span = self.gateway.tracer.start_span(
                "http.request", parent=None,
                attrs={"method": method, "target": path, "fleet": fleet_id},
            )
            if self.gateway.tracer.enabled:
                span.t0_ms = t_req  # the shared NOOP span has no slots
            try:
                view = await self.gateway.handle_event_async(
                    fleet_id, event, parent=span.context()
                )
            finally:
                span.end()
            return 200, {"fleet": fleet_id, "view": view_to_dict(view)}, _JSON
        if method == "GET" and path.startswith("/placement/"):
            fleet_id = path[len("/placement/"):]
            # latest() blocks on a worker round trip; off the loop thread.
            view = await loop.run_in_executor(
                None, self.gateway.latest, fleet_id
            )
            return 200, {"fleet": fleet_id, "view": view_to_dict(view)}, _JSON
        if method == "GET" and path == "/healthz":
            health = await loop.run_in_executor(None, self.gateway.healthz)
            return (503 if health["status"] == "broken" else 200), health, _JSON
        if method == "GET" and path == "/metrics":
            # Content negotiation: Prometheus scrapers say `Accept:
            # text/plain` (or force it with ?format=prom) and get the
            # labeled v0.0.4 text exposition; everyone else keeps the
            # JSON snapshot — the pre-obs default, byte-compatible.
            if "format=prom" in query or "text/plain" in accept:
                text = await loop.run_in_executor(
                    None, self.gateway.prometheus_text
                )
                return 200, text, _PROM
            snap = await loop.run_in_executor(
                None, self.gateway.metrics_snapshot
            )
            return 200, snap, _JSON
        if method == "GET" and path == "/slo":
            status = await loop.run_in_executor(None, self.gateway.slo_status)
            return 200, status, _JSON
        if method == "GET" and path == "/signals":
            signals = await loop.run_in_executor(None, self.gateway.signals)
            return 200, signals, _JSON
        if method == "GET" and path == "/control":
            status = await loop.run_in_executor(
                None, self.gateway.control_status
            )
            return 200, status, _JSON
        if method == "GET" and path.startswith("/debug/flight/"):
            fleet_id = path[len("/debug/flight/"):]
            records = await loop.run_in_executor(
                None, self.gateway.flight_snapshot, fleet_id
            )
            return 200, {"fleet": fleet_id, "records": records}, _JSON
        if method not in ("GET", "POST"):
            return 405, {"error": f"method {method} not supported"}, _JSON
        return 404, {"error": f"no route for {method} {target}"}, _JSON

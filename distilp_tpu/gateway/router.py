"""Consistent-hash shard routing: (fleet, model) -> exactly one worker.

Every shard must be owned by exactly one solve worker — two workers
ticking the same shard would interleave warm-state writes — and the
mapping must be stable under reconfiguration: a snapshot taken under 2
workers restored under 4 should move as few shards as possible (a moved
shard keeps its warm state — it rides the snapshot blob — but loses its
jit cache locality). A classic consistent-hash ring over virtual nodes
gives both: deterministic ownership (pure function of the shard key and
the worker count — a restored gateway recomputes the same routing), and
~1/N churn when N changes.

No coordination, no clock, no randomness: the ring is SHA-1 positions of
``worker:<i>#<v>`` labels, so two processes with the same worker count
route identically — which is what lets the load generator and the serve
CLI reason about per-worker load without talking to each other.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple


def _ring_hash(label: str) -> int:
    """64-bit ring position (SHA-1 prefix; stable across processes —
    Python's builtin ``hash`` is salted per process and would not be)."""
    return int.from_bytes(hashlib.sha1(label.encode()).digest()[:8], "big")


class ConsistentHashRouter:
    """Stable shard->worker assignment over a virtual-node hash ring.

    ``worker_ids`` generalizes the ring to a sparse id set for the
    autoscaler: retiring worker 1 of {0, 1, 2} leaves ids {0, 2} on the
    ring, and only worker 1's slices move (~1/N churn, same property as
    growing N). When ``worker_ids`` is exactly ``range(n_workers)`` the
    ring is label-for-label identical to the fixed-count form, so
    snapshot/restore routing semantics are unchanged.
    """

    def __init__(
        self,
        n_workers: Optional[int] = None,
        replicas: int = 64,
        worker_ids: Optional[Sequence[int]] = None,
    ):
        if worker_ids is None:
            if n_workers is None:
                raise ValueError("router needs n_workers or worker_ids")
            worker_ids = range(n_workers)
        ids = sorted(set(int(w) for w in worker_ids))
        if len(ids) < 1:
            raise ValueError("router needs at least one worker")
        if replicas < 1:
            raise ValueError("router needs at least one virtual node")
        self.worker_ids = ids
        self.n_workers = len(ids)
        self.replicas = replicas
        ring: List[Tuple[int, int]] = []
        for w in ids:
            for v in range(replicas):
                ring.append((_ring_hash(f"worker:{w}#{v}"), w))
        ring.sort()
        self._ring = ring
        self._points = [h for h, _ in ring]

    def owner(self, shard_key: str) -> int:
        """Worker index owning this shard (first ring point clockwise)."""
        h = _ring_hash(shard_key)
        idx = bisect.bisect_right(self._points, h)
        if idx == len(self._ring):
            idx = 0
        return self._ring[idx][1]

    def assignments(self, shard_keys: Sequence[str]) -> Dict[str, int]:
        return {k: self.owner(k) for k in shard_keys}

    def load(self, shard_keys: Sequence[str]) -> List[int]:
        """Shards per worker — the balance gauge the bench reports.

        Positionally aligned with ``worker_ids`` (identical to the old
        index-aligned list when ids are dense from zero).
        """
        slot = {w: i for i, w in enumerate(self.worker_ids)}
        counts = [0] * self.n_workers
        for k in shard_keys:
            counts[slot[self.owner(k)]] += 1
        return counts


def shard_key(fleet_id: str, model_id: str = "default") -> str:
    """The canonical shard name. '/' is reserved for the HTTP path split."""
    if not fleet_id or "/" in fleet_id or "/" in model_id:
        raise ValueError(
            f"fleet/model ids must be non-empty and '/'-free "
            f"(got fleet={fleet_id!r}, model={model_id!r})"
        )
    return f"{fleet_id}::{model_id}"

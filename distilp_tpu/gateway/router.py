"""Consistent-hash shard routing: (fleet, model) -> exactly one worker.

Every shard must be owned by exactly one solve worker — two workers
ticking the same shard would interleave warm-state writes — and the
mapping must be stable under reconfiguration: a snapshot taken under 2
workers restored under 4 should move as few shards as possible (a moved
shard keeps its warm state — it rides the snapshot blob — but loses its
jit cache locality). A classic consistent-hash ring over virtual nodes
gives both: deterministic ownership (pure function of the shard key and
the worker count — a restored gateway recomputes the same routing), and
~1/N churn when N changes.

No coordination, no clock, no randomness: the ring is SHA-1 positions of
``worker:<i>#<v>`` labels, so two processes with the same worker count
route identically — which is what lets the load generator and the serve
CLI reason about per-worker load without talking to each other.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Sequence, Tuple


def _ring_hash(label: str) -> int:
    """64-bit ring position (SHA-1 prefix; stable across processes —
    Python's builtin ``hash`` is salted per process and would not be)."""
    return int.from_bytes(hashlib.sha1(label.encode()).digest()[:8], "big")


class ConsistentHashRouter:
    """Stable shard->worker assignment over a virtual-node hash ring."""

    def __init__(self, n_workers: int, replicas: int = 64):
        if n_workers < 1:
            raise ValueError("router needs at least one worker")
        if replicas < 1:
            raise ValueError("router needs at least one virtual node")
        self.n_workers = n_workers
        self.replicas = replicas
        ring: List[Tuple[int, int]] = []
        for w in range(n_workers):
            for v in range(replicas):
                ring.append((_ring_hash(f"worker:{w}#{v}"), w))
        ring.sort()
        self._ring = ring
        self._points = [h for h, _ in ring]

    def owner(self, shard_key: str) -> int:
        """Worker index owning this shard (first ring point clockwise)."""
        h = _ring_hash(shard_key)
        idx = bisect.bisect_right(self._points, h)
        if idx == len(self._ring):
            idx = 0
        return self._ring[idx][1]

    def assignments(self, shard_keys: Sequence[str]) -> Dict[str, int]:
        return {k: self.owner(k) for k in shard_keys}

    def load(self, shard_keys: Sequence[str]) -> List[int]:
        """Shards per worker — the balance gauge the bench reports."""
        counts = [0] * self.n_workers
        for k in shard_keys:
            counts[self.owner(k)] += 1
        return counts


def shard_key(fleet_id: str, model_id: str = "default") -> str:
    """The canonical shard name. '/' is reserved for the HTTP path split."""
    if not fleet_id or "/" in fleet_id or "/" in model_id:
        raise ValueError(
            f"fleet/model ids must be non-empty and '/'-free "
            f"(got fleet={fleet_id!r}, model={model_id!r})"
        )
    return f"{fleet_id}::{model_id}"

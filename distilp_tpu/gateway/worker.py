"""The solve worker: one thread owning N shards' schedulers.

A ``ShardWorker`` is a single daemon thread draining a command queue.
Everything that touches a shard's ``Scheduler`` — event ticks, snapshot
dumps, state restores, health reads — runs as a queued closure ON the
worker thread, so per-shard work is serialized by construction: one shard
is only ever solved by its owning worker, warm-state writes never race,
and all of PR 5's chaos machinery (quarantine, deadlines, breaker,
HealthState) runs unchanged inside the worker because the ``Scheduler``
it wraps IS the single-daemon scheduler.

The thread is a daemon for the same reason ``sched._SolveWorker``'s is:
an abandoned solve deep inside jit'd device code cannot be interrupted,
and a non-daemon thread would block process exit on it. ``stop()`` is the
graceful path (drains the queue, closes every scheduler); the daemon flag
is the crash path.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Optional

from ..sched.metrics import SchedulerMetrics
from ..sched.scheduler import Scheduler
from ..utils.lockwatch import make_lock


class WorkerQueueFull(Exception):
    """A bounded ``submit`` found the worker's queue at its limit.

    Deliberately NOT a RuntimeError: the HTTP tier maps RuntimeError to
    409 (retriable server state) and this to 429 + Retry-After — an
    aliasing subclass would silently misclassify sheds. Carries the depth
    observed under the submit lock (the authoritative reading — a racing
    caller-side ``depth()`` probe is advisory only).
    """

    def __init__(self, worker_id: int, depth: int):
        super().__init__(
            f"worker {worker_id} queue is full ({depth} queued)"
        )
        self.worker_id = worker_id
        self.depth = depth


class ShardWorker:
    """One solve thread + the shards it owns (shard_key -> Scheduler)."""

    def __init__(self, worker_id: int, metrics: SchedulerMetrics):
        self.worker_id = worker_id
        self.metrics = metrics  # gateway-level, thread-safe sink
        # Owned and mutated ONLY on the worker thread (via queued
        # closures). Reads from other threads are sanctioned only when the
        # worker is quiescent (e.g. the serve CLI's sequential replay).
        self.shards: Dict[str, Scheduler] = {}
        self._q: "queue.Queue" = queue.Queue()
        self._stopped = False  # guarded-by: self._submit_lock
        # Serializes submit()'s stopped-check-then-put against stop()'s
        # sentinel put: without it a submitter that passed the check could
        # enqueue AFTER the stop sentinel — the item would never run and
        # its waiter would hang forever instead of getting the RuntimeError.
        self._submit_lock = make_lock("worker.submit")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"gw-worker-{worker_id}"
        )
        self._thread.start()

    # -- the queue protocol ------------------------------------------------

    def submit(
        self,
        fn: Callable,
        on_done: Optional[Callable[[dict], None]] = None,
        bound: Optional[int] = None,
    ):
        """Enqueue ``fn`` for the worker thread.

        Returns ``(box, done)``: wait on the threading.Event, then read
        ``box['result']`` or re-raise ``box['exc']``. ``on_done(box)``
        (optional) fires on the worker thread after ``done`` is set — the
        asyncio ingest path uses it to resolve a loop future via
        ``call_soon_threadsafe`` instead of parking an executor thread per
        in-flight event.

        ``bound`` is the admission gate: when the queue already holds that
        many commands, raise ``WorkerQueueFull`` instead of enqueueing.
        The check runs under the submit lock, so the bound cannot be
        overshot by racing submitters — this is where load shedding is
        DECIDED; the gateway turns the raise into a counted, flight-
        recorded 429. Control-plane submits (health probes, snapshots,
        stop) pass no bound: observability must stay answerable exactly
        when the queue is full.
        """
        box: dict = {}
        done = threading.Event()
        with self._submit_lock:
            if self._stopped:
                raise RuntimeError(f"worker {self.worker_id} is stopped")
            if bound is not None:
                depth = self._q.qsize()
                if depth >= bound:
                    raise WorkerQueueFull(self.worker_id, depth)
            self._q.put((fn, box, done, on_done))
        return box, done

    def call(self, fn: Callable, timeout: Optional[float] = None):
        """Synchronous round trip: run ``fn`` on the worker, return/raise."""
        box, done = self.submit(fn)
        if not done.wait(timeout=timeout):
            raise TimeoutError(
                f"worker {self.worker_id} did not answer within {timeout}s"
            )
        if "exc" in box:
            raise box["exc"]
        return box["result"]

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, box, done, on_done = item
            try:
                box["result"] = fn()
            except BaseException as e:
                # Not swallowed: the caller re-raises from the box; the
                # counter keeps worker-side failures visible even when a
                # caller abandons its wait.
                self.metrics.inc("worker_exception")
                box["exc"] = e
            finally:
                done.set()
                if on_done is not None:
                    try:
                        on_done(box)
                    except Exception:
                        # A dead completion callback (e.g. the asyncio
                        # loop closed mid-flight: call_soon_threadsafe
                        # raises) must not kill the worker thread — that
                        # would strand every queued waiter forever.
                        self.metrics.inc("worker_callback_error")

    def depth(self) -> int:
        """Commands queued but not yet finished (the backpressure gauge)."""
        return self._q.qsize()

    # -- the shard lifecycle seam ------------------------------------------
    #
    # Gateway talks to shards ONLY through these four verbs plus queued
    # closures over ``self.shards``. ``ProcShardWorker`` overrides just
    # ``create_shard`` (a subprocess cannot run the parent's build closure;
    # it needs the picklable ``spec``) — everything else rides the same
    # closures because its ``shards`` dict holds RPC proxies that quack
    # like ``Scheduler``.

    def create_shard(self, key: str, build: Callable, state=None, spec=None):
        """Build a shard's scheduler ON the worker thread and install it.

        ``build`` is a zero-arg closure returning a ready ``Scheduler``;
        ``spec`` is the picklable equivalent that process workers need
        (thread workers ignore it). ``state`` (a ``dump_state`` blob) is
        loaded before the shard is published, so the first tick it ever
        serves is already warm-restored.
        """
        def _do():
            sched = build()
            if state is not None:
                sched.load_state(state)
            self.shards[key] = sched

        self.call(_do)

    def dump_shard(self, key: str):
        """Snapshot one shard behind everything already queued (FIFO)."""
        return self.call(lambda: self.shards[key].dump_state())

    def load_shard(self, key: str, state) -> None:
        """Restore a snapshot into an existing shard (re-arms warm audit)."""
        self.call(lambda: self.shards[key].load_state(state))

    def drop_shard(self, key: str) -> None:
        """Remove and close one shard (the source side of a migration)."""
        def _do():
            sched = self.shards.pop(key)
            sched.close()

        self.call(_do)

    def stop(self, join: bool = True, timeout: float = 5.0) -> None:
        """Graceful shutdown: drain the queue, close every scheduler.

        Queued work ahead of the stop sentinel still runs (a drain IS
        queued work); the close runs on the worker thread itself so it
        never races an in-flight tick.
        """
        def _close_all() -> None:
            for sched in self.shards.values():
                sched.close()

        # Under the submit lock so the sentinel is strictly LAST: no item
        # can slip in behind it and hang its waiter (see _submit_lock).
        with self._submit_lock:
            if self._stopped:
                return
            self._stopped = True
            self._q.put((_close_all, {}, threading.Event(), None))
            self._q.put(None)
        if join:
            self._thread.join(timeout=timeout)

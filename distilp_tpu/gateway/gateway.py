"""The gateway: N solve workers behind consistent-hash shard ownership.

``distilp_tpu.sched.Scheduler`` is one fleet's replanning daemon; this
tier owns MANY of them. Each (fleet_id, model_id) shard maps to exactly
one ``ShardWorker`` (``router.ConsistentHashRouter``), which runs that
shard's ``Scheduler`` unchanged on its own thread — so independent fleets
solve concurrently while any single shard's ticks stay strictly
serialized, and every PR 5 hardening knob (quarantine, deadlines,
retries, breaker, per-shard HealthState) rides along for free. A broken
fleet degrades ITS shard's health; the others never see it.

Ingest is synchronous (``handle_event`` — the trace replay path) or
asyncio (``handle_event_async`` — the HTTP tier): both enqueue on the
owning worker, so ordering per fleet is the submission order either way.

``snapshot()`` drains every worker (a queued barrier — queued events
finish first) and serializes each shard's warm state into a
``GatewaySnapshot``; ``load_snapshot`` restores shards — re-routed by the
CURRENT worker count — with their incumbents, duals, LP iterates and
margin anchors intact, so the first tick after a restore rides warm
(``warm_resumes`` counts the proof, ``cold_resumes`` the violations).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import TYPE_CHECKING, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

if TYPE_CHECKING:
    from ..obs.flight import FlightRecorder

from ..common import DeviceProfile, ModelProfile
from ..obs.trace import NOOP_TRACER
from ..sched.events import STRUCTURAL_KINDS
from ..sched.metrics import (
    HEALTH_BROKEN,
    HEALTH_DEGRADED,
    HEALTH_HEALTHY,
    SchedulerMetrics,
)
from ..sched.scheduler import PlacementView, Scheduler
from ..utils.lockwatch import make_lock
from .procworker import WorkerCrashed
from .recovery import RecoveryStore, Supervisor
from .router import ConsistentHashRouter, shard_key
from .snapshot import GatewaySnapshot, ShardSnapshot
from .worker import ShardWorker, WorkerQueueFull

# Counters aggregated across shards into the gateway metrics snapshot —
# the serving-tier dashboard without grepping per-shard dumps.
_AGGREGATED_SHARD_COUNTERS = (
    "events_total",
    "events_quarantined",
    "tick_cold",
    "tick_warm",
    "tick_margin",
    "tick_failed",
    "tick_certified",
    "tick_uncertified",
    "warm_resumes",
    "cold_resumes",
    "deadline_missed",
    "breaker_open",
    "solver_escalations",
    "spec_hit",
    "spec_miss",
    "spec_stale",
    "spec_presolve",
    "spec_presolve_failed",
    "spec_near_hit",
    "spec_near_miss",
    "events_coalesced",
    # Cross-shard combine path (distilp_tpu.combine): per-shard routing
    # outcomes; the combiner's own batch counters live on the gateway
    # metrics directly.
    "combine_prepared",
    "combine_local",
    "combine_stale",
    "combine_fallback",
    # Compile-ledger tick attribution (obs.compile_ledger): which shards'
    # ticks paid XLA compiles, aggregated for the serving-tier dashboard.
    "compiles",
    "compile_cache_hits",
    "recompile_storms",
)


class QueueFull(Exception):
    """An event was shed at the admission gate (the HTTP tier's 429).

    Raised by ingest when the owning worker's bounded queue is full.
    ``retry_after_s`` is the backoff hint a client should honor (HTTP
    ``Retry-After``): the observed queue depth times the gateway's recent
    mean event-to-placement latency — roughly when the present backlog
    will have drained. Every raise was already counted (``events_shed``)
    and flight-recorded before it left the gateway.
    """

    def __init__(
        self, fleet_id: str, depth: int, retry_after_s: float
    ):
        super().__init__(
            f"fleet {fleet_id!r}: worker queue full ({depth} queued); "
            f"retry after {retry_after_s:.3f}s"
        )
        self.fleet_id = fleet_id
        self.depth = depth
        self.retry_after_s = retry_after_s


class FleetReadView(NamedTuple):
    """A shard fleet's state captured in ONE worker-side closure.

    What ``ShardFacade.fleet`` hands to sequential harnesses: membership,
    model and seq observed at a tick boundary of the owning worker's
    timeline (never mid-tick), plus the published placement's seq from
    the same instant — so a reader can assert tick-boundary consistency
    (``seq == published_seq`` on a clean trace) even under live ingest.
    Device profiles are the live objects (chaos injection deep-copies
    before mutating); the dict itself is a snapshot copy.
    """

    seq: int
    model: object
    devices: Dict[str, object]
    published_seq: Optional[int]

    def device_list(self) -> list:
        return list(self.devices.values())


class Gateway:
    """Horizontally scalable serving tier over sharded solve workers.

    ``scheduler_kwargs`` is the shared solver configuration every shard's
    ``Scheduler`` is built with (mip_gap, kv_bits, backend, k_candidates,
    lp_backend, risk_aware, deadline/retry/breaker knobs, ...);
    ``scheduler_factory(devices, model)`` overrides construction entirely
    (tests inject failing schedulers through it).
    """

    def __init__(
        self,
        n_workers: int = 1,
        replicas: int = 64,
        scheduler_kwargs: Optional[dict] = None,
        scheduler_factory: Optional[Callable] = None,
        metrics: Optional[SchedulerMetrics] = None,
        tracer=None,
        flight: Optional["FlightRecorder"] = None,
        max_queue_depth: Optional[int] = None,
        coalesce: bool = False,
        degrade_depth: Optional[int] = None,
        mem_degrade_headroom_bytes: Optional[float] = None,
        combine: bool = False,
        combine_policy=None,
        worker_backend: str = "thread",
        dynamic: bool = False,
        supervise: bool = False,
        recovery_dir=None,
        snapshot_every: int = 8,
        crash_loop_threshold: int = 3,
        crash_loop_window_s: float = 30.0,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
    ):
        # Library entry point that dispatches backend work (via the
        # schedulers it builds): arm the axon-wedge guard exactly like
        # StreamingReplanner/halda_solve do, so JAX_PLATFORMS=cpu cannot
        # wedge the first tick on a dead tunneled-TPU plugin.
        from ..axon_guard import force_cpu_if_env_requested

        force_cpu_if_env_requested()
        if n_workers < 1:
            raise ValueError("gateway needs at least one worker")
        self.n_workers = n_workers
        self.scheduler_kwargs = dict(scheduler_kwargs or {})
        self._factory = scheduler_factory
        self.metrics = metrics if metrics is not None else SchedulerMetrics()
        # Observability (distilp_tpu.obs), opt-in: ONE tracer and ONE
        # flight recorder shared by the gateway and every shard scheduler
        # it builds — span parenting crosses the worker-queue boundary by
        # attaching the ingest span's context on the worker thread, and
        # flight rings are keyed per fleet. With neither configured the
        # NOOP tracer makes every instrumentation site a constant-cost
        # no-op and schedulers are built exactly as before.
        self.tracer = NOOP_TRACER if tracer is None else tracer
        self.flight = flight
        # -- worker backend + dynamic fleet (PR 19) ------------------------
        # worker_backend='process' hosts each worker's schedulers in a
        # dedicated subprocess (own GIL, own XLA runtime) behind the same
        # ShardWorker contract; it excludes the cross-shard combiner,
        # chaos fault_hook injection and CALLABLE scheduler factories —
        # none of those cross a process boundary (a 'module:callable'
        # factory string works on both backends).
        if worker_backend not in ("thread", "process"):
            raise ValueError(
                f"worker_backend must be 'thread' or 'process', "
                f"got {worker_backend!r}"
            )
        if worker_backend == "process":
            if combine:
                raise ValueError(
                    "combine needs in-process shard access; use thread "
                    "workers or disable combine"
                )
            if scheduler_factory is not None and not isinstance(
                scheduler_factory, str
            ):
                raise ValueError(
                    "process workers need a 'module:callable' factory "
                    "string (a callable cannot cross a process boundary)"
                )
        self.worker_backend = worker_backend
        # dynamic=True arms live topology changes (spawn/retire/migrate).
        # Default OFF: the static gateway's ingest path takes no
        # migration gate — byte-identical to the pre-autoscaler serving
        # path, pinned by test.
        self._dynamic = bool(dynamic)
        # -- crash tolerance (supervised process tier) ---------------------
        # supervise=True arms the per-worker supervisor: child death is
        # detected (WorkerCrashed), classified, respawned under bounded
        # exponential backoff with a crash-loop breaker, and every
        # accepted event rides a per-fleet WAL + periodic micro-snapshots
        # so a respawned child restores warm and replays only the tail.
        # Default OFF: ingest takes no WAL append, no snapshot cadence,
        # no routing re-check — byte-identical to unsupervised serving
        # (pinned by test). Thread workers share the gateway's own crash
        # domain, so supervision is the process backend's feature.
        self._supervise = bool(supervise)
        if self._supervise and worker_backend != "process":
            raise ValueError(
                "supervise=True needs worker_backend='process' (thread "
                "workers live in the gateway's own crash domain — there "
                "is no child to respawn)"
            )
        self.snapshot_every = max(1, int(snapshot_every))
        self._recovery_store: Optional[RecoveryStore] = None
        self._recovery_tmpdir: Optional[str] = None
        # worker_id -> crash-loop policy; worker_id -> per-worker recovery
        # serialization (recovery always runs on the crashed worker's own
        # thread in steady state; the lock covers rare direct off-thread
        # proxy reads). Per-worker, NOT global: a global lock would let
        # two simultaneously-crashed workers deadlock through a
        # quarantine's cross-worker rebuild round trips.
        self._supervisors: Dict[int, Supervisor] = {}
        self._recover_locks: Dict[int, object] = {}
        self._quarantined_workers: List[int] = []
        # shard key -> picklable child build spec, retained so a respawn
        # (or quarantine re-home) can rebuild the shard from scratch.
        self._specs: Dict[str, dict] = {}
        # fleet -> cursor of the micro-snapshot whose counters were last
        # folded. A snapshot's counters fold exactly ONCE — on the first
        # crash after it was taken: a respawned child's counters cover
        # only its own lifetime (post-restore), so a second crash off
        # the SAME snapshot has nothing new below the cursor to fold,
        # and re-folding would double count.  # guarded-by: self._migration_lock
        self._snap_folded: Dict[str, int] = {}
        self._sup_kwargs = dict(
            threshold=crash_loop_threshold,
            window_s=crash_loop_window_s,
            backoff_base_s=backoff_base_s,
            backoff_max_s=backoff_max_s,
        )
        if self._supervise:
            if recovery_dir is None:
                import tempfile

                self._recovery_tmpdir = tempfile.mkdtemp(
                    prefix="distilp-recovery-"
                )
                recovery_dir = self._recovery_tmpdir
            self._recovery_store = RecoveryStore(recovery_dir)
        self.router = ConsistentHashRouter(n_workers, replicas=replicas)
        # Worker SLOTS: a retired worker leaves None at its index so
        # worker ids stay stable ring labels; iterate live_workers() —
        # never this list directly — everywhere that touches all workers.
        self.workers: List[Optional[ShardWorker]] = [
            self._make_worker(i) for i in range(n_workers)
        ]
        # In-flight migrations: shard key -> {'parked': [waiter tuples]}.
        # Ingest for a migrating shard PARKS under this lock; the flip
        # closure replays parked events onto the destination before the
        # entry is cleared, so no event is lost or double-applied.
        self._migration_lock = make_lock("gateway.migration")
        self._migrating: Dict[str, dict] = {}  # guarded-by: self._migration_lock
        # Scheduler metrics are live-copy-only by contract (dump_state
        # drops them); when a migration retires a source copy its
        # counters fold in here so per-fleet shard_totals stay
        # cumulative across moves (warm_resumes == shards migrated).
        self._folded_counters: Dict[str, Dict[str, int]] = {}
        # Serializes whole migrations (and spawn/retire rebalances):
        # two concurrent flips in opposite directions would deadlock
        # their worker threads on each other's load round trips.
        self._migrate_serial = make_lock("gateway.migrate_serial")
        # Autoscaler admission actuation: force_degrade(True) marks every
        # tick under PRESSURE (spec_near serving) regardless of depth —
        # the controller's fast, reversible lever while scale-out warms.
        self._forced_pressure = False
        # Per-worker sustainable eps from the capacity probe; capacity_eps
        # refreshes deterministically as worker count changes.
        self._capacity_per_worker: Optional[float] = None
        self._controller = None  # attach_controller(); /control reads it
        # shard_key -> (fleet_id, model_id, worker index); fleet -> key.
        # Written at registration (under the migration lock, for the
        # lock-discipline audit) and by a live migration's owner flip;
        # dynamic-mode ingest re-reads the entry under the same lock.
        self._shards: Dict[str, Tuple[str, str, int]] = {}
        self._fleet_key: Dict[str, str] = {}
        # Per-fleet handled-event cursor (quarantines included): the
        # resume point a trace replay skips to after a restore.
        self._handled: Dict[str, int] = {}
        self._closed = False
        # -- admission control (README "Overload & admission control").
        # All knobs default OFF: ingest below then routes through the
        # exact pre-admission path — no depth reads, no new counters, no
        # pending buffers (byte-identical serving, pinned by test).
        #
        #   max_queue_depth — bound on a worker's command queue; an event
        #       arriving at a full queue is SHED: counted, flight-recorded
        #       and raised as QueueFull (HTTP 429 + Retry-After);
        #   coalesce       — drift events queued for the same shard fold
        #       into ONE solve at the newest state (structural events are
        #       barriers); the queue holds at most one tick closure per
        #       shard, so bursts compress instead of queueing;
        #   degrade_depth  — depth at which ingest marks the tick as
        #       under PRESSURE: a speculative shard whose exact bank probe
        #       misses may serve a certified near-match (mode='spec_near')
        #       instead of queueing a solve past its deadline;
        #   mem_degrade_headroom_bytes — memory-headroom floor (needs a
        #       live obs.memory ledger): when budget - RSS drops below
        #       it, ingest marks ticks under the SAME pressure flag —
        #       composing with degrade_depth, so a memory-squeezed
        #       gateway degrades to spec_near serving before the OOM
        #       killer degrades it to nothing.
        self.max_queue_depth = max_queue_depth
        #   combine        — route coalesce batches through the cross-shard
        #       solve combiner (distilp_tpu.combine): each shard's pending
        #       drift run is PACKED instead of solved, bucketed by shape,
        #       and one vmapped dispatch prices every bucket member at
        #       once. Implies coalesce (the combiner consumes coalesce
        #       batches). ``combine_policy`` is the committed BucketPolicy
        #       (padding ladder, lane cap, flush deadline).
        self.coalesce = coalesce or combine
        self.degrade_depth = degrade_depth
        self.mem_degrade_headroom_bytes = mem_degrade_headroom_bytes
        self.combine = combine
        self._combine_policy = None
        self._combiner = None
        # Shard keys with a combine ticket in flight: a shard's next
        # coalesce batch PARKS (queues no closure) until its lane is
        # adopted, so the worker never interleaves a newer solve between
        # prepare and adopt.
        self._combine_inflight: Dict[str, bool] = {}  # guarded-by: self._admission_lock
        if combine:
            from ..combine import BucketPolicy, SolveCombiner

            self._combine_policy = (
                combine_policy if combine_policy is not None else BucketPolicy()
            )
            self._combiner = SolveCombiner(
                self._combine_policy, metrics=self.metrics
            )
        self._admission = bool(
            max_queue_depth is not None
            or self.coalesce
            or degrade_depth is not None
            or mem_degrade_headroom_bytes is not None
        )
        # Pending coalesce batches: shard key -> the batch dict its queued
        # drain closure will consume. Guarded by one lock (ingest may come
        # from the asyncio loop AND sync callers on other threads).
        self._admission_lock = make_lock("gateway.admission")
        self._pending: Dict[str, dict] = {}  # guarded-by: self._admission_lock
        # Per-fleet shed counters + monotone per-fleet shed index: the
        # record-by-record reconciliation key (each shed flight record
        # carries its index, so counter vs records can be audited even
        # after the bounded ring overflowed). Own lock — _shed runs inside
        # _submit_coalesced's admission-lock hold, so it cannot share it.
        self._shed_lock = make_lock("gateway.shed")
        self._shed_counts: Dict[str, int] = {}  # guarded-by: self._shed_lock
        # EWMA of event->placement ms, the Retry-After estimate's input.
        self._serve_ewma_ms: Optional[float] = None
        # Attached background observers (timeline samplers, prom
        # scrapers): anything with a .stop(join=True) that reads this
        # gateway on its own thread. close() stops them BEFORE the
        # workers so a probe mid-round-trip can never land on a stopping
        # worker (the PR 8 bench ordering gotcha, fixed at the source).
        self._samplers: List = []
        # SLO layer (obs.timeline + obs.slo), opt-in via attach_slo():
        # None everywhere by default — /slo and /signals 404, no sampler
        # thread, no new counters (byte-identical serving, pinned).
        self.timeline = None
        self.slo_engine = None
        # Max-sustainable events/sec from the PR 12 closed-loop capacity
        # probe (serve --capacity-eps / the bench's measured value): the
        # denominator of /signals' headroom computation. Written by
        # note_capacity and by the per-worker refresh inside a
        # spawn/retire, both under the migrate-serial lock.
        self.capacity_eps: Optional[float] = None

    # -- worker fleet ------------------------------------------------------

    def _make_worker(self, worker_id: int) -> ShardWorker:
        if self.worker_backend == "process":
            from ..obs import compile_ledger as _cl
            from .procworker import ProcShardWorker

            # The child mirrors the parent's compile-ledger enablement:
            # a ledgered run gets per-process compile attribution (the
            # bench federation section's zero-warm-compiles gate reads
            # it via ledger_counters()); an unledgered run pays nothing.
            w = ProcShardWorker(
                worker_id,
                metrics=self.metrics,
                compile_ledger=_cl.current() is not None,
            )
            if self._supervise:
                self._supervisors[worker_id] = Supervisor(**self._sup_kwargs)
                self._recover_locks[worker_id] = make_lock("gateway.recover")
                # Read paths retry once after this hook recovers the
                # worker in place (mutating calls never route through it).
                w.recovery_hook = (
                    lambda worker: self._recover_worker(worker)[0]
                    == "respawned"
                )
            return w
        return ShardWorker(worker_id, metrics=self.metrics)

    def live_workers(self) -> List[ShardWorker]:
        """Current worker fleet, retired slots excluded."""
        return [w for w in self.workers if w is not None]

    def live_worker_ids(self) -> List[int]:
        return [w.worker_id for w in self.workers if w is not None]

    # -- shard lifecycle ---------------------------------------------------

    def _build_scheduler(
        self,
        devices: Sequence[DeviceProfile],
        model: ModelProfile,
        fleet_id: str = "default",
    ) -> Scheduler:
        if self._factory is not None:
            # Factory signature stays (devices, model): tests inject
            # failing schedulers through it and obs plumbing is theirs.
            # A 'module:callable' string resolves to the same shape (the
            # form process workers require — the child imports it too).
            if isinstance(self._factory, str):
                from .procworker import resolve_factory

                return resolve_factory(self._factory)(devices, model)
            return self._factory(devices, model)
        kw = dict(self.scheduler_kwargs)
        if self.tracer is not NOOP_TRACER:
            kw["tracer"] = self.tracer
        if self.flight is not None:
            kw["flight"] = self.flight
            kw["flight_key"] = fleet_id
        return Scheduler(devices, model, **kw)

    def _shard_spec(self, devices, model, fleet_id: str) -> Optional[dict]:
        """Picklable build instructions for a process worker's child
        (None on the thread backend — it builds via the closure)."""
        if self.worker_backend != "process":
            return None
        return {
            "devices": [
                d.model_dump() if hasattr(d, "model_dump") else d
                for d in devices
            ],
            "model": (
                model.model_dump()
                if hasattr(model, "model_dump")
                else model
            ),
            "fleet_id": fleet_id,
            "kwargs": dict(self.scheduler_kwargs),
            "factory": (
                self._factory if isinstance(self._factory, str) else None
            ),
        }

    def register_fleet(
        self,
        fleet_id: str,
        devices: Sequence[DeviceProfile],
        model: ModelProfile,
        model_id: str = "default",
        state: Optional[dict] = None,
        events_handled: int = 0,
    ) -> int:
        """Create (or restore) a shard; returns the owning worker index.

        ``state`` is a ``Scheduler.dump_state`` blob: the shard resumes
        with its warm pool, published placement and health machine intact
        (the blob's fleet/model override ``devices``/``model`` — they are
        still required so a registration without state is well-formed).
        """
        key = shard_key(fleet_id, model_id)
        if key in self._shards:
            raise ValueError(f"shard {key!r} is already registered")
        if fleet_id in self._fleet_key:
            # The ingest/snapshot directory is keyed by fleet_id; a second
            # shard under the same fleet would silently clobber the first's
            # routing and resume cursor. One live model per fleet — a model
            # change is a ModelSwap EVENT on the existing shard, not a
            # second registration.
            raise ValueError(
                f"fleet {fleet_id!r} is already registered (under model "
                f"{self._shards[self._fleet_key[fleet_id]][1]!r}); swap "
                "models via a model_swap event, or use a distinct fleet id"
            )
        widx = self.router.owner(key)
        worker = self.workers[widx]
        spec = self._shard_spec(devices, model, fleet_id)
        worker.create_shard(
            key,
            build=lambda: self._build_scheduler(devices, model, fleet_id),
            state=state,
            spec=spec,
        )
        if spec is not None:
            # Retained for crash recovery: a respawned (or re-homed)
            # child rebuilds the shard from this spec before restoring
            # its micro-snapshot and replaying the WAL tail.
            self._specs[key] = spec
        if self._supervise and state is not None:
            # A shard registered FROM a snapshot blob is warm before its
            # first micro-snapshot lands; seed the recovery store with
            # that blob so a crash in the gap still restores warm.
            self._recovery_store.save_micro_snapshot(
                fleet_id, events_handled, state, {}
            )
        with self._migration_lock:
            self._shards[key] = (fleet_id, model_id, widx)
        self._fleet_key[fleet_id] = key
        self._handled[fleet_id] = events_handled
        self.metrics.inc("shards_registered")
        if state is not None:
            self.metrics.inc("shards_restored")
        return widx

    def fleet_ids(self) -> List[str]:
        return list(self._fleet_key)

    def _lookup(self, fleet_id: str) -> Tuple[str, ShardWorker]:
        key = self._fleet_key.get(fleet_id)
        if key is None:
            raise KeyError(f"unknown fleet {fleet_id!r}; register it first")
        return key, self.workers[self._shards[key][2]]

    def scheduler(self, fleet_id: str) -> Scheduler:
        """Direct handle on a shard's live scheduler.

        Main-thread reads are only sound while the owning worker is
        quiescent (sequential replay, post-drain inspection, chaos
        arming) — event ticks always go through the worker queue. For
        reads that must be sound under LIVE ingest, use ``read_shard``.
        """
        key, worker = self._lookup(fleet_id)
        return worker.shards[key]

    def read_shard(self, fleet_id: str, fn: Callable):
        """Run ``fn(scheduler)`` as a queued closure ON the owning worker.

        The sound way to read a shard under live ingest: the closure runs
        behind every queued tick, so whatever ``fn`` computes is observed
        at a tick boundary of that shard's timeline — never mid-tick.
        (``ShardFacade``'s ``.fleet``/``.metrics`` reads route through
        here; the PR 7 facade read caller-side and was only sound while
        the worker was quiescent.) Blocks for the round trip.
        """
        key, worker = self._lookup(fleet_id)
        return worker.call(lambda: fn(worker.shards[key]))

    # -- ingest ------------------------------------------------------------

    def configure_admission(
        self,
        max_queue_depth: Optional[int] = None,
        coalesce: bool = False,
        degrade_depth: Optional[int] = None,
        mem_degrade_headroom_bytes: Optional[float] = None,
        combine: bool = False,
        combine_policy=None,
    ) -> None:
        """Reconfigure the admission knobs (see ``__init__``).

        Call on a quiescent gateway only (between arms of a bench sweep,
        after a warmup phase): ingest reads the knobs without a lock, and
        flipping them mid-flight would split one burst across two
        policies. All-default arguments turn admission OFF — back to the
        byte-identical pre-admission ingest path.
        """
        if combine and self.worker_backend == "process":
            raise ValueError(
                "combine needs in-process shard access; use thread "
                "workers or disable combine"
            )
        old_combiner = None
        with self._admission_lock:
            if self._pending or self._combine_inflight:
                raise RuntimeError(
                    "cannot reconfigure admission with coalesce batches "
                    "or combine tickets pending (the gateway is not "
                    "quiescent)"
                )
            self.max_queue_depth = max_queue_depth
            self.coalesce = coalesce or combine
            self.degrade_depth = degrade_depth
            self.mem_degrade_headroom_bytes = mem_degrade_headroom_bytes
            if combine != self.combine or combine_policy is not None:
                old_combiner = self._combiner
                self._combiner = None
                self._combine_policy = None
                self.combine = combine
                if combine:
                    from ..combine import BucketPolicy, SolveCombiner

                    self._combine_policy = (
                        combine_policy if combine_policy is not None
                        else BucketPolicy()
                    )
                    self._combiner = SolveCombiner(
                        self._combine_policy, metrics=self.metrics
                    )
            self._admission = bool(
                max_queue_depth is not None
                or self.coalesce
                or degrade_depth is not None
                or mem_degrade_headroom_bytes is not None
            )
        if old_combiner is not None:
            # Outside the lock: stop() joins the flush thread, whose
            # deliveries take worker queues — never while holding the
            # admission lock an ingest path also needs.
            old_combiner.stop()

    def warm_combine(self, fleet_ids: Optional[Sequence[str]] = None) -> dict:
        """Trace every combined executable the committed policy can reach.

        For each registered combinable shard, packs its CURRENT fleet
        state at the policy's padded size (through ``read_shard``, so the
        pack observes a tick boundary), groups the packs by bucket
        signature, and runs one throwaway ``solve_batch`` per committed
        lane shape (``BucketPolicy.lane_shapes``) per signature. Results
        are discarded — this exists purely to populate the jit cache so
        the measured phase's compile ledger stays flat: with both shape
        axes committed (padded M by ``pad_for``, lane count by
        ``quantize_lanes``) the reachable executable set is exactly what
        this method enumerates, which is the PR 14 zero-recompile gate's
        warm contract for combined traffic. Call after the per-shard
        warmup (packs reuse each shard's warm signature) and before the
        measured phase. Also pre-positions every combinable shard's static
        half in the per-lane device cache (``lane_static_to_device``) so
        measured-phase flushes re-ship only dynamic bytes. Returns
        ``{"buckets": ..., "shapes_traced": ..., "statics_primed": ...}``.
        """
        if self._combiner is None:
            raise RuntimeError(
                "warm_combine requires the combine admission path "
                "(configure_admission(combine=True) first)"
            )
        from ..solver.batchlayout import lane_static_to_device, solve_batch

        policy = self._combine_policy
        ids = list(fleet_ids) if fleet_ids is not None else self.fleet_ids()
        by_sig: Dict[tuple, tuple] = {}  # sig -> (fleet_id, instance)
        primed = 0
        for fid in ids:

            def _pack(s, warm_override=None):
                planner = s.pool.peek(s.fleet.key())
                if planner is None:
                    return None
                devs = s.fleet.device_list()
                return planner.prepare(
                    devs, s.fleet.model, M_pad=policy.pad_for(len(devs)),
                    warm_override=warm_override,
                )

            prep = self.read_shard(fid, _pack)
            if prep is None:
                continue  # MoE / non-jax / cold shard: not combinable
            # Pre-position this shard's drift-invariant half on device NOW
            # (before the openloop warm boundary): measured-phase flushes
            # then assemble their static stacks from cache — no static
            # re-uploads, and no live-array growth past the leak baseline.
            _, uploaded = lane_static_to_device(prep.instance.static_np)
            primed += 1 if uploaded else 0
            by_sig.setdefault(prep.instance.signature, (fid, prep.instance))
        shapes = 0
        seen = set(by_sig)
        for fid, inst in list(by_sig.values()):
            best = None
            for lanes in policy.lane_shapes(inst.M_pad):
                decoded = solve_batch([inst], lane_pad=lanes)
                best = decoded[0][1]
                shapes += 1
            # Round two: the STEADY-STATE signature. A shard's second and
            # later combined ticks warm-seed from an adopted batched
            # result, whose root-IPM iterates carry the padded family's
            # shapes — that flips ``has_root_warm`` (and the dyn blob
            # size) relative to the per-shard-seeded pack traced above,
            # minting a fresh executable on the SECOND post-warmup tick
            # if it is not traced here.
            if best is None or best.ipm_state is None:
                continue
            prep2 = self.read_shard(
                fid, lambda s, b=best: _pack(s, warm_override=b)
            )
            if prep2 is None or prep2.instance.signature in seen:
                continue
            seen.add(prep2.instance.signature)
            for lanes in policy.lane_shapes(prep2.instance.M_pad):
                solve_batch([prep2.instance], lane_pad=lanes)
                shapes += 1
        return {
            "buckets": len(seen),
            "shapes_traced": shapes,
            "statics_primed": primed,
        }

    def _mem_pressure(self) -> bool:
        """True when the memory-headroom floor is configured AND the live
        memory ledger reports headroom below it. Cost: a cached-or-/proc
        RSS read (~0.1 ms worst case, no live-array walk) — cheap enough
        per ingest. No ledger (or no readable RSS) means no verdict:
        degrade-on-low-headroom degrades on EVIDENCE, never on absence.
        """
        if self.mem_degrade_headroom_bytes is None:
            return False
        from ..obs import memory as _mem

        led = _mem.current()
        if led is None:
            return False
        headroom = led.headroom_bytes()
        if headroom is None or headroom >= self.mem_degrade_headroom_bytes:
            return False
        self.metrics.inc("mem_pressure")
        return True

    def _tick_closure(
        self, fleet_id: str, key: str, worker, event, parent=None,
        t_enq=None, pressure: bool = False, depth: Optional[int] = None,
    ):
        """The queued unit of ingest: tick the shard AND advance the
        fleet's resume cursor, both ON the worker thread. The cursor must
        move inside the closure — a snapshot is a later closure on the
        same queue, so it always observes a cursor consistent with the
        shard state it dumps (bumping the cursor caller-side after the
        wait would let a snapshot read state covering event n with a
        cursor still at n-1, and a resume would double-apply event n).

        ``parent``/``t_enq`` carry the ingest span's context and enqueue
        timestamp (ms) across the queue: the closure's first act on the
        worker thread is recording the **queue-wait span** — submit to
        pickup, the number that diagnoses worker thrash — and attaching
        the ingest context so the tick's own spans parent under it. With
        tracing off both are shared no-ops (parent is None). ``depth`` is
        the queue depth observed at enqueue (the admission-control input),
        attached to the queue-wait span when tracing is on; ``pressure``
        rides through to the scheduler's degraded-serving seam.
        """

        def _do() -> PlacementView:
            if self._supervise:
                # Crash-tolerant path: WAL append before dispatch, crash
                # detection + recovery around it. Kept out of line so the
                # unsupervised closure below stays byte-identical.
                return self._supervised_tick(
                    fleet_id, key, worker, event, parent, t_enq,
                    pressure, depth,
                )
            attrs = {"worker": worker.worker_id}
            if depth is not None:
                attrs["depth"] = depth
            self.tracer.record_span(
                "gateway.queue_wait",
                t_enq if t_enq is not None else 0.0,
                None,
                parent=parent,
                attrs=attrs,
            )
            with self.tracer.attach(parent):
                # finally, not on success: a raising handle() may still
                # have mutated the fleet (seq advances before the solve
                # fails), and a cursor one behind the seq would make a
                # resume double-apply that event. Counting a
                # rejected-and-raised event too only skips a repeat
                # rejection on resume — always safe.
                try:
                    if pressure:
                        return worker.shards[key].handle(
                            event, pressure=True
                        )
                    return worker.shards[key].handle(event)
                finally:
                    self._handled[fleet_id] = (
                        self._handled.get(fleet_id, 0) + 1
                    )

        return _do

    def _supervised_tick(
        self, fleet_id: str, key: str, worker, event, parent, t_enq,
        pressure: bool, depth: Optional[int],
    ) -> PlacementView:
        """One supervised tick, ON a worker thread: journal the event,
        dispatch it, and on child death recover (respawn or quarantine)
        before answering the waiter.

        A quarantine may have re-homed this shard after the closure was
        queued — the drain of a dead worker's queue runs on its (still
        live) parent thread. Re-resolve the owner first and FORWARD to
        its queue when it moved: the inner closure does its own WAL
        append and cursor bump, so the forwarding frame must return
        before the caller's bump region (it is inside ``_tick_closure``'s
        ``_do`` body, before any bump of this frame's own).
        """
        with self._migration_lock:
            cur = self.workers[self._shards[key][2]]
        if cur is not worker:
            box, done = self._submit_tick(
                fleet_id, key, cur, event, parent, t_enq
            )
            done.wait()
            if "exc" in box:
                raise box["exc"]
            return box["result"]
        attrs = {"worker": worker.worker_id}
        if depth is not None:
            attrs["depth"] = depth
        self.tracer.record_span(
            "gateway.queue_wait",
            t_enq if t_enq is not None else 0.0,
            None,
            parent=parent,
            attrs=attrs,
        )
        with self.tracer.attach(parent):
            cursor = self._handled.get(fleet_id, 0) + 1
            # Journal BEFORE dispatch: a child that dies holding this
            # event leaves it replayable from the WAL tail.
            self._recovery_store.wal(fleet_id).append(cursor, event)
            self.metrics.inc("wal_appends")
            try:
                try:
                    if pressure:
                        view = worker.shards[key].handle(
                            event, pressure=True
                        )
                    else:
                        view = worker.shards[key].handle(event)
                except WorkerCrashed:  # dlint: disable=DLP017 accounted inside _recover_worker (worker_crashes inc + recovery_mttr_ms observe per attempt)
                    # The RPC died mid-flight: whether the child applied
                    # the event is UNKNOWABLE, so its partial state is
                    # discarded entirely — recovery restores the last
                    # micro-snapshot and replays the WAL tail, which
                    # includes this event (appended above). Exactly-once
                    # holds by construction, not by guessing.
                    verdict, views = self._recover_worker(worker)
                    view = views.get(fleet_id)
                    if view is None:
                        owner = self.workers[self._shards[key][2]]
                        view = owner.shards[key].latest()
                self._maybe_micro_snapshot(fleet_id, key, cursor)
                return view
            finally:
                self._handled[fleet_id] = (
                    self._handled.get(fleet_id, 0) + 1
                )

    def _maybe_micro_snapshot(self, fleet_id: str, key: str, cursor: int) -> None:
        """Persist a micro-snapshot when ``cursor`` lands on a boundary
        (the first event always snapshots — a kill before the first
        boundary must still respawn warm). Runs on the owning worker's
        thread; a crash DURING the dump is survivable (the previous
        snapshot + WAL tail still cover everything), so failure here
        only counts, never raises."""
        if cursor != 1 and cursor % self.snapshot_every != 0:
            return
        self._maybe_micro_snapshot_at(fleet_id, key, cursor)

    def _submit_tick(
        self, fleet_id: str, key: str, worker: ShardWorker, event, parent, t_enq,
        on_done=None,
    ):
        """Route one event to its worker, migration-aware when dynamic.

        Static gateways (``dynamic=False``, the default) fall straight
        through to the admission path below — no extra lock, no new code
        on the hot path. Dynamic gateways take the migration gate: an
        event for a shard whose flip is in flight PARKS (no closure is
        queued anywhere) and is replayed onto the destination worker by
        the flip itself, in arrival order, before the gate clears — so a
        live migration loses no event, double-applies no event, and
        serves every tick. The gate also re-resolves the owning worker
        under the lock: the caller's ``worker`` argument may predate a
        completed flip.
        """
        if self._dynamic:
            with self._migration_lock:
                mig = self._migrating.get(key)
                if mig is not None:
                    box: dict = {}
                    done = threading.Event()
                    mig["parked"].append(
                        (event, parent, t_enq, on_done, box, done)
                    )
                    self.metrics.inc("migration_parked")
                    return box, done
                worker = self.workers[self._shards[key][2]]
                return self._submit_tick_routed(
                    fleet_id, key, worker, event, parent, t_enq, on_done
                )
        return self._submit_tick_routed(
            fleet_id, key, worker, event, parent, t_enq, on_done
        )

    def _submit_tick_routed(
        self, fleet_id: str, key: str, worker: ShardWorker, event, parent, t_enq,
        on_done=None,
    ):
        """Route one event through the admission gate onto its worker.

        Returns the ``(box, done)`` pair the waiter resolves on. With
        admission OFF this is exactly the pre-admission submit — no depth
        reads beyond the traced span's, no new code paths. With it on:

        - a full queue (``max_queue_depth``) sheds the event — counted,
          flight-recorded, raised as ``QueueFull`` (the bound itself is
          enforced inside ``ShardWorker.submit`` under its lock, so racing
          submitters cannot overshoot it);
        - past ``degrade_depth`` the tick is marked under pressure
          (degraded-mode serving from the speculation bank);
        - with ``coalesce`` on, drift events for a shard that already has
          a queued-but-unstarted tick closure JOIN that closure's batch
          instead of queueing their own — the shard solves once, at the
          newest state, and every waiter gets that view. Structural
          events are barriers: they detach the open batch (its closure
          still drains exactly the events that joined before the barrier)
          and queue behind it, preserving per-fleet order.
        """
        # force_degrade(True) routes through the admission branch even
        # when no static knob is set: the forced flag IS the pressure
        # verdict. With it off (always, on static gateways) this line is
        # exactly the old precomputed check.
        admission = self._admission or self._forced_pressure
        depth: Optional[int] = None
        if admission or self.tracer.enabled:
            depth = worker.depth()
        if not admission:
            return worker.submit(
                self._tick_closure(
                    fleet_id, key, worker, event,
                    parent=parent, t_enq=t_enq, depth=depth,
                ),
                on_done,
            )
        pressure = (
            (self.degrade_depth is not None and depth >= self.degrade_depth)
            or self._forced_pressure
            or self._mem_pressure()
        )
        structural = getattr(event, "kind", None) in STRUCTURAL_KINDS
        if self.coalesce and not structural:
            return self._submit_coalesced(
                fleet_id, key, worker, event, parent, t_enq,
                pressure, depth, on_done,
            )
        if self.coalesce and structural:
            # Barrier: later drift must not join a batch whose closure
            # was enqueued BEFORE this structural event — that would
            # reorder it ahead. Pop AND submit under ONE lock hold: with
            # the lock released in between, a racing drift ingest could
            # open (and submit) a fresh batch that lands in the worker
            # FIFO ahead of this structural closure — exactly the
            # reordering the barrier exists to rule out. The detached
            # batch still drains exactly the events that joined it.
            closure = self._tick_closure(
                fleet_id, key, worker, event,
                parent=parent, t_enq=t_enq, pressure=pressure, depth=depth,
            )
            with self._admission_lock:
                batch = self._pending.get(key)
                if batch is not None and batch.get("parked"):
                    # A PARKED batch (combine ticket in flight) has no
                    # queued closure to drain ahead of us, so popping it
                    # would strand its waiters. Append the structural
                    # event instead: order within the batch is arrival
                    # order, and a mixed batch drains through the local
                    # per-shard path (prepare_combine never sees it).
                    box: dict = {}
                    done = threading.Event()
                    batch["events"].append(event)
                    batch["waiters"].append((box, done, on_done))
                    batch["pressure"] = batch["pressure"] or pressure
                    return box, done
                self._pending.pop(key, None)
                try:
                    return worker.submit(
                        closure, on_done, bound=self.max_queue_depth
                    )
                except WorkerQueueFull as e:
                    raise self._shed(
                        fleet_id, event, worker, e.depth
                    ) from None
        closure = self._tick_closure(
            fleet_id, key, worker, event,
            parent=parent, t_enq=t_enq, pressure=pressure, depth=depth,
        )
        try:
            return worker.submit(closure, on_done, bound=self.max_queue_depth)
        except WorkerQueueFull as e:
            raise self._shed(fleet_id, event, worker, e.depth) from None

    def _submit_coalesced(
        self, fleet_id, key, worker: ShardWorker, event, parent, t_enq,
        pressure, depth, on_done,
    ):
        box: dict = {}
        done = threading.Event()
        with self._admission_lock:
            batch = self._pending.get(key)
            if batch is not None:
                # Joining an open batch queues NOTHING: the burst
                # compresses into the one already-queued solve (this is
                # why a coalescing gateway's queue depth stays ~#shards
                # under a same-shard flood).
                batch["events"].append(event)
                batch["waiters"].append((box, done, on_done))
                batch["pressure"] = batch["pressure"] or pressure
                return box, done
            batch = {
                "events": [event],
                "waiters": [(box, done, on_done)],
                "pressure": pressure,
                "parked": False,
            }
            self._pending[key] = batch
            if self.combine and self._combine_inflight.get(key):
                # The shard's previous batch is mid-combine (prepare done,
                # adopt pending): queueing a drain now would let the worker
                # solve NEWER state before the older lane lands. Park the
                # batch — it keeps absorbing joiners — and let the adopt
                # closure submit the drain when the lane is redeemed.
                batch["parked"] = True
                batch["args"] = (parent, t_enq, depth)
                return box, done
            closure = self._batch_closure(
                fleet_id, key, worker, batch, parent, t_enq, depth
            )
            # Submit INSIDE the admission lock: once the batch is in
            # _pending another ingest thread may join it, and a joined
            # waiter must never be stranded by this submit shedding —
            # under the lock, join and shed cannot interleave. (Lock
            # order admission->submit is taken nowhere in reverse;
            # _shed's own counting uses the separate _shed_lock.)
            try:
                worker.submit(closure, bound=self.max_queue_depth)
            except WorkerQueueFull as e:
                del self._pending[key]
                raise self._shed(
                    fleet_id, event, worker, e.depth
                ) from None
        return box, done

    def _batch_closure(
        self, fleet_id, key, worker, batch, parent, t_enq, depth
    ):
        """The queued drain of one coalesce batch: runs on the worker
        thread, detaches the batch (late joiners up to this instant are
        included — maximal coalescing), ticks the shard ONCE via the
        scheduler's coalescing hook, and resolves every waiter with the
        one resulting view. The resume cursor advances by the whole batch
        inside the closure, same consistency argument as
        ``_tick_closure``."""

        def _do() -> None:
            with self._admission_lock:
                if self._pending.get(key) is batch:
                    del self._pending[key]
                events = list(batch["events"])
                waiters = list(batch["waiters"])
                pressure = batch["pressure"]
            attrs = {"worker": worker.worker_id, "batch": len(events)}
            if depth is not None:
                attrs["depth"] = depth
            self.tracer.record_span(
                "gateway.queue_wait",
                t_enq if t_enq is not None else 0.0,
                None,
                parent=parent,
                attrs=attrs,
            )
            shared: dict = {}
            with self.tracer.attach(parent):
                combiner = self._combiner
                if combiner is not None and not any(
                    getattr(ev, "kind", None) in STRUCTURAL_KINDS
                    for ev in events
                ):
                    # Combine path: PACK this shard's tick instead of
                    # solving it; the batched dispatch happens on the
                    # combiner thread and the lane is redeemed by an
                    # adopt closure queued back onto this worker. A
                    # short-circuit view (spec hit, breaker, local
                    # fallback) resolves the waiters right here.
                    from ..combine import CombineEntry

                    with self._admission_lock:
                        if self._combine_inflight.get(key):
                            # This closure was queued in the window
                            # between the previous batch's detach and its
                            # inflight mark — the ingest-side parking
                            # check could not see the lane. Applying our
                            # events now would advance the fleet past the
                            # packed seq and turn that lane stale, so
                            # RE-PARK instead: the adopt closure drains
                            # us when the lane is redeemed.
                            open_batch = self._pending.get(key)
                            if open_batch is not None:
                                # A newer batch opened behind us; our
                                # events are OLDER — merge at the front
                                # so per-fleet order is preserved.
                                open_batch["events"][:0] = events
                                open_batch["waiters"][:0] = waiters
                                open_batch["pressure"] = (
                                    open_batch["pressure"] or pressure
                                )
                            else:
                                batch["events"] = list(events)
                                batch["waiters"] = list(waiters)
                                batch["pressure"] = pressure
                                batch["parked"] = True
                                batch["args"] = (parent, t_enq, depth)
                                self._pending[key] = batch
                            return

                    ticket = None
                    try:
                        sched = worker.shards[key]
                        m_pad = self._combine_policy.pad_for(
                            len(sched.fleet.device_list())
                        )
                        ticket, view = sched.prepare_combine(
                            events, pressure=pressure, M_pad=m_pad
                        )
                        if view is not None:
                            shared["result"] = view
                    except BaseException as e:
                        self.metrics.inc("worker_exception")
                        shared["exc"] = e
                    finally:
                        self._handled[fleet_id] = (
                            self._handled.get(fleet_id, 0) + len(events)
                        )
                    if ticket is not None:
                        with self._admission_lock:
                            self._combine_inflight[key] = True
                        combiner.submit(
                            CombineEntry(
                                ticket,
                                self._combine_deliver(
                                    fleet_id, key, worker, ticket, waiters
                                ),
                            )
                        )
                        return
                    self._resolve_waiters(waiters, shared)
                    return
                try:
                    if self._supervise:
                        shared["result"] = self._supervised_batch(
                            fleet_id, key, worker, events, pressure
                        )
                    else:
                        shared["result"] = worker.shards[key].handle_coalesced(
                            events, pressure=pressure
                        )
                except BaseException as e:
                    # Counted here (not re-raised to the worker loop): the
                    # waiters below are the real consumers and each gets
                    # the exception; the worker's own box has no reader.
                    self.metrics.inc("worker_exception")
                    shared["exc"] = e
                finally:
                    self._handled[fleet_id] = (
                        self._handled.get(fleet_id, 0) + len(events)
                    )
                    self._resolve_waiters(waiters, shared)

        return _do

    def _supervised_batch(
        self, fleet_id: str, key: str, worker, events, pressure: bool
    ) -> PlacementView:
        """The coalesced-drain analogue of ``_supervised_tick``: journal
        every event of the batch before the one dispatch, recover on
        child death, micro-snapshot when the batch crosses a boundary.
        The CALLER's finally still bumps the cursor by ``len(events)``
        — this method only journals and dispatches."""
        with self._migration_lock:
            cur = self.workers[self._shards[key][2]]
        if cur is not worker:
            # Shard re-homed by a quarantine after this drain was queued:
            # run the whole supervised batch on the new owner's thread
            # (serialized behind its queue) and hand back its view. The
            # caller's cursor bump covers these events exactly once —
            # this forwarded frame bumps nothing.
            return cur.call(
                lambda: self._supervised_batch(
                    fleet_id, key, cur, events, pressure
                )
            )
        base = self._handled.get(fleet_id, 0)
        wal = self._recovery_store.wal(fleet_id)
        for i, ev in enumerate(events):
            wal.append(base + 1 + i, ev)
        self.metrics.inc("wal_appends", len(events))
        try:
            view = worker.shards[key].handle_coalesced(
                events, pressure=pressure
            )
        except WorkerCrashed:  # dlint: disable=DLP017 accounted inside _recover_worker (worker_crashes inc + recovery_mttr_ms observe per attempt)
            verdict, views = self._recover_worker(worker)
            view = views.get(fleet_id)
            if view is None:
                owner = self.workers[self._shards[key][2]]
                view = owner.shards[key].latest()
        cursor = base + len(events)
        if base == 0 or cursor // self.snapshot_every > base // self.snapshot_every:
            self._maybe_micro_snapshot_at(fleet_id, key, cursor)
        return view

    def _maybe_micro_snapshot_at(self, fleet_id: str, key: str, cursor: int) -> None:
        """Unconditional micro-snapshot at ``cursor`` (the batch path
        computed the boundary crossing itself — a batch can straddle one
        without any member landing exactly on it)."""
        owner = self.workers[self._shards[key][2]]
        sched = owner.shards[key]
        try:
            state = sched.dump_state()
            counters = dict(sched.metrics.counters)
        except WorkerCrashed:
            self.metrics.inc("micro_snapshot_failed")
            return
        self._recovery_store.save_micro_snapshot(
            fleet_id, cursor, state, counters
        )
        self.metrics.inc("micro_snapshots")

    # -- crash recovery ----------------------------------------------------

    def _recover_worker(self, worker) -> Tuple[str, Dict[str, PlacementView]]:
        """Bring a crashed process worker's shards back: respawn with
        bounded backoff (retrying through double-crashes — snapshot
        restore + WAL replay is idempotent, each attempt rebuilds from
        scratch) or, when the crash-loop breaker opens, quarantine the
        worker and re-home its slice onto the surviving ring.

        Returns ``(verdict, views)`` where views maps each recovered
        fleet_id to the placement view its replayed tail produced —
        the supervised tick answers its waiter from this map.

        Locking: per-WORKER recover locks, never one global lock — a
        quarantine re-homes shards via round trips through OTHER workers'
        queues, and two workers quarantining simultaneously under one
        global lock would deadlock on each other's rebuild round trips.
        Recovery otherwise runs inline on the dead worker's own (still
        live) parent thread, so per-worker work is naturally serialized.
        """
        wid = worker.worker_id
        lock = self._recover_locks.get(wid)
        if lock is None:
            raise RuntimeError(
                f"worker {wid} crashed with supervision off"
            )
        with lock:
            if wid in self._quarantined_workers:
                return "quarantined", {}
            if self._closed:
                # Clean shutdown, not a crash: the gateway closed the
                # child under us. Nothing to respawn.
                return "stopped", {}
            if worker.child_alive():
                try:
                    worker.rpc({"op": "ping"})
                    # A racing caller on this thread already recovered it.
                    return "respawned", {}
                except WorkerCrashed:  # dlint: disable=DLP017 probe only: a dead ping falls through to the recovery loop below, whose record_crash/worker_crashes account every attempt
                    pass
            sup = self._supervisors[wid]
            t0 = time.perf_counter()
            views: Dict[str, PlacementView] = {}
            while True:
                verdict = sup.record_crash()
                self.metrics.inc("worker_crashes")
                if verdict == "quarantine" and len(self.live_worker_ids()) > 1:
                    views = self._quarantine_worker(worker)
                    mttr = (time.perf_counter() - t0) * 1000.0
                    self.metrics.observe("recovery_mttr_ms", mttr)
                    self._record_recovery(worker, "quarantine", mttr, views)
                    return "quarantined", views
                # A single-worker gateway has nowhere to re-home: keep
                # respawning past the breaker (documented; the breaker
                # still surfaces via crashes_in_window in /signals).
                time.sleep(sup.backoff_s())
                try:
                    worker.respawn_child()
                    self.metrics.inc("child_respawns")
                    views = self._rebuild_worker_shards(worker)
                    break
                except WorkerCrashed:  # dlint: disable=DLP017 the loop's next record_crash() increments worker_crashes — every failed attempt is counted, none swallowed
                    # Crash DURING recovery (respawn died, or replay
                    # killed the fresh child): loop — the next attempt
                    # restores the same snapshot and replays the same
                    # tail. The abandoned attempt's counters die
                    # unfolded, which is correct: attempt N+1 replays
                    # the whole tail and regenerates them.
                    continue
            mttr = (time.perf_counter() - t0) * 1000.0
            self.metrics.observe("recovery_mttr_ms", mttr)
            self._record_recovery(worker, "respawn", mttr, views)
            return "respawned", views

    def _fold_snapshot_counters(self, fid: str, snap: Optional[dict]) -> None:
        """Fold a dead child's micro-snapshot counters into the fleet's
        running totals — at most ONCE per snapshot (see ``_snap_folded``):
        the replay regenerates only the tail's counters, so the fold
        covers exactly the prefix the restoring child will not recount,
        and a repeat crash off the same snapshot folds nothing new."""
        if not snap or not snap.get("counters"):
            return
        cursor = int(snap.get("cursor", 0))
        with self._migration_lock:
            if self._snap_folded.get(fid) == cursor:
                return
            self._snap_folded[fid] = cursor
            acc = self._folded_counters.setdefault(fid, {})
            for name, v in snap["counters"].items():
                if v:
                    acc[name] = acc.get(name, 0) + int(v)

    def _rebuild_worker_shards(self, worker) -> Dict[str, PlacementView]:
        """Rebuild every shard a freshly-respawned child owns: build from
        the retained spec, restore the micro-snapshot (warm — load_state
        rides the bit-exact chain), replay the WAL tail record by
        record. Raises ``WorkerCrashed`` if the child dies mid-rebuild
        (the caller's retry loop handles it)."""
        from .procworker import SchedulerProxy

        with self._migration_lock:
            owned = [
                (key, fid)
                for key, (fid, _mid, widx) in self._shards.items()
                if widx == worker.worker_id
            ]
        views: Dict[str, PlacementView] = {}
        for key, fid in owned:
            spec = self._specs.get(key)
            snap, records = self._recovery_store.recovery_plan(fid)
            worker.rpc({
                "op": "build",
                "key": key,
                "spec": spec,
                "state": snap["state"] if snap is not None else None,
            })
            # Installed directly (not via create_shard's queued closure):
            # recovery already runs ON this worker's thread.
            worker.shards[key] = SchedulerProxy(worker, key)
            self._fold_snapshot_counters(fid, snap)
            for _cursor, ev in records:
                views[fid] = worker.shards[key].handle(ev)
                self.metrics.inc("events_replayed")
            self.metrics.inc("shards_recovered")
        return views

    def _quarantine_worker(self, worker) -> Dict[str, PlacementView]:
        """Crash-loop breaker open: retire the worker from the ring and
        re-home its shards onto the survivors (consistent hashing moves
        ONLY the dead worker's keys), restoring each from its
        micro-snapshot + WAL tail on the new owner's thread.

        Stale closures already queued on the dead worker's drain forward
        themselves: supervised paths re-resolve the owner at their top
        and round-trip through the new owner's queue."""
        wid = worker.worker_id
        # Ring/worker-list rewrites share _migrate_serial with the
        # autoscaler's spawn/retire (no _migrate_serial holder ever
        # takes a recover lock, so the nesting is acyclic). The lock
        # covers ONLY the attribute flips — the per-shard rebuilds are
        # blocking round trips through other workers' queues and must
        # not park a concurrent scale action behind them; shard entry
        # ownership stays consistent under _migration_lock per entry.
        with self._migrate_serial:
            self._quarantined_workers.append(wid)
            self.metrics.inc("workers_quarantined")
            remaining = [i for i in self.live_worker_ids() if i != wid]
            self.router = ConsistentHashRouter(
                replicas=self.router.replicas, worker_ids=remaining
            )
        with self._migration_lock:
            owned = [
                (key, fid, mid)
                for key, (fid, mid, widx) in self._shards.items()
                if widx == wid
            ]
        views: Dict[str, PlacementView] = {}
        for key, fid, mid in owned:
            spec = self._specs.get(key)
            snap, records = self._recovery_store.recovery_plan(fid)
            tidx = self.router.owner(key)
            target = self.workers[tidx]
            target.create_shard(
                key,
                build=None,
                state=snap["state"] if snap is not None else None,
                spec=spec,
            )
            self._fold_snapshot_counters(fid, snap)

            def _replay(target=target, key=key, recs=records, fid=fid):
                out = None
                for _cursor, ev in recs:
                    out = target.shards[key].handle(ev)
                    self.metrics.inc("events_replayed")
                return out

            v = target.call(_replay)
            if v is not None:
                views[fid] = v
            self.metrics.inc("shards_recovered")
            with self._migration_lock:
                self._shards[key] = (fid, mid, tidx)
        # Retire the slot from the worker's OWN thread (a stop() would
        # join ourselves); queued closures still drain past the sentinel
        # and forward themselves to the new owners.
        worker.retire_crashed()
        with self._migrate_serial:
            self.workers[wid] = None
            self.n_workers = len(remaining)
        return views

    def _record_recovery(self, worker, kind: str, mttr_ms: float, views) -> None:
        """Flight-record the recovery trail with the signals snapshot
        that accompanied it (the chaos contract: every kill's recovery
        is reconstructible from the flight recorder alone)."""
        if self.flight is None:
            return
        sig = None
        if self.timeline is not None:
            try:
                sig = self.signals()
            except Exception:  # dlint: disable=DLP017 the recovery record must land even when signals cannot be built mid-crash (e.g. a second worker down); sig=None records that fact
                sig = None
        sup = self._supervisors.get(worker.worker_id)
        self.flight.record(
            "recovery",
            {
                "t": time.time(),
                "worker": worker.worker_id,
                "action": kind,
                "generation": worker.generation,
                "pid": worker.child_pid,
                "mttr_ms": round(mttr_ms, 3),
                "fleets": sorted(views),
                "crashes_in_window": (
                    sup.crashes_in_window if sup is not None else None
                ),
                "signals": sig,
            },
        )

    def recovery_status(self) -> dict:
        """The supervision tier's audit surface (merged into ``/signals``
        as the ``recovery`` block and probed by chaos_replay).

        ``events_lost`` is the reconciliation: per fleet, the handled
        cursor minus (live + folded) ``events_total`` — every accepted
        event must be accounted for by exactly one application. Zero is
        the contract; positive means lost events, negative double-apply.
        """
        c = self.metrics.counters
        status = {
            "supervised": self._supervise,
            "worker_crashes": c.get("worker_crashes", 0),
            "child_respawns": c.get("child_respawns", 0),
            "shards_recovered": c.get("shards_recovered", 0),
            "events_replayed": c.get("events_replayed", 0),
            "wal_appends": c.get("wal_appends", 0),
            "micro_snapshots": c.get("micro_snapshots", 0),
            "workers_quarantined": c.get("workers_quarantined", 0),
            "quarantined_workers": list(self._quarantined_workers),
        }
        per_fleet = self._per_worker(
            lambda s, _fid: dict(s.metrics.counters)
        )
        lost = 0
        warm = cold = ident = 0
        for fid, cursor in self._handled.items():
            live = per_fleet.get(fid, {})
            folded = self._folded_counters.get(fid, {})
            applied = (
                live.get("events_total", 0)
                + folded.get("events_total", 0)
            )
            lost += cursor - applied
            warm += live.get("warm_resumes", 0) + folded.get("warm_resumes", 0)
            cold += live.get("cold_resumes", 0) + folded.get("cold_resumes", 0)
            # A restore whose first tick changed identity (structural
            # event replayed first) proves nothing about warmth and
            # counts as neither warm nor cold — surfaced so the crash
            # contract can still reconcile one resume per recovery.
            ident += live.get("resume_identity_changed", 0) + folded.get(
                "resume_identity_changed", 0
            )
        status["events_lost"] = lost
        status["warm_resumes"] = warm
        status["cold_resumes"] = cold
        status["identity_resumes"] = ident
        lat = self.metrics.snapshot().get("latency", {})
        mttr = lat.get("recovery_mttr_ms")
        if mttr:
            status["mttr_p50_ms"] = mttr.get("p50_ms")
            status["mttr_p99_ms"] = mttr.get("p99_ms")
        return status

    def chaos_process_hook(self, fleet_id: str):
        """The ``chaos_replay`` bridge for process-level faults: returns
        ``hook(kind, spec)`` that aims each fault at the CURRENT owner
        of ``fleet_id``'s shard (a kill may have re-homed it since the
        last fault)."""
        def hook(kind: str, spec) -> None:
            key = self._fleet_key[fleet_id]
            with self._migration_lock:
                worker = self.workers[self._shards[key][2]]
            if kind == "child_kill":
                worker.kill_child()
            elif kind == "rpc_torn":
                worker.inject_torn_frame()
            elif kind == "rpc_delay":
                worker.inject_rpc_delay(
                    getattr(spec, "delay_s", 0.05) or 0.05
                )
            else:
                raise ValueError(f"unknown process fault kind {kind!r}")

        return hook

    def _resolve_waiters(self, waiters, shared: dict) -> None:
        """Resolve a batch's waiters with one shared outcome (result or
        exc); a dead completion callback must not kill the caller's
        thread (same contract as ``ShardWorker._run``)."""
        for w_box, w_done, w_on_done in waiters:
            w_box.update(shared)
            w_done.set()
            if w_on_done is not None:
                try:
                    w_on_done(w_box)
                except Exception:
                    self.metrics.inc("worker_callback_error")

    def _combine_deliver(self, fleet_id, key, worker: ShardWorker, ticket, waiters):
        """The combiner's per-lane delivery callback: queue the shard's
        ``adopt_combine`` back onto its OWN worker (scatter), resolve the
        batch's waiters with the adopted view, then un-park the batch
        that accumulated behind the in-flight lane."""

        def deliver(decoded, err) -> None:
            def _adopt() -> None:
                shared: dict = {}
                try:
                    shared["result"] = worker.shards[key].adopt_combine(
                        ticket, decoded, error=err
                    )
                except BaseException as e:
                    self.metrics.inc("worker_exception")
                    shared["exc"] = e
                finally:
                    self._release_combine(fleet_id, key, worker)
                    self._resolve_waiters(waiters, shared)

            try:
                worker.submit(_adopt)
            except BaseException as e:
                # Worker already stopping (shutdown race): the lane
                # cannot be adopted; resolve the waiters with the error
                # so nothing blocks forever.
                self.metrics.inc("worker_exception")
                with self._admission_lock:
                    self._combine_inflight.pop(key, None)
                self._resolve_waiters(
                    waiters, {"exc": err if err is not None else e}
                )

        return deliver

    def _release_combine(self, fleet_id, key, worker: ShardWorker) -> None:
        """Clear a shard's in-flight combine marker and submit the drain
        of any batch that parked behind it (runs on the worker thread at
        the end of the adopt closure)."""
        parked_waiters = None
        shed_shared = None
        with self._admission_lock:
            self._combine_inflight.pop(key, None)
            batch = self._pending.get(key)
            if batch is None or not batch.get("parked"):
                return
            batch["parked"] = False
            parent, t_enq, depth = batch.pop("args")
            closure = self._batch_closure(
                fleet_id, key, worker, batch, parent, t_enq, depth
            )
            try:
                worker.submit(closure, bound=self.max_queue_depth)
            except WorkerQueueFull as e:  # dlint: disable=DLP017 accounted inside _shed (events_shed + per-fleet tally + flight record); the QueueFull is handed back to every parked waiter, not swallowed
                del self._pending[key]
                parked_waiters = list(batch["waiters"])
                shed_shared = {
                    "exc": self._shed(
                        fleet_id, batch["events"][-1], worker, e.depth
                    )
                }
        if parked_waiters is not None:
            self._resolve_waiters(parked_waiters, shed_shared)

    def _shed(self, fleet_id: str, event, worker: ShardWorker, depth: int) -> QueueFull:
        """Account one shed, then hand back the exception to raise.

        Every shed is (1) counted — ``events_shed`` plus the per-fleet
        tally ``shed_counts()`` — and (2) flight-recorded with a monotone
        per-fleet ``shed_index``, so counters and records reconcile
        record by record even after the bounded ring overflows (the
        contract ``traffic.shed_violations`` audits). ``retry_after_s``
        estimates when the backlog drains: depth x the EWMA of recent
        event-to-placement latency.
        """
        self.metrics.inc("events_shed")
        with self._shed_lock:
            idx = self._shed_counts.get(fleet_id, 0) + 1
            self._shed_counts[fleet_id] = idx
        ewma_ms = self._serve_ewma_ms
        retry_after = min(
            30.0, max(0.05, depth * ((ewma_ms or 1000.0) / 1e3))
        )
        if self.flight is not None:
            self.flight.record(
                fleet_id,
                {
                    "shed": True,
                    "shed_index": idx,
                    "fleet": fleet_id,
                    "kind": getattr(event, "kind", type(event).__name__),
                    "worker": worker.worker_id,
                    "depth": depth,
                    "retry_after_s": round(retry_after, 4),
                },
            )
        return QueueFull(fleet_id, depth, retry_after)

    def shed_counts(self) -> Dict[str, int]:
        """Per-fleet shed tallies (reconciled against flight records)."""
        with self._shed_lock:
            return dict(self._shed_counts)

    # -- dynamic fleet: spawn / retire / live migration --------------------
    #
    # All three verbs require dynamic=True (the static hot path takes no
    # migration gate) and are serialized by one lock: two in-flight
    # flips in opposite directions would deadlock their worker threads
    # on each other's load round trips, and the autoscaler is
    # single-threaded anyway.

    def _require_dynamic(self) -> None:
        if not self._dynamic:
            raise RuntimeError(
                "live topology changes need a dynamic gateway "
                "(Gateway(..., dynamic=True))"
            )
        if self.combine:
            raise RuntimeError(
                "live topology changes are unsupported with the "
                "cross-shard combiner on"
            )

    def spawn_worker(self) -> Tuple[int, List[str]]:
        """Add one worker; rebalance the ring onto it via live migration.

        Returns ``(worker_id, moved shard keys)``. The new worker takes
        ~1/N of the ring (consistent hashing), and every moved shard
        arrives warm: its pool and published placement ride the
        bit-exact snapshot blob through ``migrate_shard``.
        """
        self._require_dynamic()
        with self._migrate_serial:
            widx = len(self.workers)
            self.workers.append(self._make_worker(widx))
            self.n_workers = len(self.live_worker_ids())
            self.router = ConsistentHashRouter(
                replicas=self.router.replicas,
                worker_ids=self.live_worker_ids(),
            )
            self.metrics.inc("workers_spawned")
            moved = self._rebalance()
            self._refresh_capacity()
            return widx, moved

    def retire_worker(self, widx: Optional[int] = None) -> Tuple[int, List[str]]:
        """Drain one worker (default: highest id) and stop it.

        Its ring slices — and only its — move to the survivors first
        (live migrations, warm), then the worker stops. The slot stays
        ``None`` so remaining worker ids keep their stable ring labels.
        """
        self._require_dynamic()
        with self._migrate_serial:
            live = self.live_worker_ids()
            if len(live) <= 1:
                raise RuntimeError("cannot retire the last worker")
            if widx is None:
                widx = live[-1]
            worker = self.workers[widx] if 0 <= widx < len(self.workers) else None
            if worker is None:
                raise ValueError(f"worker {widx} is not live")
            remaining = [w for w in live if w != widx]
            self.router = ConsistentHashRouter(
                replicas=self.router.replicas, worker_ids=remaining
            )
            moved = self._rebalance()
            worker.stop(join=True)
            self.workers[widx] = None
            self.n_workers = len(remaining)
            self.metrics.inc("workers_retired")
            self._refresh_capacity()
            return widx, moved

    def _rebalance(self) -> List[str]:
        """Migrate every shard whose ring owner changed. Caller holds
        ``_migrate_serial``."""
        moved: List[str] = []
        for key, (fid, _mid, cur) in list(self._shards.items()):
            target = self.router.owner(key)
            if target != cur:
                self._migrate_shard_locked(fid, target)
                moved.append(key)
        return moved

    def migrate_shard(self, fleet_id: str, dst_widx: int) -> None:
        """Move one fleet's shard to another worker with zero cold ticks.

        Two phases. **Prefetch** (source keeps serving): snapshot the
        shard behind whatever is queued, build + warm-load the
        destination copy — the expensive part (scheduler build, first
        compile) happens entirely off the serving path. **Flip**: mark
        the shard migrating (ingest parks — no closure queued anywhere),
        queue the flip on the source; it runs after every tick admitted
        before parking, dumps the now-quiescent final state, loads it
        into the destination (re-arming the warm-resume audit: the blob
        is the authority, the prefetch was advisory), flips routing, and
        replays parked events onto the destination in arrival order
        before the gate clears. No event is lost, none applies twice,
        and the destination's first tick is warm — ``warm_resumes``
        advances by exactly one per migrated shard, ``cold_resumes`` and
        ``tick_cold`` by zero.

        On a flip failure the gate clears with routing unchanged and
        parked events replay onto the still-intact source — the
        migration failed, serving did not.
        """
        self._require_dynamic()
        with self._migrate_serial:
            self._migrate_shard_locked(fleet_id, dst_widx)

    def _migrate_shard_locked(self, fleet_id: str, dst_widx: int) -> None:
        key = self._fleet_key.get(fleet_id)
        if key is None:
            raise KeyError(f"unknown fleet {fleet_id!r}")
        fid, mid, src_widx = self._shards[key]
        if dst_widx == src_widx:
            return
        src = self.workers[src_widx]
        dst = (
            self.workers[dst_widx]
            if 0 <= dst_widx < len(self.workers)
            else None
        )
        if dst is None:
            raise ValueError(f"worker {dst_widx} is not live")

        # Phase 1 — prefetch: base snapshot + destination build, source
        # still serving every tick. The source's cumulative counters ride
        # along: if the flip later aborts because the source CHILD died,
        # this prefetch is the last readable copy of them (counters are
        # live-copy-only — they do not ride the dump blob).
        def _prefetch(w=src, k=key):
            s = w.shards[k]
            return s.dump_state(), dict(s.metrics.counters)

        base, pre_counters = src.call(_prefetch)
        spec = self._spec_from_blob(base, fid)
        dst.create_shard(
            key,
            build=lambda: self._build_from_blob(base, fid),
            state=base,
            spec=spec,
        )

        # Phase 2 — park and flip.
        with self._migration_lock:
            self._migrating[key] = {"parked": []}
        abort = {"src_lost": False}

        def _flip():
            ok = False
            try:
                try:
                    state = src.shards[key].dump_state()
                except WorkerCrashed:
                    # The SOURCE child died under the flip dump: its
                    # counters are gone with it — the abort path below
                    # folds the prefetched copy so the fleet's totals
                    # survive the crash. (A dst-side failure must NOT
                    # set this: the source still serves, and folding a
                    # still-counting copy would double count.)
                    abort["src_lost"] = True
                    raise
                dst.load_shard(key, state)
                ok = True
            finally:
                with self._migration_lock:
                    mig = self._migrating.pop(key, None)
                    if ok:
                        self._shards[key] = (fid, mid, dst_widx)
                    target = self.workers[self._shards[key][2]]
                    parked = mig["parked"] if mig else []
                    for rec in parked:
                        self._submit_parked(fid, key, target, rec)
                if ok:
                    # The source copy is inert (nothing routes to it);
                    # fold its counters into the fleet's running totals
                    # (they do not ride the blob), then drop it off the
                    # gate, still on the source thread.
                    stale = src.shards.pop(key)
                    counters = dict(stale.metrics.counters)
                    with self._migration_lock:
                        acc = self._folded_counters.setdefault(fid, {})
                        for name, v in counters.items():
                            if v:
                                acc[name] = acc.get(name, 0) + v
                    stale.close()
            return len(parked)

        try:
            parked_n = src.call(_flip)
        except BaseException:
            # Failed flip: best-effort drop of the prefetched copy.
            self.metrics.inc("migration_failed")
            if abort["src_lost"]:
                # Source child crashed mid-migration: fold the Phase-1
                # prefetched counters so the fleet's cumulative totals
                # are not silently dropped with the dead child. (Events
                # ticked between prefetch and crash are covered by the
                # supervision tier's own snapshot fold when it is on.)
                with self._migration_lock:
                    acc = self._folded_counters.setdefault(fid, {})
                    for name, v in pre_counters.items():
                        if v:
                            acc[name] = acc.get(name, 0) + v
            try:
                dst.drop_shard(key)
            except Exception:  # dlint: disable=DLP017 the flip failure was counted (migration_failed) and re-raises below; this drop is best-effort cleanup of the never-published prefetch copy
                pass
            raise
        self.metrics.inc("shards_migrated")
        if spec is not None:
            # The shard moved: future crash recovery rebuilds it on the
            # destination from this (identical) spec.
            self._specs[key] = spec
        if self.flight is not None:
            self.flight.record(
                "migration",
                {
                    "t": time.time(),
                    "fleet": fid,
                    "shard": key,
                    "src": src_widx,
                    "dst": dst_widx,
                    "parked": parked_n,
                },
            )

    def _submit_parked(self, fleet_id, key, worker, rec) -> None:
        """Replay one parked event onto the post-flip owner, resolving
        the waiter that has been parked since ingest."""
        event, parent, t_enq, on_done, box, done = rec
        inner = self._tick_closure(
            fleet_id, key, worker, event, parent=parent, t_enq=t_enq
        )

        def _do():
            shared: dict = {}
            try:
                shared["result"] = inner()
            except BaseException as e:
                self.metrics.inc("worker_exception")
                shared["exc"] = e
            finally:
                self._resolve_waiters([(box, done, on_done)], shared)

        worker.submit(_do)

    def _build_from_blob(self, blob: dict, fleet_id: str):
        """Rebuild a shard's scheduler from its snapshot's own fleet
        profile (migration has no caller-supplied devices/model)."""
        if self._factory is not None:
            # A factory owns its own devices/model contract — the blob's
            # values pass through exactly as dump_state recorded them.
            return self._build_scheduler(
                blob["devices"], blob.get("model"), fleet_id
            )
        devices = [
            DeviceProfile.model_validate(d) for d in blob["devices"]
        ]
        model = (
            ModelProfile.model_validate(blob["model"])
            if blob.get("model") is not None
            else None
        )
        return self._build_scheduler(devices, model, fleet_id)

    def _spec_from_blob(self, blob: dict, fleet_id: str) -> Optional[dict]:
        if self.worker_backend != "process":
            return None
        return {
            "devices": list(blob["devices"]),
            "model": blob.get("model"),
            "fleet_id": fleet_id,
            "kwargs": dict(self.scheduler_kwargs),
            "factory": (
                self._factory if isinstance(self._factory, str) else None
            ),
        }

    # -- controller actuation seams ----------------------------------------

    def force_degrade(self, on: bool) -> None:
        """Mark every tick under PRESSURE (spec_near serving) regardless
        of queue depth — the autoscaler's fast, reversible lever while a
        spawned worker warms. Off restores the static admission verdict
        exactly."""
        self._forced_pressure = bool(on)

    def set_spec_k(self, k: int) -> None:
        """Set ``spec_k`` on every live shard (ON each worker thread; a
        process worker forwards per shard over its RPC)."""
        for w in self.live_workers():
            def _do(w=w):
                for sched in w.shards.values():
                    sched.spec_k = k

            w.call(_do)

    def note_capacity(self, eps: float, n_workers: Optional[int] = None) -> None:
        """Record the closed-loop capacity probe: ``eps`` sustainable at
        ``n_workers`` (default: current live count). The per-worker
        quotient is kept so ``capacity_eps`` refreshes deterministically
        on every spawn/retire — no live re-probe inside the control loop
        (replay must stay a pure function of timeline + policy)."""
        n = n_workers if n_workers is not None else len(self.live_workers())
        with self._migrate_serial:
            self._capacity_per_worker = eps / max(1, n)
            self.capacity_eps = eps

    def _refresh_capacity(self) -> None:
        if self._capacity_per_worker is not None:
            self.capacity_eps = self._capacity_per_worker * len(
                self.live_workers()
            )

    def attach_controller(self, loop) -> None:
        """Attach a running ControlLoop; stopped with the samplers on
        close() (before the workers — an actuation mid-close must not
        land on a stopping worker)."""
        self._controller = loop
        self.attach_sampler(loop)

    def control_status(self) -> dict:
        """The /control payload: live topology + the decision trail."""
        actions: List[dict] = []
        if self.flight is not None and "control" in self.flight.keys():
            actions = [dict(r) for r in self.flight.snapshot("control")]
        return {
            "enabled": self._controller is not None,
            "dynamic": self._dynamic,
            "worker_backend": self.worker_backend,
            "workers": self.live_worker_ids(),
            "capacity_eps": self.capacity_eps,
            "forced_degrade": self._forced_pressure,
            "migrations": int(
                self.metrics.counters.get("shards_migrated", 0)
            ),
            "supervised": self._supervise,
            "quarantined_workers": list(self._quarantined_workers),
            "actions": actions,
        }

    def handle_event(self, fleet_id: str, event) -> PlacementView:
        """Apply one event to its fleet's shard; blocks for the view.

        Latency observed here (``gateway_event_to_placement``) includes
        the queue wait on the owning worker — the number a client sees,
        not just the solve. Raises ``QueueFull`` when admission control
        sheds the event (already counted and flight-recorded).
        """
        span = self.tracer.start_span(
            "gateway.ingest", parent=None, attrs={"fleet": fleet_id}
        )
        try:
            t0 = time.perf_counter()
            key, worker = self._lookup(fleet_id)
            self.tracer.record_span(
                "gateway.route",
                t0 * 1e3,
                None,
                parent=span.context(),
                attrs={"shard": key, "worker": worker.worker_id},
            )
            box, done = self._submit_tick(
                fleet_id, key, worker, event,
                parent=span.context(), t_enq=t0 * 1e3,
            )
            done.wait()
            if "exc" in box:
                raise box["exc"]
            view = box["result"]
            self._note_handled(worker, t0)
            return view
        finally:
            span.end()

    async def handle_event_async(
        self, fleet_id: str, event, parent=None
    ) -> PlacementView:
        """Asyncio ingest: enqueue on the owning worker, await the view.

        Completion resolves a loop future via ``call_soon_threadsafe`` —
        no executor thread parked per in-flight event, so thousands of
        fleets can await concurrently over a handful of workers.

        ``parent`` is an optional ``SpanContext`` (the HTTP tier's request
        span). Parenting here is EXPLICIT — on the shared loop thread a
        thread-local "current span" would leak between interleaved
        coroutines and mis-parent concurrent fleets' spans.
        """
        span = self.tracer.start_span(
            "gateway.ingest", parent=parent, attrs={"fleet": fleet_id}
        )
        try:
            # t0 BEFORE the lookup, like the sync path: the route span
            # must actually time the shard resolution, not measure ~0.
            t0 = time.perf_counter()
            key, worker = self._lookup(fleet_id)
            self.tracer.record_span(
                "gateway.route",
                t0 * 1e3,
                None,
                parent=span.context(),
                attrs={"shard": key, "worker": worker.worker_id},
            )
            loop = asyncio.get_running_loop()
            fut: "asyncio.Future" = loop.create_future()

            def _resolve(box: dict) -> None:
                if fut.cancelled():
                    return
                if "exc" in box:
                    fut.set_exception(box["exc"])
                else:
                    fut.set_result(box["result"])

            self._submit_tick(
                fleet_id, key, worker, event,
                parent=span.context(), t_enq=t0 * 1e3,
                on_done=lambda box: loop.call_soon_threadsafe(_resolve, box),
            )
            view = await fut
            self._note_handled(worker, t0)
            return view
        finally:
            span.end()

    def _note_handled(self, worker: ShardWorker, t0: float) -> None:
        """Caller-side observability only (the resume cursor moved on the
        worker thread, inside the tick closure)."""
        ms = (time.perf_counter() - t0) * 1e3
        self.metrics.inc("gateway_events")
        self.metrics.inc(f"worker_{worker.worker_id}_events")
        self.metrics.observe("gateway_event_to_placement", ms)
        if self._admission:
            # Retry-After's input: a cheap EWMA of what one event costs
            # end to end. Racy float write, deliberately unlocked — it is
            # a backoff hint, not an accounting counter.
            prev = self._serve_ewma_ms
            self._serve_ewma_ms = (
                ms if prev is None else 0.9 * prev + 0.1 * ms
            )

    def latest(self, fleet_id: str) -> PlacementView:
        """The fleet's most recent published placement (via its worker, so
        it never races a tick in flight)."""
        key, worker = self._lookup(fleet_id)
        return worker.call(lambda: worker.shards[key].latest())

    # -- observability -----------------------------------------------------

    def _per_worker(self, extract) -> Dict[str, dict]:
        """Run ``extract(scheduler, fleet_id)`` for every shard, ONE
        queued round trip per worker (not per shard — with hundreds of
        shards a per-shard loop would pay hundreds of FIFO waits behind
        in-flight solves for a single observability probe). The closure
        runs ON the worker thread, behind everything already queued, so
        anything it reads (shard state, the resume cursor that tick
        closures bump) is observed at one consistent point of that
        worker's timeline. Returns fleet_id -> value.
        """
        by_worker: Dict[int, List[Tuple[str, str]]] = {}
        for key, (fleet_id, _mid, widx) in self._shards.items():
            by_worker.setdefault(widx, []).append((key, fleet_id))
        out: Dict[str, dict] = {}
        for widx, members in by_worker.items():
            worker = self.workers[widx]

            def _collect(w=worker, ms=members) -> dict:
                return {fid: extract(w.shards[k], fid) for k, fid in ms}

            if threading.current_thread() is worker._thread:
                # Re-entrant probe FROM a worker thread (the recovery
                # trail snapshots /signals mid-closure): a queued round
                # trip to ourselves would deadlock — run inline; the
                # read is mid-closure rather than at a tick boundary,
                # which is exactly what a crash-time snapshot wants.
                out.update(_collect())
            else:
                out.update(worker.call(_collect))
        return out

    def healthz(self) -> dict:
        """Per-shard health + the worst state as the overall verdict."""
        rank = {HEALTH_HEALTHY: 0, HEALTH_DEGRADED: 1, HEALTH_BROKEN: 2}
        worst = HEALTH_HEALTHY
        shards = self._per_worker(lambda s, _fid: s.health_snapshot())
        for key, (fleet_id, model_id, widx) in self._shards.items():
            snap = shards[fleet_id]
            snap["worker"] = widx
            snap["model_id"] = model_id
            if rank.get(snap["state"], 2) > rank[worst]:
                worst = snap["state"]
        return {
            "status": worst,
            "workers": self.n_workers,
            "shards": shards,
            "queue_depths": [w.depth() for w in self.live_workers()],
        }

    def metrics_snapshot(self) -> dict:
        """Gateway counters/latency + per-shard aggregates, plain dicts."""
        agg: Dict[str, int] = {}
        per_shard: Dict[str, dict] = {}
        all_counters = self._per_worker(
            lambda s, _fid: dict(s.metrics.counters)
        )
        with self._migration_lock:
            folded = {
                f: dict(c) for f, c in self._folded_counters.items()
            }
        for fleet_id, counters in all_counters.items():
            # Counters of copies this fleet's migrations retired: the
            # live copy starts fresh, the totals stay cumulative.
            for name, v in folded.get(fleet_id, {}).items():
                counters[name] = counters.get(name, 0) + v
            per_shard[fleet_id] = {
                c: counters.get(c, 0)
                for c in _AGGREGATED_SHARD_COUNTERS
                if counters.get(c, 0)
            }
            for c in _AGGREGATED_SHARD_COUNTERS:
                agg[c] = agg.get(c, 0) + counters.get(c, 0)
        snap = self.metrics.snapshot()
        snap["shard_totals"] = agg
        snap["per_shard"] = per_shard
        snap["workers"] = self.n_workers
        snap["shards"] = len(self._shards)
        return snap

    def shard_metrics_snapshot(self, fleet_id: str) -> dict:
        """One shard's metrics snapshot with the fleet's FOLDED counters
        merged in: migrations and crash recoveries retire scheduler
        copies whose counters fold gateway-side, and a per-shard audit
        (chaos reconciliation, the walkthrough's counter reads) needs
        the cumulative view, not just the live copy's. A fleet that
        never migrated or crashed merges nothing — byte-identical."""
        snap = self.read_shard(fleet_id, lambda s: s.metrics_snapshot())
        with self._migration_lock:
            folded = dict(self._folded_counters.get(fleet_id, {}))
        if folded:
            counters = snap.get("counters", {})
            for name, v in folded.items():
                counters[name] = counters.get(name, 0) + v
        return snap

    def prometheus_text(self) -> str:
        """Prometheus v0.0.4 text: per-shard metrics with
        ``{fleet,shard,worker,health}`` labels + gateway-level counters.

        The ``GET /metrics`` content-negotiated rendering: per-shard
        counters surface as labeled samples instead of being summed away
        (the JSON snapshot's ``shard_totals`` loses exactly the per-shard
        split a dashboard needs to see ONE broken fleet). One queued round
        trip per worker, same consistency argument as ``_per_worker``.
        """
        from ..obs.export import render_prometheus

        per_shard = self._per_worker(
            lambda s, _fid: (s.metrics.snapshot(), s.health)
        )
        entries = []
        for key, (fleet_id, _mid, widx) in self._shards.items():
            snap, health = per_shard[fleet_id]
            entries.append(
                {
                    "fleet": fleet_id,
                    "shard": key,
                    "worker": widx,
                    "health": health,
                    "counters": snap["counters"],
                    "latency": snap["latency"],
                }
            )
        gw = self.metrics.snapshot()
        return render_prometheus(
            entries,
            gateway_counters=gw["counters"],
            gateway_latency=gw["latency"],
            # Live queue depth per worker: THE admission-control input as
            # a labeled gauge, next to the counters it explains (a scrape
            # that sees events_shed climbing reads the depth that caused
            # it in the same exposition).
            worker_gauges={
                "worker_queue_depth": {
                    str(w.worker_id): w.depth()
                    for w in self.live_workers()
                }
            },
        )

    def attach_sampler(self, sampler):
        """Register a background observer thread (timeline sampler, prom
        scraper — anything with ``.stop(join=True)``) for teardown:
        ``close()`` stops every attached sampler before the workers, so
        the observer can never probe a stopping worker. Returns the
        sampler for chaining."""
        self._samplers.append(sampler)
        return sampler

    def attach_slo(
        self, engine, timeline, capacity_eps: Optional[float] = None
    ) -> None:
        """Install the SLO engine + timeline this gateway serves on
        ``GET /slo`` / ``GET /signals`` (see ``obs.slo``). The caller
        owns sampler construction (and usually attaches it via
        ``attach_sampler``); this only wires the read surface."""
        self.slo_engine = engine
        self.timeline = timeline
        if capacity_eps is not None:
            # Route through note_capacity: same lock, and the per-worker
            # quotient stays consistent if the fleet later goes dynamic.
            self.note_capacity(capacity_eps)

    def timeline_sample(self) -> Dict[str, float]:
        """One flat ``{series: value}`` sample for the metrics timeline:
        gateway counters (``c.<name>``), per-shard aggregate counters
        (``shards.<name>``), gateway latency quantiles (``lat.<hist>.*``)
        and the live per-worker queue depths (``queue_depth.w<i>`` — the
        admission-control input the /signals trend derives from). One
        ``metrics_snapshot`` round trip per worker per tick; that cost
        is exactly what the bench's slo section gates at <= 5%."""
        from ..obs.timeline import flatten_metrics_snapshot

        snap = self.metrics_snapshot()
        out = flatten_metrics_snapshot(snap)
        for name, value in snap.get("shard_totals", {}).items():
            out[f"shards.{name}"] = float(value)
        # The availability SLO's inputs always exist, zero-valued before
        # the first event: a counter minted mid-incident would otherwise
        # have no pre-incident baseline sample, and the burst's delta
        # would be invisible to every window that needs it most.
        out.setdefault("c.gateway_events", 0.0)
        out.setdefault("c.events_shed", 0.0)
        # Offered = accepted + shed: the availability SLO's denominator
        # (a shed never reaches gateway_events, and an error ratio over
        # accepted-only would understate a shedding gateway's burn).
        out["c.events_offered"] = out["c.gateway_events"] + out["c.events_shed"]
        depths = []
        for w in self.live_workers():
            d = w.depth()
            depths.append(d)
            out[f"queue_depth.w{w.worker_id}"] = float(d)
        out["queue_depth.max"] = float(max(depths) if depths else 0)
        if self._dynamic:
            # The controller's own series, only on dynamic gateways so
            # static samples stay byte-identical: live worker count is
            # the signal a replayed decision trail is audited against.
            out["control.workers"] = float(len(depths))
        if self._supervise:
            # Recovery series, only on supervised gateways (same
            # byte-identical argument): zero-valued from the first
            # sample so a kill's delta has a pre-incident baseline.
            for name in (
                "c.worker_crashes",
                "c.child_respawns",
                "c.events_replayed",
                "c.shards_recovered",
                "c.workers_quarantined",
            ):
                out.setdefault(name, 0.0)
        from ..obs import compile_ledger as _cl

        led = _cl.current()
        if led is not None:
            # Process-wide compile telemetry (the ledger sees every
            # worker thread's compiles, attributed or not); the series
            # set is timeline_series's one definition, shared with
            # Scheduler.timeline_sample so the two serving shapes'
            # names cannot drift.
            out.update(led.timeline_series())
        from ..obs import memory as _mem

        mled = _mem.current()
        if mled is not None:
            # mem.* watermark gauges (obs.memory.timeline_series — same
            # one-definition contract as the compile series): live-array
            # bytes by platform, RSS/HWM, headroom. Absent when
            # unavailable, never zeroed; feature-off byte-identical.
            out.update(mled.timeline_series())
        return out

    def slo_status(self) -> dict:
        """The ``GET /slo`` payload (KeyError -> HTTP 404 when no SLO
        engine is attached — same contract as the flight endpoint)."""
        if self.slo_engine is None:
            raise KeyError("SLO engine not enabled (serve --slo <spec>)")
        return self.slo_engine.status()

    def signals(self) -> dict:
        """The ``GET /signals`` autoscaling payload (versioned, schema'd
        by ``obs.slo.SignalsPayload``)."""
        if self.timeline is None:
            raise KeyError(
                "signals need a metrics timeline (serve --slo <spec> "
                "or --timeline-dir; burn rates need --slo)"
            )
        from ..obs.slo import build_signals

        return build_signals(
            self.timeline,
            engine=self.slo_engine,
            capacity_eps=self.capacity_eps,
            combine=(
                self._combiner.snapshot()
                if self._combiner is not None
                else None
            ),
            recovery=(
                self.recovery_status() if self._supervise else None
            ),
        ).model_dump()

    def flight_snapshot(self, fleet_id: str) -> List[dict]:
        """The fleet's live flight-recorder ring (``GET /debug/flight/<fleet>``)."""
        if self.flight is None:
            raise KeyError(
                "flight recorder not enabled (serve --flight-dir)"
            )
        if fleet_id not in self._fleet_key:
            raise KeyError(f"unknown fleet {fleet_id!r}")
        return self.flight.snapshot(fleet_id)

    # -- snapshot / restore ------------------------------------------------

    def snapshot(self) -> GatewaySnapshot:
        """Drain every worker and serialize every shard's warm state.

        The dump runs ON each worker thread behind whatever events are
        already queued — a natural barrier: the snapshot observes each
        shard after its last accepted event, never mid-tick. New events
        submitted while snapshotting land after the dump and are NOT in
        the snapshot (their replay is the restore side's job).
        """
        # State AND resume cursor are captured in ONE worker-thread
        # closure: the cursor moves inside queued tick closures, so
        # reading both on the worker guarantees they describe the same
        # point of the shard's timeline even while async ingest keeps
        # submitting (a caller-side cursor read could observe a tick the
        # dump did not, and a resume would then skip an uncovered event).
        states = self._per_worker(
            lambda s, fid: (s.dump_state(), self._handled.get(fid, 0))
        )
        shards: List[ShardSnapshot] = []
        for key, (fleet_id, model_id, _widx) in self._shards.items():
            state, cursor = states[fleet_id]
            shards.append(
                ShardSnapshot(
                    fleet_id=fleet_id,
                    model_id=model_id,
                    shard_key=key,
                    events_handled=cursor,
                    state=state,
                )
            )
        self.metrics.inc("snapshots_taken")
        return GatewaySnapshot(
            n_workers=self.n_workers,
            shards=shards,
            counters=self.metrics.snapshot()["counters"],
        )

    def load_snapshot(self, snap: GatewaySnapshot) -> None:
        """Restore every shard from a snapshot into THIS gateway.

        Worker count may differ from the producing gateway's: shards
        re-route by the current consistent-hash ring, warm state riding
        the blob to the new owner. Must be called before any events are
        ingested (restore is a boot-time operation, not a live merge).
        """
        if self._shards:
            raise RuntimeError(
                "load_snapshot needs a fresh gateway (shards already "
                "registered)"
            )
        for shard in snap.shards:
            if self._factory is not None:
                # A factory owns its own devices/model contract — the
                # blob's raw values pass through (mirrors
                # ``_build_from_blob``; no profile validation).
                devices = shard.state["devices"]
                model = shard.state["model"]
            else:
                devices = [
                    DeviceProfile.model_validate(d)
                    for d in shard.state["devices"]
                ]
                model = ModelProfile.model_validate(shard.state["model"])
            self.register_fleet(
                shard.fleet_id,
                devices,
                model,
                model_id=shard.model_id,
                state=shard.state,
                events_handled=shard.events_handled,
            )

    def events_handled(self, fleet_id: str) -> int:
        """This fleet's resume cursor (events handled, quarantines
        included) — restored from the snapshot on the other side."""
        return self._handled.get(fleet_id, 0)

    def uncovered(self, items: Sequence[Tuple[str, object]]):
        """The suffix of a trace the resume cursors do NOT cover.

        THE one implementation of the resume-skip contract (CLI,
        walkthrough and tests all route through it): for each fleet, skip
        its first ``events_handled(fleet)`` items — handled counts
        quarantined events too (they advanced the cursor without the
        fleet seq, and replaying them would only repeat the rejection).
        """
        seen: Dict[str, int] = {}
        out: List[Tuple[str, object]] = []
        for fleet_id, ev in items:
            seen[fleet_id] = seen.get(fleet_id, 0) + 1
            if seen[fleet_id] > self._handled.get(fleet_id, 0):
                out.append((fleet_id, ev))
        return out

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Stop attached samplers, then every worker (graceful: queued
        work drains first). Idempotent — CLI finally blocks, harness
        teardowns and ``with`` exits may all call it.

        Sampler order matters: an attached prom scraper or timeline
        sampler probes the workers on its own thread, and a probe landing
        after a worker stopped would count a scrape error on a perfectly
        clean shutdown (the PR 8 bench re-learned this per harness; now
        the gateway owns the ordering). ``stop()`` on a sampler is
        required idempotent, so a harness that already stopped its own
        sampler is fine."""
        if self._closed:
            return
        self._closed = True
        for sampler in self._samplers:
            try:
                sampler.stop()
            except Exception:
                # A sampler that fails to stop must not leak workers; the
                # failure is counted, teardown continues.
                self.metrics.inc("timeline_sample_error")
        if self._combiner is not None:
            # Before the workers: the drain's deliveries queue adopt
            # closures on still-running workers, and the workers' own
            # graceful stop then drains those.
            self._combiner.stop()
        for w in self.live_workers():
            w.stop()
        if self._recovery_store is not None:
            self._recovery_store.close()
        if self._recovery_tmpdir is not None:
            import shutil

            shutil.rmtree(self._recovery_tmpdir, ignore_errors=True)

    def __enter__(self) -> "Gateway":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ShardFacade:
    """A single gateway shard masquerading as a bare ``Scheduler``.

    The serve CLI's replay and chaos harnesses (``sched.sim.replay``,
    ``sched.faults.chaos_replay``) drive a scheduler-shaped object:
    ``handle``/``latest``/``metrics``/``fleet``/``health``/``fault_hook``.
    This facade routes ``handle`` through the owning worker's queue (so
    the multi-worker path is what is actually exercised) and — fixing the
    PR 7 quiescence hazard — routes every READ through a queued
    worker-side closure too (``Gateway.read_shard``), so harness reads
    are sound under live ingest, not only while the worker is quiescent:
    a read lands behind every queued tick and observes the shard at a
    tick boundary. ``.fleet`` returns a ``FleetReadView`` captured in one
    closure (seq, model, membership AND the published seq from the same
    instant — the consistency the concurrent-ingest test pins);
    ``.metrics`` returns the live thread-safe sink, the round trip being
    the sequencing point.
    """

    def __init__(self, gateway: Gateway, fleet_id: str):
        object.__setattr__(self, "_gw", gateway)
        object.__setattr__(self, "_fleet", fleet_id)

    def _read(self, fn):
        return self._gw.read_shard(self._fleet, fn)

    def handle(self, event) -> PlacementView:
        return self._gw.handle_event(self._fleet, event)

    def latest(self) -> PlacementView:
        return self._gw.latest(self._fleet)

    def metrics_snapshot(self) -> dict:
        # Routed through the gateway so counters folded from retired
        # scheduler copies (migrations, crash recoveries) stay in the
        # fleet's totals; without folds this is the plain shard read.
        return self._gw.shard_metrics_snapshot(self._fleet)

    def health_snapshot(self) -> dict:
        return self._read(lambda s: s.health_snapshot())

    def close(self) -> None:
        """No-op: the gateway owns worker/scheduler lifecycle."""

    @property
    def metrics(self):
        return self._read(lambda s: s.metrics)

    @property
    def fleet(self) -> FleetReadView:
        def _capture(s) -> FleetReadView:
            if hasattr(s, "fleet_view"):
                # Process-backed shard: the scheduler lives in a child
                # and ``s`` is a SchedulerProxy — ``s._published`` would
                # read the proxy, not the scheduler. One RPC captures
                # the whole view child-side instead.
                wire = s.fleet_view()
                if wire is None:
                    raise AttributeError(
                        "shard scheduler exposes no fleet"
                    )
                model = wire["model"]
                if isinstance(model, dict):
                    model = ModelProfile.model_validate(model)
                devices = {
                    did: (
                        DeviceProfile.model_validate(d)
                        if isinstance(d, dict)
                        else d
                    )
                    for did, d in wire["devices"].items()
                }
                return FleetReadView(
                    seq=wire["seq"],
                    model=model,
                    devices=devices,
                    published_seq=wire["published_seq"],
                )
            pub = s._published
            return FleetReadView(
                seq=s.fleet.seq,
                model=s.fleet.model,
                devices=dict(s.fleet.devices),
                published_seq=None if pub is None else pub.seq,
            )

        return self._read(_capture)

    @property
    def health(self):
        return self._read(lambda s: s.health)

    @property
    def quarantined(self):
        return self._read(lambda s: list(s.quarantined))

    @property
    def fault_hook(self):
        return self._read(lambda s: s.fault_hook)

    def __setattr__(self, name, value):
        # chaos_replay installs its injector via `scheduler.fault_hook =`;
        # forward that one write to the live scheduler as a queued
        # closure (serialized behind in-flight ticks, like the reads) —
        # everything else stays local.
        if name == "fault_hook":
            self._read(lambda s: setattr(s, "fault_hook", value))
        else:
            object.__setattr__(self, name, value)


def view_to_dict(view) -> dict:
    """A served placement as the JSON the HTTP tier returns.

    Stub scheduler factories serve plain-dict views (already JSON);
    those pass through untouched so the HTTP tier works over a
    stub-backed gateway (crash-taxonomy tests, process smokes).
    """
    if isinstance(view, dict):
        return view
    r = view.result
    return {
        "k": r.k,
        "w": r.w,
        "n": r.n,
        "y": r.y,
        "obj_value": r.obj_value,
        "certified": r.certified,
        "gap": r.gap,
        "mode": view.mode,
        "seq": view.seq,
        "fleet_seq": view.fleet_seq,
        "events_behind": view.events_behind,
        "age_s": round(view.age_s, 6),
        "twin_p95_s": view.twin_p95_s,
        "risk_selected": view.risk_selected,
    }

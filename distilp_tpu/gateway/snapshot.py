"""GatewaySnapshot: the gateway's warm state, serialized for restarts.

A drain/restore cycle must resume with warm ticks — zero cold re-solves —
so the snapshot carries, per shard: the fleet snapshot (devices + model +
event seq), the published placement, the health/breaker machine, and the
warm pool's full blob (incumbents, Lagrangian duals, IPM/PDHG root
iterates, MoE margin anchors) via ``Scheduler.dump_state`` →
``StreamingReplanner.dump_warm_state``. Arrays travel as base64 raw
bytes, so the round trip is bit-exact and a restored tick equals the
uninterrupted one.

The snapshot is plain JSON on disk (one file, atomic rename) — restore
does not need the producing process, only a gateway built with the same
solver configuration. Worker count may differ across the cycle: shards
re-route by consistent hash on restore, each carrying its warm state to
its new owner.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict, List

from pydantic import BaseModel, Field

SNAPSHOT_VERSION = 1
SNAPSHOT_FILENAME = "gateway_snapshot.json"


class ShardSnapshot(BaseModel):
    """One shard's identity + its scheduler's full warm state."""

    fleet_id: str
    model_id: str = "default"
    shard_key: str
    # How many trace events this shard has HANDLED (quarantines included)
    # — the resume cursor a trace replay skips to. ``Scheduler`` state
    # carries the fleet seq (events *applied*); a quarantined event
    # advances handled but not seq, and a resume must not replay it.
    events_handled: int = 0
    # Scheduler.dump_state() blob (JSON-able; arrays base64-encoded).
    state: dict


class GatewaySnapshot(BaseModel):
    """Every shard's warm state + the gateway shape that produced it."""

    version: int = SNAPSHOT_VERSION
    n_workers: int
    shards: List[ShardSnapshot] = Field(default_factory=list)
    # Gateway-level counters at snapshot time (informational only; a
    # restored gateway starts fresh counters — `warm_resumes` on the other
    # side is what audits the cycle).
    counters: Dict[str, int] = Field(default_factory=dict)

    def shard_for(self, fleet_id: str) -> ShardSnapshot:
        for s in self.shards:
            if s.fleet_id == fleet_id:
                return s
        raise KeyError(f"snapshot has no shard for fleet {fleet_id!r}")


def snapshot_path(directory) -> Path:
    return Path(directory) / SNAPSHOT_FILENAME


def _durable_replace(tmp: Path, path: Path) -> None:
    """``os.replace`` with the two fsyncs rename-atomicity forgets.

    ``tmp`` must already hold the complete payload. The file is fsynced
    BEFORE the rename (so the bytes are on the platter when the name
    flips) and the parent directory is fsynced after (so the rename
    itself survives a host crash — without it the directory entry can
    still point at the old inode after power loss, or at nothing).
    Directory fds are unsupported on some filesystems; that fsync is
    best-effort by design, the file fsync is not.
    """
    fd = os.open(tmp, os.O_RDWR)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    try:
        dfd = os.open(path.parent, os.O_RDONLY)
    except OSError:  # dlint: disable=DLP017 directory fds unsupported on some filesystems; the directory fsync is best-effort by contract (docstring), the file fsync above is not
        return
    try:
        os.fsync(dfd)
    except OSError:  # dlint: disable=DLP017 same best-effort contract: a filesystem that rejects directory fsync still got the file fsync + atomic rename
        pass
    finally:
        os.close(dfd)


def save_snapshot(snap: GatewaySnapshot, directory) -> Path:
    """Write the snapshot atomically (tmp + durable rename) under ``directory``.

    A crash mid-write must leave either the previous snapshot or none —
    never a torn file a restore would half-parse — and a snapshot that
    returned from here must survive a host crash (`_durable_replace`).
    """
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    path = snapshot_path(directory)
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(snap.model_dump()))
    _durable_replace(tmp, path)
    return path


def load_snapshot(directory) -> GatewaySnapshot:
    path = snapshot_path(directory)
    if not path.is_file():
        raise FileNotFoundError(f"no gateway snapshot at {path}")
    snap = GatewaySnapshot.model_validate(json.loads(path.read_text()))
    if snap.version != SNAPSHOT_VERSION:
        raise ValueError(
            f"unknown snapshot version {snap.version} "
            f"(this build reads {SNAPSHOT_VERSION})"
        )
    return snap

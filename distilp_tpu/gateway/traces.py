"""Multi-fleet JSONL traces: the gateway's replayable wire format.

The single-fleet trace (``sched.events``) is one event per line; a
gateway trace tags each line with the fleet it belongs to, and declares
each fleet before its first event:

    {"fleet": "f000", "synthetic": {"m": 3, "seed": 101}}
    {"fleet": "f000", "event": {"kind": "load", "t_comm_jitter": {...}}}
    {"fleet": "f001", "synthetic": {"m": 4, "seed": 102}}
    ...

A ``synthetic`` spec line builds the fleet deterministically from
``utils.make_synthetic_fleet`` (names prefixed with the fleet id so two
fleets never alias devices); the served model comes from the caller (the
serve CLI's ``--profile`` folder), so the trace file stays small and
model-agnostic. Event order across fleets IS the file order — a replay
that honors it is reproducible, and per-fleet order is what shard
serialization guarantees under concurrent ingest.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from ..sched.events import event_from_dict


def is_gateway_trace(path) -> bool:
    """Whether the JSONL file is fleet-tagged (vs a single-fleet trace)."""
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    return "fleet" in json.loads(line)
                except ValueError:  # dlint: disable=DLP017 format probe: a non-JSON line means "not a gateway trace", not a fault
                    return False
    return False


def make_fleet_from_spec(fleet_id: str, spec: dict):
    """Deterministic devices for a ``synthetic`` spec line."""
    from ..utils import make_synthetic_fleet

    m = int(spec.get("m", 3))
    seed = int(spec.get("seed", 0))
    pool_bytes = int(spec.get("pool_bytes", 0))
    devices = make_synthetic_fleet(m, seed=seed, pool_bytes=pool_bytes)
    for d in devices:
        d.name = f"{fleet_id}-{d.name}"
    return devices


def read_gateway_trace(path) -> Tuple[Dict[str, dict], List[Tuple[str, object]]]:
    """(fleet specs, [(fleet_id, event), ...]) in file order.

    Raises on an event line for an undeclared fleet — a trace that relies
    on registration happening elsewhere is not replayable on its own.
    """
    specs: Dict[str, dict] = {}
    items: List[Tuple[str, object]] = []
    with open(path, "r") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            data = json.loads(line)
            fleet_id = data.get("fleet")
            if not fleet_id:
                raise ValueError(
                    f"{path}:{lineno}: gateway trace line without a fleet tag"
                )
            if "synthetic" in data:
                specs[fleet_id] = dict(data["synthetic"])
            elif "event" in data:
                if fleet_id not in specs:
                    raise ValueError(
                        f"{path}:{lineno}: event for undeclared fleet "
                        f"{fleet_id!r} (no synthetic spec line before it)"
                    )
                items.append((fleet_id, event_from_dict(data["event"])))
            else:
                raise ValueError(
                    f"{path}:{lineno}: gateway trace line needs a "
                    "'synthetic' spec or an 'event'"
                )
    return specs, items


def write_gateway_trace(
    path,
    specs: Dict[str, dict],
    items: Sequence[Tuple[str, object]],
) -> None:
    """Write a gateway trace; spec lines first (stable, replay-friendly)."""
    with open(Path(path), "w") as f:
        for fleet_id, spec in specs.items():
            f.write(json.dumps({"fleet": fleet_id, "synthetic": spec}) + "\n")
        for fleet_id, ev in items:
            data = ev.model_dump(exclude_defaults=True)
            data["kind"] = ev.kind
            f.write(json.dumps({"fleet": fleet_id, "event": data}) + "\n")

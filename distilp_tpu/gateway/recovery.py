"""Crash-tolerance primitives for the process-backed worker tier.

Three small pieces the gateway's supervisor composes into the
exactly-once recovery contract:

``ShardWAL``
    A per-fleet append-only write-ahead journal. Every ACCEPTED event is
    framed into the journal *before* its RPC dispatches to the child, as
    ``(cursor, event)`` where cursor is the fleet's events-handled count
    after this event applies. Frames are 8-byte big-endian length +
    pickle — the same framing the worker RPC itself speaks — and a torn
    trailing frame (the writer died mid-append) is tolerated on read:
    a half-written record's event never reached the child either, so
    dropping it loses nothing.

``RecoveryStore``
    The on-disk layout: one directory per fleet holding ``wal.bin`` and
    ``micro_snapshot.bin``. Micro-snapshots ride the bit-exact
    ``dump_state``/``load_state`` chain (plus the shard's live counters,
    so cumulative metrics survive the child); they are written via
    :func:`~distilp_tpu.gateway.snapshot._durable_replace` (fsync before
    rename + dir fsync) and each successful snapshot truncates the WAL
    to its cursor. WAL appends only flush — the journal defends against
    CHILD death (the parent process, which holds the page cache, is
    alive to replay); the durable rename defends against HOST death.

``Supervisor``
    The respawn policy for one worker: a crash-time deque pruned to a
    sliding window. Each crash classifies to ``respawn`` (with bounded
    exponential backoff, doubling per crash in the window) until N
    crashes land inside the window — then ``quarantine``: the worker is
    taken out of the ring, its slice rebalanced away, and the fact
    surfaced in ``/signals`` for the controller to vote scale-out on.

Single-writer contract: a fleet's WAL and snapshot are only touched from
the worker thread that owns the fleet's shard (tick closures are
serialized per worker, and recovery itself runs inline on that thread),
so these classes carry no locks by design.
"""

from __future__ import annotations

import pickle
import struct
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from .snapshot import _durable_replace

WAL_FILENAME = "wal.bin"
MICRO_SNAPSHOT_FILENAME = "micro_snapshot.bin"

_LEN = struct.Struct(">Q")


def _frame(cursor: int, event: Any) -> bytes:
    payload = pickle.dumps((cursor, event), protocol=pickle.HIGHEST_PROTOCOL)
    return _LEN.pack(len(payload)) + payload


class ShardWAL:
    """Append-only ``(cursor, event)`` journal for one fleet's shard."""

    def __init__(self, path) -> None:
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = None

    # -- write side ------------------------------------------------------

    def append(self, cursor: int, event: Any) -> None:
        """Journal one accepted event BEFORE its RPC dispatches."""
        if self._fh is None or self._fh.closed:
            self._fh = open(self.path, "ab")
        self._fh.write(_frame(cursor, event))
        self._fh.flush()

    def truncate_to(self, cursor: int) -> None:
        """Drop every record with ``record.cursor <= cursor`` (snapshot
        boundary). Rewrites via durable replace so a host crash leaves
        either the old journal or the truncated one, never a torn mix."""
        keep = [(c, e) for c, e in self.records() if c > cursor]
        self.close()
        tmp = self.path.with_name(self.path.name + ".tmp")
        with open(tmp, "wb") as fh:
            for c, e in keep:
                fh.write(_frame(c, e))
            fh.flush()
        _durable_replace(tmp, self.path)

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            self._fh.close()
        self._fh = None

    # -- read side -------------------------------------------------------

    def records(self) -> List[Tuple[int, Any]]:
        """All intact records, in append order. A torn trailing frame
        (partial header or payload) ends the scan without raising: the
        half-written event never dispatched, so it is not recovery state."""
        if self._fh is not None and not self._fh.closed:
            self._fh.flush()
        if not self.path.is_file():
            return []
        out: List[Tuple[int, Any]] = []
        raw = self.path.read_bytes()
        off = 0
        while off + _LEN.size <= len(raw):
            (n,) = _LEN.unpack_from(raw, off)
            if off + _LEN.size + n > len(raw):
                break  # torn tail
            out.append(pickle.loads(raw[off + _LEN.size : off + _LEN.size + n]))
            off += _LEN.size + n
        return out

    def tail_after(self, cursor: int) -> List[Tuple[int, Any]]:
        return [(c, e) for c, e in self.records() if c > cursor]


class RecoveryStore:
    """Per-fleet WAL + micro-snapshot layout rooted at one directory."""

    def __init__(self, root) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._wals: Dict[str, ShardWAL] = {}

    def _fleet_dir(self, fleet_id: str) -> Path:
        return self.root / fleet_id.replace("/", "_")

    def wal(self, fleet_id: str) -> ShardWAL:
        if fleet_id not in self._wals:
            self._wals[fleet_id] = ShardWAL(self._fleet_dir(fleet_id) / WAL_FILENAME)
        return self._wals[fleet_id]

    def _snap_path(self, fleet_id: str) -> Path:
        return self._fleet_dir(fleet_id) / MICRO_SNAPSHOT_FILENAME

    def save_micro_snapshot(
        self,
        fleet_id: str,
        cursor: int,
        state: dict,
        counters: Optional[Dict[str, int]] = None,
    ) -> None:
        """Durably persist ``dump_state`` at ``cursor``; truncate the WAL.

        The order matters: the snapshot must be on disk (durable rename)
        BEFORE its WAL prefix disappears, so a crash between the two
        steps only leaves redundant journal records — replaying a record
        at-or-below the snapshot cursor is skipped by the cursor compare,
        never double-applied.
        """
        path = self._snap_path(fleet_id)
        path.parent.mkdir(parents=True, exist_ok=True)
        blob = pickle.dumps(
            {"cursor": cursor, "state": state, "counters": dict(counters or {})},
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_bytes(blob)
        _durable_replace(tmp, path)
        self.wal(fleet_id).truncate_to(cursor)

    def load_micro_snapshot(self, fleet_id: str) -> Optional[dict]:
        path = self._snap_path(fleet_id)
        if not path.is_file():
            return None
        return pickle.loads(path.read_bytes())

    def recovery_plan(self, fleet_id: str) -> Tuple[Optional[dict], List[Tuple[int, Any]]]:
        """(micro-snapshot or None, WAL records strictly after its cursor)."""
        snap = self.load_micro_snapshot(fleet_id)
        cursor = snap["cursor"] if snap is not None else 0
        return snap, self.wal(fleet_id).tail_after(cursor)

    def drop(self, fleet_id: str) -> None:
        """Forget a fleet's recovery state (fleet deregistered)."""
        wal = self._wals.pop(fleet_id, None)
        if wal is not None:
            wal.close()
        d = self._fleet_dir(fleet_id)
        for name in (WAL_FILENAME, MICRO_SNAPSHOT_FILENAME):
            p = d / name
            if p.is_file():
                p.unlink()

    def close(self) -> None:
        for wal in self._wals.values():
            wal.close()
        self._wals.clear()


class Supervisor:
    """Respawn policy for ONE worker: classify each crash, bound the rate.

    ``record_crash()`` returns the verdict — ``"respawn"`` while fewer
    than ``threshold`` crashes landed inside the sliding ``window_s``,
    ``"quarantine"`` at the threshold (the crash-loop breaker opening).
    ``backoff_s()`` is the sleep before the next respawn attempt:
    ``base * 2**(crashes_in_window - 1)`` capped at ``max``.
    """

    def __init__(
        self,
        threshold: int = 3,
        window_s: float = 30.0,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.threshold = max(1, int(threshold))
        self.window_s = float(window_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock
        self._crashes: Deque[float] = deque()
        self.total_crashes = 0
        self.quarantined = False

    def _prune(self, now: float) -> None:
        while self._crashes and now - self._crashes[0] > self.window_s:
            self._crashes.popleft()

    def record_crash(self) -> str:
        now = self._clock()
        self._prune(now)
        self._crashes.append(now)
        self.total_crashes += 1
        if len(self._crashes) >= self.threshold:
            self.quarantined = True
            return "quarantine"
        return "respawn"

    def backoff_s(self) -> float:
        n = max(1, len(self._crashes))
        return min(self.backoff_base_s * (2 ** (n - 1)), self.backoff_max_s)

    @property
    def crashes_in_window(self) -> int:
        self._prune(self._clock())
        return len(self._crashes)

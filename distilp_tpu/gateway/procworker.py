"""Process-backed shard workers: one subprocess per worker, thin RPC.

A ``ProcShardWorker`` IS a ``ShardWorker`` — same daemon thread, same
queue, same submit/stop/read contract — except the objects in its
``shards`` dict are ``SchedulerProxy`` instances: every scheduler method
a queued closure touches (``handle``, ``dump_state``, ``health`` …) is
forwarded over a length-prefixed RPC on a private Unix domain socket to
a child process that hosts the real ``Scheduler``. The child has its own
Python interpreter and its own XLA runtime, so N process workers solve
on N GILs and N device runtimes — the scaling the thread backend cannot
reach (measured 1.68x at 2 thread workers, negative at 4: one GIL, one
process-wide XLA runtime).

Why this shape and not multiprocessing:

- ``subprocess.Popen([sys.executable, "-m", …])`` gives the child a
  FRESH interpreter. ``fork`` after jax initializes is undefined
  behavior (XLA runtime state forks mid-flight); ``spawn`` via
  multiprocessing drags a pickled parent context we don't want. The
  child imports jax lazily, on the first shard build — same discipline
  dlint enforces on every serving-tier module (DLP013).
- The parent binds and listens BEFORE spawning, so the child's connect
  never races the listener; the socket lives in a mode-0700 tempdir, so
  the pickle channel is private to this uid (pickle over a socket is an
  RCE vector only if something else can write to it — nothing can).
- Framing is 8-byte big-endian length + pickle payload. One
  request/one reply, strictly serialized under the parent's RPC lock:
  the worker thread is the only steady-state caller, but control-plane
  probes (health under load) share the channel, and interleaved frames
  would corrupt it.

The RPC carries only plain data: events and ``dump_state`` blobs are
already picklable by the snapshot contract, and ``PlacementView``
results cross the wire as ``model_dump()`` dicts (rebuilt parent-side
via ``model_validate`` — the exact round trip ``dump_state`` already
proves bit-exact), so no jax array ever crosses a process boundary.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
from typing import Any, Callable, Dict, Optional

from ..sched.metrics import SchedulerMetrics
from ..utils.lockwatch import make_lock
from .worker import ShardWorker

_LEN = struct.Struct(">Q")

# Scheduler methods whose return value is a PlacementView (or None):
# converted to a wire dict child-side, rebuilt parent-side.
_VIEW_METHODS = frozenset({"handle", "handle_coalesced", "latest"})


# -- framing (shared by both ends) ----------------------------------------


def send_frame(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_frame(sock: socket.socket) -> Optional[Any]:
    """One framed object, or None on clean EOF at a frame boundary."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    body = _recv_exact(sock, n)
    if body is None:
        raise EOFError("peer closed mid-frame")
    return pickle.loads(body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf:
                # Partial bytes then EOF: a torn connection, never a
                # clean shutdown — must not parse as a (corrupt) frame.
                raise EOFError("peer closed mid-frame")
            return None
        buf += chunk
    return buf


def _view_to_wire(view) -> Optional[dict]:
    """PlacementView -> plain dict (no jax leaves cross the socket)."""
    if view is None:
        return None
    if not hasattr(view, "result") or not hasattr(view, "mode"):
        # Stub schedulers (tests) return plain picklable values; only a
        # real PlacementView needs the model_dump round trip.
        return view
    return {
        "__placement_view__": 1,
        "result": view.result.model_dump(),
        "seq": view.seq,
        "fleet_seq": view.fleet_seq,
        "events_behind": view.events_behind,
        "age_s": view.age_s,
        "mode": view.mode,
        "key": tuple(view.key) if view.key is not None else None,
        "twin_p95_s": view.twin_p95_s,
        "risk_selected": view.risk_selected,
    }


def _view_from_wire(wire: Optional[dict]):
    if wire is None:
        return None
    if not (isinstance(wire, dict) and wire.get("__placement_view__")):
        return wire  # stub schedulers may return plain picklable values
    from ..solver.result import HALDAResult
    from ..sched.scheduler import PlacementView

    return PlacementView(
        result=HALDAResult.model_validate(wire["result"]),
        seq=wire["seq"],
        fleet_seq=wire["fleet_seq"],
        events_behind=wire["events_behind"],
        age_s=wire["age_s"],
        mode=wire["mode"],
        key=wire["key"],
        twin_p95_s=wire["twin_p95_s"],
        risk_selected=wire["risk_selected"],
    )


def resolve_factory(spec: str) -> Callable:
    """'package.module:callable' -> the callable (shared by both ends:
    the Gateway validates it parent-side; the child imports it to build).
    """
    mod_name, sep, attr = spec.partition(":")
    if not sep or not mod_name or not attr:
        raise ValueError(
            f"scheduler factory spec must be 'module:callable', got {spec!r}"
        )
    import importlib

    fn = getattr(importlib.import_module(mod_name), attr)
    if not callable(fn):
        raise TypeError(f"factory {spec!r} resolved to non-callable {fn!r}")
    return fn


# -- parent side ----------------------------------------------------------


class _MetricsView:
    """Read-only snapshot of a child scheduler's metrics, shaped like the
    live ``SchedulerMetrics`` surface the gateway's read closures use
    (``.counters`` mapping + ``.snapshot()``)."""

    def __init__(self, counters: dict, snapshot: dict):
        self.counters = counters
        self._snapshot = snapshot

    def snapshot(self) -> dict:
        return dict(self._snapshot)


class SchedulerProxy:
    """Parent-side stand-in for one child-hosted ``Scheduler``.

    Quacks exactly like the scheduler surface the gateway's queued
    closures touch, so ``_tick_closure``/``dump_shard``/``healthz`` run
    unchanged. Methods here are called ON the worker thread (or from
    quiescent control-plane reads); the owning worker's RPC lock
    serializes the channel either way.
    """

    def __init__(self, owner: "ProcShardWorker", key: str):
        self._owner = owner
        self._key = key

    def _call(self, method: str, *args, **kwargs):
        out = self._owner.rpc(
            {
                "op": "call",
                "key": self._key,
                "method": method,
                "args": args,
                "kwargs": kwargs,
            }
        )
        if method in _VIEW_METHODS:
            return _view_from_wire(out)
        return out

    # the tick surface
    def handle(self, event, pressure: bool = False):
        if pressure:
            return self._call("handle", event, pressure=True)
        return self._call("handle", event)

    def handle_coalesced(self, events, pressure: bool = False):
        return self._call("handle_coalesced", events, pressure=pressure)

    def latest(self):
        return self._call("latest")

    # the snapshot chain (bit-exact blobs pass through untouched)
    def dump_state(self) -> dict:
        return self._call("dump_state")

    def load_state(self, state: dict) -> None:
        self._call("load_state", state)

    # the read surface
    def health_snapshot(self) -> dict:
        return self._call("health_snapshot")

    def metrics_snapshot(self) -> dict:
        return self._call("metrics_snapshot")

    @property
    def health(self) -> str:
        return self._owner.rpc(
            {"op": "getattr", "key": self._key, "name": "health"}
        )

    @property
    def metrics(self) -> _MetricsView:
        out = self._owner.rpc({"op": "metrics", "key": self._key})
        return _MetricsView(out["counters"], out["snapshot"])

    # the control surface (autoscaler spec_k actuation)
    @property
    def spec_k(self) -> int:
        return self._owner.rpc(
            {"op": "getattr", "key": self._key, "name": "spec_k"}
        )

    @spec_k.setter
    def spec_k(self, k: int) -> None:
        self._owner.rpc(
            {"op": "setattr", "key": self._key, "name": "spec_k", "value": k}
        )

    def close(self) -> None:
        """Drop + close the child-side scheduler (idempotent, best
        effort: a dead child already closed everything the hard way)."""
        try:
            self._owner.rpc({"op": "drop", "key": self._key})
        except Exception:  # dlint: disable=DLP017 best-effort teardown: a dead child already dropped everything; the worker's stop() path counts real RPC failures
            pass


class ProcShardWorker(ShardWorker):
    """A ShardWorker whose shards live in a dedicated subprocess.

    The parent keeps the thread + queue (closures, FIFO ordering, the
    submit/stop contract, coalescing — all parent-side and unchanged);
    only the scheduler calls inside those closures cross the socket.
    Unsupported with cross-shard combine, chaos ``fault_hook`` injection
    and callable scheduler factories — the Gateway gates those off for
    this backend (each needs in-process object sharing).
    """

    def __init__(
        self,
        worker_id: int,
        metrics: SchedulerMetrics,
        *,
        python: Optional[str] = None,
        spawn_timeout_s: float = 60.0,
        compile_ledger: bool = False,
    ):
        self._sock_dir = tempfile.mkdtemp(prefix=f"distilp-pw{worker_id}-")
        path = os.path.join(self._sock_dir, "rpc.sock")
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(path)
        self._listener.listen(1)
        cmd = [
            python or sys.executable,
            "-m",
            "distilp_tpu.gateway.procworker",
            "--socket",
            path,
        ]
        if compile_ledger:
            cmd.append("--compile-ledger")
        self._proc = subprocess.Popen(cmd)
        self._listener.settimeout(spawn_timeout_s)
        try:
            self._conn, _ = self._listener.accept()
        except socket.timeout:
            self._proc.kill()
            raise RuntimeError(
                f"process worker {worker_id} child did not connect within "
                f"{spawn_timeout_s}s"
            )
        self._conn.settimeout(None)
        # Serializes request/reply pairs on the one channel: the worker
        # thread is the steady-state caller but control-plane reads
        # (health probes under load, ledger snapshots) share it.
        self._rpc_lock = make_lock("procworker.rpc")
        super().__init__(worker_id, metrics)
        self.rpc({"op": "ping"})  # fail fast if the child can't serve

    # -- channel -----------------------------------------------------------

    def rpc(self, req: dict) -> Any:
        with self._rpc_lock:
            send_frame(self._conn, req)
            reply = recv_frame(self._conn)
        if reply is None:
            raise EOFError(
                f"process worker {self.worker_id} child exited "
                f"(rc={self._proc.poll()})"
            )
        if reply.get("ok"):
            return reply.get("result")
        exc = reply.get("exc")
        if isinstance(exc, BaseException):
            raise exc
        raise RuntimeError(f"process worker {self.worker_id}: {exc}")

    # -- shard lifecycle ---------------------------------------------------

    def create_shard(self, key: str, build=None, state=None, spec=None):
        """Build the shard IN the child from its picklable ``spec``; the
        parent installs a proxy. Runs as a queued closure so registration
        keeps the thread backend's FIFO placement behind queued work."""
        if spec is None:
            raise RuntimeError(
                "process workers need a picklable build spec (a callable "
                "scheduler_factory cannot cross a process boundary — pass "
                "a 'module:callable' factory string instead)"
            )

        def _do():
            self.rpc({"op": "build", "key": key, "spec": spec, "state": state})
            self.shards[key] = SchedulerProxy(self, key)

        self.call(_do)

    # -- shutdown ----------------------------------------------------------

    def stop(self, join: bool = True, timeout: float = 5.0) -> None:
        """Base stop drains the queue and closes every proxy (child-side
        drops), then the child itself is stopped and reaped. ``join`` is
        forced: the child teardown RPC must not race queued drop RPCs."""
        with self._submit_lock:
            already = self._stopped
        super().stop(join=True, timeout=timeout)
        if already:
            return
        try:
            self.rpc({"op": "stop"})
        except Exception:  # dlint: disable=DLP017 teardown race: the child may have exited on socket EOF before the stop RPC lands; proc.wait/kill below is the enforcement
            pass
        try:
            self._proc.wait(timeout=timeout)
        except Exception:  # dlint: disable=DLP017 the recovery IS the recording: a child that ignores stop gets SIGKILLed, never orphaned
            self._proc.kill()
        for s in (self._conn, self._listener):
            try:
                s.close()
            except Exception:  # dlint: disable=DLP017 socket already torn down by the dead child; nothing to account
                pass
        import shutil

        shutil.rmtree(self._sock_dir, ignore_errors=True)

    # -- child observability (bench: per-process compile accounting) ------

    def ledger_counters(self) -> Optional[dict]:
        """The CHILD's compile-ledger counters (None when not enabled):
        the bench's zero-warm-compiles gate reads these per process."""
        return self.rpc({"op": "ledger_counters"})


# -- child side -----------------------------------------------------------


def _child_build(shards: Dict[str, Any], req: dict) -> None:
    spec = req["spec"]
    if spec.get("factory"):
        factory = resolve_factory(spec["factory"])
        devices = spec["devices"]
        model = spec["model"]
        if devices and all(isinstance(d, dict) for d in devices):
            from ..common import DeviceProfile

            devices = [DeviceProfile.model_validate(d) for d in devices]
        if isinstance(model, dict):
            from ..common import ModelProfile

            model = ModelProfile.model_validate(model)
        sched = factory(devices, model)
    else:
        # jax enters the child here, on first real shard build — never at
        # module import (DLP013 discipline holds in the child too).
        from ..common import DeviceProfile, ModelProfile
        from ..sched.scheduler import Scheduler

        devices = [
            DeviceProfile.model_validate(d) for d in spec["devices"]
        ]
        model = (
            ModelProfile.model_validate(spec["model"])
            if spec.get("model") is not None
            else None
        )
        sched = Scheduler(devices, model, **dict(spec.get("kwargs") or {}))
    if req.get("state") is not None:
        sched.load_state(req["state"])
    shards[req["key"]] = sched


def _child_dispatch(shards: Dict[str, Any], req: dict) -> Any:
    op = req["op"]
    if op == "ping":
        return os.getpid()
    if op == "build":
        _child_build(shards, req)
        return None
    if op == "call":
        sched = shards[req["key"]]
        out = getattr(sched, req["method"])(
            *req.get("args", ()), **req.get("kwargs", {})
        )
        if req["method"] in _VIEW_METHODS:
            return _view_to_wire(out)
        return out
    if op == "getattr":
        return getattr(shards[req["key"]], req["name"])
    if op == "setattr":
        setattr(shards[req["key"]], req["name"], req["value"])
        return None
    if op == "metrics":
        m = shards[req["key"]].metrics
        return {"counters": dict(m.counters), "snapshot": m.snapshot()}
    if op == "drop":
        sched = shards.pop(req["key"], None)
        if sched is not None:
            sched.close()
        return None
    if op == "ledger_counters":
        from ..obs import compile_ledger as _cl

        led = _cl.current()
        return led.counters() if led is not None else None
    raise ValueError(f"unknown procworker op {op!r}")


def child_main(argv: Optional[list] = None) -> int:
    """The worker subprocess: connect, then serve one request at a time.

    Single-threaded by design — the parent's worker thread already
    serializes shard work, so a concurrent child would only add races.
    Clean EOF (parent died or closed) exits 0 after closing shards: an
    orphaned child must not outlive its gateway.
    """
    import argparse

    ap = argparse.ArgumentParser(prog="distilp-procworker")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--compile-ledger", action="store_true")
    args = ap.parse_args(argv)

    if args.compile_ledger:
        from ..obs import compile_ledger as _cl

        _cl.enable()

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(args.socket)
    shards: Dict[str, Any] = {}
    try:
        while True:
            req = recv_frame(sock)
            if req is None:
                break
            if req.get("op") == "stop":
                send_frame(sock, {"ok": True, "result": None})
                break
            try:
                result = _child_dispatch(shards, req)
                reply = {"ok": True, "result": result}
            except BaseException as e:  # dlint: disable=DLP017 not swallowed: the exception crosses the wire in the reply and re-raises parent-side, where the worker's metrics sink lives
                try:
                    pickle.dumps(e)
                    reply = {"ok": False, "exc": e}
                except Exception:  # dlint: disable=DLP017 the failure is not swallowed — it crosses the wire as a repr string and re-raises parent-side
                    reply = {"ok": False, "exc": f"{type(e).__name__}: {e}"}
            send_frame(sock, reply)
    finally:
        for sched in shards.values():
            try:
                sched.close()
            except Exception:  # dlint: disable=DLP017 child exit path: the process dies next line, there is no sink left to record into
                pass
        try:
            sock.close()
        except Exception:  # dlint: disable=DLP017 child exit path: the process is exiting, the parent's EOF read is the signal
            pass
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(child_main())

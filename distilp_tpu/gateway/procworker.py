"""Process-backed shard workers: one subprocess per worker, thin RPC.

A ``ProcShardWorker`` IS a ``ShardWorker`` — same daemon thread, same
queue, same submit/stop/read contract — except the objects in its
``shards`` dict are ``SchedulerProxy`` instances: every scheduler method
a queued closure touches (``handle``, ``dump_state``, ``health`` …) is
forwarded over a length-prefixed RPC on a private Unix domain socket to
a child process that hosts the real ``Scheduler``. The child has its own
Python interpreter and its own XLA runtime, so N process workers solve
on N GILs and N device runtimes — the scaling the thread backend cannot
reach (measured 1.68x at 2 thread workers, negative at 4: one GIL, one
process-wide XLA runtime).

Why this shape and not multiprocessing:

- ``subprocess.Popen([sys.executable, "-m", …])`` gives the child a
  FRESH interpreter. ``fork`` after jax initializes is undefined
  behavior (XLA runtime state forks mid-flight); ``spawn`` via
  multiprocessing drags a pickled parent context we don't want. The
  child imports jax lazily, on the first shard build — same discipline
  dlint enforces on every serving-tier module (DLP013).
- The parent binds and listens BEFORE spawning, so the child's connect
  never races the listener; the socket lives in a mode-0700 tempdir, so
  the pickle channel is private to this uid (pickle over a socket is an
  RCE vector only if something else can write to it — nothing can).
- Framing is 8-byte big-endian length + pickle payload. One
  request/one reply, strictly serialized under the parent's RPC lock:
  the worker thread is the only steady-state caller, but control-plane
  probes (health under load) share the channel, and interleaved frames
  would corrupt it.

The RPC carries only plain data: events and ``dump_state`` blobs are
already picklable by the snapshot contract, and ``PlacementView``
results cross the wire as ``model_dump()`` dicts (rebuilt parent-side
via ``model_validate`` — the exact round trip ``dump_state`` already
proves bit-exact), so no jax array ever crosses a process boundary.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Dict, Optional

from ..sched.metrics import SchedulerMetrics
from ..utils.lockwatch import make_lock
from .worker import ShardWorker

_LEN = struct.Struct(">Q")

# Scheduler methods whose return value is a PlacementView (or None):
# converted to a wire dict child-side, rebuilt parent-side.
_VIEW_METHODS = frozenset({"handle", "handle_coalesced", "latest"})


class WorkerCrashed(Exception):
    """The child process died under (or before) an RPC.

    Deliberately a plain ``Exception`` — NOT ``RuntimeError`` (the HTTP
    ladder maps that to 409) and NOT ``EOFError`` (that means the HTTP
    *client* hung up, a 400). ``gateway/http.py`` catches this type
    explicitly and answers 503 + Retry-After: the shard is coming back.

    Carries the pending-call inventory so the supervisor (and the error
    text a caller sees) knows exactly what was in flight: the RPC op
    that died on the wire is AMBIGUOUS (it may or may not have applied
    child-side — recovery resolves it from the WAL), while the queued
    closures behind it never dispatched and simply run post-recovery.
    """

    def __init__(
        self,
        worker_id: int,
        returncode: Optional[int],
        op: Optional[str],
        queued: int,
        detail: str = "",
    ):
        self.worker_id = worker_id
        self.returncode = returncode
        self.op = op
        self.queued = queued
        msg = (
            f"process worker {worker_id} child crashed (rc={returncode}) "
            f"during op {op!r}; {queued} queued call(s) pending"
        )
        if detail:
            msg += f" [{detail}]"
        super().__init__(msg)


# -- framing (shared by both ends) ----------------------------------------


def send_frame(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_frame(sock: socket.socket) -> Optional[Any]:
    """One framed object, or None on clean EOF at a frame boundary."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    body = _recv_exact(sock, n)
    if body is None:
        raise EOFError("peer closed mid-frame")
    return pickle.loads(body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf:
                # Partial bytes then EOF: a torn connection, never a
                # clean shutdown — must not parse as a (corrupt) frame.
                raise EOFError("peer closed mid-frame")
            return None
        buf += chunk
    return buf


def _view_to_wire(view) -> Optional[dict]:
    """PlacementView -> plain dict (no jax leaves cross the socket)."""
    if view is None:
        return None
    if not hasattr(view, "result") or not hasattr(view, "mode"):
        # Stub schedulers (tests) return plain picklable values; only a
        # real PlacementView needs the model_dump round trip.
        return view
    return {
        "__placement_view__": 1,
        "result": view.result.model_dump(),
        "seq": view.seq,
        "fleet_seq": view.fleet_seq,
        "events_behind": view.events_behind,
        "age_s": view.age_s,
        "mode": view.mode,
        "key": tuple(view.key) if view.key is not None else None,
        "twin_p95_s": view.twin_p95_s,
        "risk_selected": view.risk_selected,
    }


def _view_from_wire(wire: Optional[dict]):
    if wire is None:
        return None
    if not (isinstance(wire, dict) and wire.get("__placement_view__")):
        return wire  # stub schedulers may return plain picklable values
    from ..solver.result import HALDAResult
    from ..sched.scheduler import PlacementView

    return PlacementView(
        result=HALDAResult.model_validate(wire["result"]),
        seq=wire["seq"],
        fleet_seq=wire["fleet_seq"],
        events_behind=wire["events_behind"],
        age_s=wire["age_s"],
        mode=wire["mode"],
        key=wire["key"],
        twin_p95_s=wire["twin_p95_s"],
        risk_selected=wire["risk_selected"],
    )


def resolve_factory(spec: str) -> Callable:
    """'package.module:callable' -> the callable (shared by both ends:
    the Gateway validates it parent-side; the child imports it to build).
    """
    mod_name, sep, attr = spec.partition(":")
    if not sep or not mod_name or not attr:
        raise ValueError(
            f"scheduler factory spec must be 'module:callable', got {spec!r}"
        )
    import importlib

    fn = getattr(importlib.import_module(mod_name), attr)
    if not callable(fn):
        raise TypeError(f"factory {spec!r} resolved to non-callable {fn!r}")
    return fn


# -- parent side ----------------------------------------------------------


class _MetricsView:
    """Read-only snapshot of a child scheduler's metrics, shaped like the
    live ``SchedulerMetrics`` surface the gateway's read closures use
    (``.counters`` mapping + ``.snapshot()``)."""

    def __init__(self, counters: dict, snapshot: dict):
        self.counters = counters
        self._snapshot = snapshot

    def snapshot(self) -> dict:
        return dict(self._snapshot)


class SchedulerProxy:
    """Parent-side stand-in for one child-hosted ``Scheduler``.

    Quacks exactly like the scheduler surface the gateway's queued
    closures touch, so ``_tick_closure``/``dump_shard``/``healthz`` run
    unchanged. Methods here are called ON the worker thread (or from
    quiescent control-plane reads); the owning worker's RPC lock
    serializes the channel either way.
    """

    def __init__(self, owner: "ProcShardWorker", key: str):
        self._owner = owner
        self._key = key

    def _call(self, method: str, *args, **kwargs):
        out = self._owner.rpc(
            {
                "op": "call",
                "key": self._key,
                "method": method,
                "args": args,
                "kwargs": kwargs,
            }
        )
        if method in _VIEW_METHODS:
            return _view_from_wire(out)
        return out

    def _retry_read(self, fn):
        """Read-only RPCs retry ONCE against a respawned child; mutating
        calls never come through here — a mutation that died on the wire
        is ambiguous, and resolving it is the WAL's job, not a retry's."""
        try:
            return fn()
        except WorkerCrashed:
            if not self._owner.ensure_recovered():
                raise
            return fn()

    # the tick surface (mutating — NEVER auto-retried)
    def handle(self, event, pressure: bool = False):
        if pressure:
            return self._call("handle", event, pressure=True)
        return self._call("handle", event)

    def handle_coalesced(self, events, pressure: bool = False):
        return self._call("handle_coalesced", events, pressure=pressure)

    def latest(self):
        return self._retry_read(lambda: self._call("latest"))

    # the snapshot chain (bit-exact blobs pass through untouched)
    def dump_state(self) -> dict:
        return self._retry_read(lambda: self._call("dump_state"))

    def load_state(self, state: dict) -> None:
        self._call("load_state", state)

    # the read surface
    def health_snapshot(self) -> dict:
        return self._retry_read(lambda: self._call("health_snapshot"))

    def metrics_snapshot(self) -> dict:
        return self._retry_read(lambda: self._call("metrics_snapshot"))

    def fleet_view(self) -> Optional[dict]:
        """The child fleet's read surface as a plain dict (seq, published
        seq, model, devices) — None when the scheduler has no fleet
        (stub factories). The facade rebuilds a FleetReadView from it."""
        return self._retry_read(
            lambda: self._owner.rpc({"op": "fleet_view", "key": self._key})
        )

    @property
    def health(self) -> str:
        return self._retry_read(
            lambda: self._owner.rpc(
                {"op": "getattr", "key": self._key, "name": "health"}
            )
        )

    @property
    def metrics(self) -> _MetricsView:
        out = self._retry_read(
            lambda: self._owner.rpc({"op": "metrics", "key": self._key})
        )
        return _MetricsView(out["counters"], out["snapshot"])

    # the control surface (autoscaler spec_k actuation)
    @property
    def spec_k(self) -> int:
        return self._retry_read(
            lambda: self._owner.rpc(
                {"op": "getattr", "key": self._key, "name": "spec_k"}
            )
        )

    @spec_k.setter
    def spec_k(self, k: int) -> None:
        self._owner.rpc(
            {"op": "setattr", "key": self._key, "name": "spec_k", "value": k}
        )

    def close(self) -> None:
        """Drop + close the child-side scheduler (idempotent, best
        effort: a dead child already closed everything the hard way)."""
        try:
            self._owner.rpc({"op": "drop", "key": self._key})
        except Exception:  # dlint: disable=DLP017 best-effort teardown: a dead child already dropped everything; the worker's stop() path counts real RPC failures
            pass


class ProcShardWorker(ShardWorker):
    """A ShardWorker whose shards live in a dedicated subprocess.

    The parent keeps the thread + queue (closures, FIFO ordering, the
    submit/stop contract, coalescing — all parent-side and unchanged);
    only the scheduler calls inside those closures cross the socket.
    Unsupported with cross-shard combine, chaos ``fault_hook`` injection
    and callable scheduler factories — the Gateway gates those off for
    this backend (each needs in-process object sharing).
    """

    def __init__(
        self,
        worker_id: int,
        metrics: SchedulerMetrics,
        *,
        python: Optional[str] = None,
        spawn_timeout_s: float = 60.0,
        compile_ledger: bool = False,
    ):
        self._sock_dir = tempfile.mkdtemp(prefix=f"distilp-pw{worker_id}-")
        self._python = python
        self._spawn_timeout_s = spawn_timeout_s
        self._compile_ledger = compile_ledger
        # Bumped on every respawn; each generation gets its own socket
        # path so a straggling old child can never connect to the new
        # listener.
        self._generation = 0
        self._delay_next_rpc = 0.0
        # Installed by a supervising Gateway: called with this worker
        # when an RPC dies under a read path; returns True when the
        # worker was respawned in place (safe to retry a read), False
        # when unsupervised or quarantined.
        self.recovery_hook: Optional[Callable[["ProcShardWorker"], bool]] = None
        self._spawn(worker_id)
        # Serializes request/reply pairs on the one channel: the worker
        # thread is the steady-state caller but control-plane reads
        # (health probes under load, ledger snapshots) share it.
        self._rpc_lock = make_lock("procworker.rpc")
        super().__init__(worker_id, metrics)
        self.rpc({"op": "ping"})  # fail fast if the child can't serve

    def _spawn(self, worker_id: int) -> None:
        """Bind a fresh generation socket, spawn the child, accept."""
        name = f"rpc{self._generation}.sock" if self._generation else "rpc.sock"
        path = os.path.join(self._sock_dir, name)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(path)
        self._listener.listen(1)
        cmd = [
            self._python or sys.executable,
            "-m",
            "distilp_tpu.gateway.procworker",
            "--socket",
            path,
        ]
        if self._compile_ledger:
            cmd.append("--compile-ledger")
        self._proc = subprocess.Popen(cmd)
        self._listener.settimeout(self._spawn_timeout_s)
        try:
            self._conn, _ = self._listener.accept()
        except socket.timeout:
            self._proc.kill()
            raise RuntimeError(
                f"process worker {worker_id} child did not connect within "
                f"{self._spawn_timeout_s}s"
            )
        self._conn.settimeout(None)

    # -- channel -----------------------------------------------------------

    def rpc(self, req: dict) -> Any:
        delay = self._delay_next_rpc
        if delay:
            self._delay_next_rpc = 0.0
            time.sleep(delay)
        try:
            with self._rpc_lock:
                send_frame(self._conn, req)
                reply = recv_frame(self._conn)
        except (EOFError, OSError) as e:
            # Send hit a broken pipe, or recv saw bytes-then-EOF: the
            # child died mid-call. Typed so callers (and the HTTP
            # ladder) can tell a crashed worker from a client hangup.
            raise WorkerCrashed(
                self.worker_id,
                self._reap_returncode(),
                req.get("op"),
                self.depth(),
                detail=str(e),
            ) from e
        if reply is None:
            raise WorkerCrashed(
                self.worker_id,
                self._reap_returncode(),
                req.get("op"),
                self.depth(),
                detail="clean EOF at frame boundary",
            )
        if reply.get("ok"):
            return reply.get("result")
        exc = reply.get("exc")
        if isinstance(exc, BaseException):
            raise exc
        raise RuntimeError(f"process worker {self.worker_id}: {exc}")

    def _reap_returncode(self) -> Optional[int]:
        """The child's exit status for a WorkerCrashed. The socket EOF
        races the SIGCHLD: the parent's blocked recv often notices the
        death before the corpse is reapable, and a bare ``poll()`` would
        report ``None`` — erasing the taxonomy (SIGKILL's -9 vs a torn
        frame's deliberate nonzero exit). A dead peer implies an exit is
        imminent, so a short wait is bounded in practice."""
        rc = self._proc.poll()
        if rc is not None:
            return rc
        try:
            return self._proc.wait(timeout=2.0)
        except Exception:  # dlint: disable=DLP017 the exit status is diagnostic garnish; a child that outlives the wait is reaped by stop()/respawn and the crash itself is already being raised
            return None

    # -- supervision surface ----------------------------------------------

    def child_alive(self) -> bool:
        return self._proc.poll() is None

    @property
    def child_pid(self) -> int:
        return self._proc.pid

    @property
    def generation(self) -> int:
        return self._generation

    def ensure_recovered(self) -> bool:
        """Route a crashed read through the gateway's supervisor (if one
        is installed). True → respawned in place, retry the read."""
        hook = self.recovery_hook
        if hook is None:
            return False
        return bool(hook(self))

    def respawn_child(self) -> int:
        """Tear the dead channel down, spawn a fresh child, re-ping.

        The caller (the gateway's supervisor) owns shard state: after
        this returns the child is EMPTY — every shard must be rebuilt
        from its spec + micro-snapshot and the WAL tail replayed before
        the worker serves again. Returns the new child pid.
        """
        for s in (self._conn, self._listener):
            try:
                s.close()
            except OSError:  # dlint: disable=DLP017 closing a channel the dead child already tore down; the respawn below is the observable outcome
                pass
        try:
            self._proc.kill()
            self._proc.wait(timeout=5.0)
        except Exception:  # dlint: disable=DLP017 reaping an already-dead child can raise; the fresh spawn below is the enforcement
            pass
        self._generation += 1
        self._spawn(self.worker_id)
        self.rpc({"op": "ping"})
        return self._proc.pid

    # -- process-level chaos primitives (sched/faults.py drives these) ----

    def kill_child(self) -> Optional[int]:
        """SIGKILL the child (chaos ``child_kill``). The next RPC — or
        the one currently blocked on the socket — raises WorkerCrashed."""
        try:
            self._proc.kill()
            self._proc.wait(timeout=5.0)
        except Exception:  # dlint: disable=DLP017 chaos primitive: the child may already be dead; the WorkerCrashed on the next RPC is the observable signal
            pass
        return self._proc.poll()

    def inject_torn_frame(self) -> None:
        """Half-close the channel mid-frame (chaos ``rpc_torn``): write a
        partial length header, then shut the socket down. The child's
        ``_recv_exact`` sees bytes-then-EOF → EOFError → nonzero exit (a
        torn peer must never parse as a frame); the parent's next RPC
        raises WorkerCrashed on the closed channel."""
        with self._rpc_lock:
            try:
                self._conn.sendall(_LEN.pack(1 << 20)[: _LEN.size // 2])
                self._conn.shutdown(socket.SHUT_RDWR)
            except OSError:  # dlint: disable=DLP017 chaos primitive: channel already dead is the same observable outcome (next RPC raises WorkerCrashed)
                pass
            self._conn.close()
        try:
            self._proc.wait(timeout=5.0)
        except Exception:  # dlint: disable=DLP017 a child that survives a torn channel gets SIGKILLed; either way the next RPC raises WorkerCrashed
            self._proc.kill()

    def inject_rpc_delay(self, delay_s: float) -> None:
        """One-shot latency injection (chaos ``rpc_delay``): the next RPC
        sleeps ``delay_s`` before dispatch, stretching the tick without
        killing anything — the degraded-but-alive corner of the plan."""
        self._delay_next_rpc = float(delay_s)

    # -- shard lifecycle ---------------------------------------------------

    def create_shard(self, key: str, build=None, state=None, spec=None):
        """Build the shard IN the child from its picklable ``spec``; the
        parent installs a proxy. Runs as a queued closure so registration
        keeps the thread backend's FIFO placement behind queued work."""
        if spec is None:
            raise RuntimeError(
                "process workers need a picklable build spec (a callable "
                "scheduler_factory cannot cross a process boundary — pass "
                "a 'module:callable' factory string instead)"
            )

        def _do():
            self.rpc({"op": "build", "key": key, "spec": spec, "state": state})
            self.shards[key] = SchedulerProxy(self, key)

        self.call(_do)

    # -- shutdown ----------------------------------------------------------

    def stop(self, join: bool = True, timeout: float = 5.0) -> None:
        """Base stop drains the queue and closes every proxy (child-side
        drops), then the child itself is stopped and reaped. ``join`` is
        forced: the child teardown RPC must not race queued drop RPCs."""
        with self._submit_lock:
            already = self._stopped
        super().stop(join=True, timeout=timeout)
        if already:
            return
        try:
            self.rpc({"op": "stop"})
        except Exception:  # dlint: disable=DLP017 teardown race: the child may have exited on socket EOF before the stop RPC lands; proc.wait/kill below is the enforcement
            pass
        try:
            self._proc.wait(timeout=timeout)
        except Exception:  # dlint: disable=DLP017 the recovery IS the recording: a child that ignores stop gets SIGKILLed, never orphaned
            self._proc.kill()
        for s in (self._conn, self._listener):
            try:
                s.close()
            except Exception:  # dlint: disable=DLP017 socket already torn down by the dead child; nothing to account
                pass
        import shutil

        shutil.rmtree(self._sock_dir, ignore_errors=True)

    def retire_crashed(self) -> None:
        """Teardown FROM this worker's own thread, child already dead:
        the quarantine path runs inside one of our queued closures, so
        ``stop()``'s forced join would deadlock on ourselves. Marks the
        queue stopped (the sentinel still drains queued closures first —
        supervised ones forward themselves to the shard's new owner),
        reaps the corpse, and releases the sockets. No stop RPC: there
        is no child to answer it."""
        # Drop the dead proxies BEFORE the stop sentinel's _close_all
        # drains: each close would RPC a corpse and raise into a box
        # nobody reads. The shards were already re-homed.
        self.shards.clear()
        super().stop(join=False)
        try:
            self._proc.kill()
            self._proc.wait(timeout=5)
        except Exception:  # dlint: disable=DLP017 the child is already a corpse (or reaped); this kill is belt-and-braces against a half-dead child, not a recordable failure
            pass
        for s in (self._conn, self._listener):
            try:
                s.close()
            except Exception:  # dlint: disable=DLP017 socket already torn down by the dead child; nothing to account
                pass
        import shutil

        shutil.rmtree(self._sock_dir, ignore_errors=True)

    # -- child observability (bench: per-process compile accounting) ------

    def ledger_counters(self) -> Optional[dict]:
        """The CHILD's compile-ledger counters (None when not enabled):
        the bench's zero-warm-compiles gate reads these per process."""
        return self.rpc({"op": "ledger_counters"})


# -- child side -----------------------------------------------------------


def _child_build(shards: Dict[str, Any], req: dict) -> None:
    spec = req["spec"]
    if spec.get("factory"):
        factory = resolve_factory(spec["factory"])
        devices = spec["devices"]
        model = spec["model"]
        if devices and all(isinstance(d, dict) for d in devices):
            from ..common import DeviceProfile

            devices = [DeviceProfile.model_validate(d) for d in devices]
        if isinstance(model, dict):
            from ..common import ModelProfile

            model = ModelProfile.model_validate(model)
        sched = factory(devices, model)
    else:
        # jax enters the child here, on first real shard build — never at
        # module import (DLP013 discipline holds in the child too).
        from ..common import DeviceProfile, ModelProfile
        from ..sched.scheduler import Scheduler

        devices = [
            DeviceProfile.model_validate(d) for d in spec["devices"]
        ]
        model = (
            ModelProfile.model_validate(spec["model"])
            if spec.get("model") is not None
            else None
        )
        sched = Scheduler(devices, model, **dict(spec.get("kwargs") or {}))
    if req.get("state") is not None:
        sched.load_state(req["state"])
    shards[req["key"]] = sched


def _child_dispatch(shards: Dict[str, Any], req: dict) -> Any:
    op = req["op"]
    if op == "ping":
        return os.getpid()
    if op == "build":
        _child_build(shards, req)
        return None
    if op == "call":
        sched = shards[req["key"]]
        out = getattr(sched, req["method"])(
            *req.get("args", ()), **req.get("kwargs", {})
        )
        if req["method"] in _VIEW_METHODS:
            return _view_to_wire(out)
        return out
    if op == "getattr":
        return getattr(shards[req["key"]], req["name"])
    if op == "setattr":
        setattr(shards[req["key"]], req["name"], req["value"])
        return None
    if op == "metrics":
        m = shards[req["key"]].metrics
        return {"counters": dict(m.counters), "snapshot": m.snapshot()}
    if op == "fleet_view":
        sched = shards[req["key"]]
        fleet = getattr(sched, "fleet", None)
        if fleet is None:
            return None
        model = getattr(fleet, "model", None)
        devices = getattr(fleet, "devices", None) or {}
        # The published seq lives on the scheduler's placement record,
        # not the fleet — mirror ShardFacade's thread-path `_capture`.
        pub = getattr(sched, "_published", None)
        return {
            "seq": getattr(fleet, "seq", 0),
            "published_seq": None if pub is None else getattr(pub, "seq", None),
            "model": model.model_dump() if hasattr(model, "model_dump") else None,
            "devices": {
                did: d.model_dump() if hasattr(d, "model_dump") else d
                for did, d in dict(devices).items()
            },
        }
    if op == "drop":
        sched = shards.pop(req["key"], None)
        if sched is not None:
            sched.close()
        return None
    if op == "ledger_counters":
        from ..obs import compile_ledger as _cl

        led = _cl.current()
        return led.counters() if led is not None else None
    raise ValueError(f"unknown procworker op {op!r}")


def child_main(argv: Optional[list] = None) -> int:
    """The worker subprocess: connect, then serve one request at a time.

    Single-threaded by design — the parent's worker thread already
    serializes shard work, so a concurrent child would only add races.
    Clean EOF (parent died or closed) exits 0 after closing shards: an
    orphaned child must not outlive its gateway.
    """
    import argparse

    ap = argparse.ArgumentParser(prog="distilp-procworker")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--compile-ledger", action="store_true")
    args = ap.parse_args(argv)

    if args.compile_ledger:
        from ..obs import compile_ledger as _cl

        _cl.enable()

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(args.socket)
    shards: Dict[str, Any] = {}
    try:
        while True:
            req = recv_frame(sock)
            if req is None:
                break
            if req.get("op") == "stop":
                send_frame(sock, {"ok": True, "result": None})
                break
            try:
                result = _child_dispatch(shards, req)
                reply = {"ok": True, "result": result}
            except BaseException as e:  # dlint: disable=DLP017 not swallowed: the exception crosses the wire in the reply and re-raises parent-side, where the worker's metrics sink lives
                try:
                    pickle.dumps(e)
                    reply = {"ok": False, "exc": e}
                except Exception:  # dlint: disable=DLP017 the failure is not swallowed — it crosses the wire as a repr string and re-raises parent-side
                    reply = {"ok": False, "exc": f"{type(e).__name__}: {e}"}
            send_frame(sock, reply)
    finally:
        for sched in shards.values():
            try:
                sched.close()
            except Exception:  # dlint: disable=DLP017 child exit path: the process dies next line, there is no sink left to record into
                pass
        try:
            sock.close()
        except Exception:  # dlint: disable=DLP017 child exit path: the process is exiting, the parent's EOF read is the signal
            pass
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(child_main())

"""Solver CLI (reference /root/reference/src/cli/solver.py).

Reads a profile folder (``model_profile.json`` + one JSON per device; the
head device is whichever sorts first, reference cli/solver.py:49-51), runs
the HALDA sweep, prints the placement, optionally writes a solution JSON.

Differences from the reference, all deliberate:
- ``--backend {cpu,jax}`` selects the engine (jax = batched IPM + B&B on the
  accelerator); the reference has only scipy/HiGHS.
- ``--time-limit``, ``--k-candidates``, ``--kv-bits`` and ``--mip-gap`` are
  actually forwarded (the reference parses several of these and drops them,
  cli/solver.py:211).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver",
        description="HALDA placement solver over a folder of device/model profiles",
    )
    p.add_argument(
        "--profile",
        "-p",
        required=True,
        help="folder containing model_profile.json and per-device JSONs",
    )
    p.add_argument("--backend", choices=["cpu", "jax"], default="cpu")
    p.add_argument("--mip-gap", type=float, default=1e-4)
    p.add_argument("--kv-bits", default="4bit", help="4bit | 8bit | fp16 | bf16")
    p.add_argument("--time-limit", type=float, default=3600.0, help="per-k seconds (cpu backend)")
    p.add_argument(
        "--k-candidates",
        default=None,
        help="comma-separated k values (default: all proper factors of L)",
    )
    p.add_argument("--plot", action="store_true", help="plot the k-objective curve")
    p.add_argument("--debug", action="store_true")
    p.add_argument("--save-solution", default=None, help="write the solution JSON here")
    p.add_argument(
        "--moe",
        choices=["auto", "on", "off"],
        default="auto",
        help="expert+layer co-assignment: auto (when the profile has MoE "
        "component metrics), on (require them), off (dense formulation)",
    )
    p.add_argument(
        "--warm-from",
        default=None,
        help="warm-start from a solution JSON previously written by "
        "--save-solution (jax backend): the stored assignment is re-priced "
        "exactly under the current profiles and seeds the search; stored "
        "Lagrangian duals make a MoE re-solve re-certify without the full "
        "root ascent",
    )
    p.add_argument(
        "--expert-loads",
        default=None,
        help="load-weighted expert routing: a JSON file with one relative "
        "load per routed expert (or inline comma-separated values). Runs "
        "the solve->map->re-price loop and prints the expert->device "
        "mapping (MoE profiles only; see solver/routing.py)",
    )
    # JAX-backend search knobs (None = problem-class defaults, see
    # backend_jax.default_search_params). The certificate warning names
    # these; they must be reachable from the shell, not only the API.
    p.add_argument(
        "--max-rounds", type=int, default=None,
        help="branch-and-bound round budget (jax backend)",
    )
    p.add_argument(
        "--beam", type=int, default=None,
        help="frontier rows given an IPM solve per round (jax backend)",
    )
    p.add_argument(
        "--ipm-iters", type=int, default=None,
        help="interior-point iterations per LP relaxation (jax backend)",
    )
    p.add_argument(
        "--ipm-warm-iters", type=int, default=None,
        help="IPM budget of rounds after the root (warm-started nodes; "
        "default about half of --ipm-iters; set equal to --ipm-iters to "
        "disable the warm truncation — jax backend)",
    )
    p.add_argument(
        "--node-cap", type=int, default=None,
        help="frontier capacity; overflow floors the certificate (jax backend)",
    )
    p.add_argument(
        "--lp-backend", choices=["ipm", "pdhg", "auto"], default="auto",
        help="LP relaxation engine (jax backend): ipm = batched "
        "interior-point (dense Cholesky per node — fastest on small "
        "fleets), pdhg = matrix-free restarted Halpern PDHG (no "
        "factorizations — the only engine that fits M=512-4096 fleets), "
        "auto = pdhg at fleet scale, ipm below (default). The chosen "
        "engine lands in timings/metrics",
    )
    p.add_argument(
        "--pdhg-iters", type=int, default=None,
        help="first-order iterations per LP relaxation (pdhg engine; "
        "default 2000 scaled up with fleet size, a quarter of it for warm "
        "rounds — truncation only loosens bounds, never the certificate's "
        "validity)",
    )
    p.add_argument(
        "--pdhg-restart-tol", type=float, default=None,
        help="Halpern restart sufficient-decay factor in (0, 1) (pdhg "
        "engine; default 0.2 — smaller restarts less often)",
    )
    p.add_argument(
        "--mesh-shards", type=int, default=None,
        help="row-partition every PDHG LP relaxation across this many "
        "devices (pdhg engine; default 1 = no mesh). On a CPU host the "
        "CLI forces that many virtual host devices before the backend "
        "initializes (utils.shardcompat)",
    )
    p.add_argument(
        "--pdhg-dtype", choices=["f32", "f64"], default=None,
        help="first-order iterate precision (pdhg engine; default: the "
        "solver's search dtype). The mip-gap certificate is evaluated in "
        "f64 regardless, and an uncertified f32 solve escalates to f64",
    )
    p.add_argument(
        "--batch-size", type=int, default=1,
        help="price dense compute at the profiles' b_N throughput column "
        "(default 1 = reference parity; the model profile must carry the "
        "column: profile with batch_sizes=[N, ...])",
    )
    p.add_argument(
        "--per-k", action="store_true",
        help="solve EVERY feasible segment count to its own certificate "
        "and print the full k-curve with assignments (jax backend: one "
        "batched dispatch; cpu backend: one HiGHS solve per k; default: "
        "report only the winner, losing k's as objectives)",
    )
    return p


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver serve",
        description="run the fleet scheduler service over a churn trace "
        "(see distilp_tpu.sched): events in, certified placements out, "
        "warm solver state kept across ticks",
    )
    p.add_argument(
        "--trace",
        required=True,
        help="JSONL churn trace (one event per line; see sched.events "
        "for the schema, sched.sim / `generate_trace` to make one)",
    )
    p.add_argument(
        "--profile",
        "-p",
        required=True,
        help="profile folder; model_profile.json is the served model, the "
        "device JSONs are the starting fleet unless --synthetic-fleet",
    )
    p.add_argument(
        "--synthetic-fleet",
        type=int,
        default=0,
        metavar="M",
        help="start from M synthetic devices instead of the folder's "
        "device JSONs (deterministic; see utils.make_synthetic_fleet)",
    )
    p.add_argument("--fleet-seed", type=int, default=0)
    p.add_argument("--backend", choices=["cpu", "jax"], default="jax")
    p.add_argument("--mip-gap", type=float, default=1e-3)
    p.add_argument("--kv-bits", default="4bit")
    p.add_argument(
        "--k-candidates",
        default=None,
        help="comma-separated k values (default: all proper factors of L)",
    )
    p.add_argument(
        "--warm-pool",
        type=int,
        default=4,
        help="max warm replanners kept (LRU over (fleet, model) identities)",
    )
    p.add_argument(
        "--cold-start",
        action="store_true",
        help="A/B debugging: disable every cross-tick warm path (incumbent "
        "seed, Lagrangian duals, root IPM iterates, margin chain) so each "
        "tick solves from scratch; compare against a warm run to measure "
        "the reuse win",
    )
    p.add_argument(
        "--lp-backend", choices=["ipm", "pdhg", "auto"], default="auto",
        help="LP relaxation engine per tick (jax backend): ipm | pdhg | "
        "auto (default: pdhg at fleet scale, ipm below); the engine each "
        "tick ran is counted in the metrics snapshot "
        "(lp_backend_ipm/lp_backend_pdhg)",
    )
    p.add_argument(
        "--pdhg-iters", type=int, default=None,
        help="first-order iterations per LP relaxation (pdhg engine)",
    )
    p.add_argument(
        "--pdhg-restart-tol", type=float, default=None,
        help="Halpern restart sufficient-decay factor (pdhg engine)",
    )
    p.add_argument(
        "--mesh-shards", type=int, default=None,
        help="row-partition every tick's PDHG LP relaxations across this "
        "many devices (pdhg engine; CPU hosts get forced virtual devices "
        "before backend init)",
    )
    p.add_argument(
        "--pdhg-dtype", choices=["f32", "f64"], default=None,
        help="first-order iterate precision per tick (pdhg engine; f64 "
        "certificate unconditional)",
    )
    p.add_argument(
        "--risk-aware",
        action="store_true",
        help="risk-aware serving: every tick scores the fresh solve, the "
        "warm pool's cached incumbents and the solver-enumerated per-k "
        "optima on the digital twin (seeded Monte-Carlo p95 + feasibility-"
        "violation penalty; see distilp_tpu.twin) and serves the lowest-"
        "risk candidate instead of the freshest placement",
    )
    p.add_argument(
        "--risk-samples",
        type=int,
        default=256,
        help="Monte-Carlo samples per risk-aware candidate score",
    )
    p.add_argument(
        "--risk-seed", type=int, default=0,
        help="PRNG seed of the risk-aware perturbation draws",
    )
    # Speculative replanning (sched.forecast + sched.speculate; README
    # "Speculative replanning"). Default OFF = byte-identical serving.
    p.add_argument(
        "--speculate",
        action="store_true",
        help="speculative replanning: forecast drift from the applied "
        "event stream (per-channel EWMA + trend), pre-solve the K most "
        "likely near-future instances as ONE vmapped scenario batch "
        "after each tick (warm-seeded from the incumbent, off the "
        "serving path), and serve a matching event from the pre-solved "
        "bank at cache-hit latency (published mode='spec'; honest "
        "misses fall through to the normal tick path)",
    )
    p.add_argument(
        "--spec-k",
        type=int,
        default=3,
        help="forecast candidates pre-solved per speculation batch",
    )
    p.add_argument(
        "--spec-tolerance",
        type=float,
        default=0.05,
        help="relative tolerance of the speculation bank's instance "
        "digest: a banked placement serves an event whose fleet is "
        "within one tolerance bucket per drift channel of the instance "
        "it was certified on",
    )
    p.add_argument(
        "--fail-uncertified",
        action="store_true",
        help="exit 1 if any structural event's placement misses its "
        "optimality certificate",
    )
    # Fault-hardened serving (see README "Degraded-mode semantics"). All
    # default OFF so a plain `serve` replay is byte-identical to the
    # pre-chaos service.
    p.add_argument(
        "--deadline-ms",
        type=float,
        default=None,
        help="per-tick wall-clock solve deadline: an overrunning solve is "
        "abandoned and the last-known-good placement is served with "
        "mode='stale' (the first-ever solve is exempt — there is nothing "
        "to serve instead)",
    )
    p.add_argument(
        "--max-retries",
        type=int,
        default=0,
        help="solve retries per tick with bounded exponential backoff "
        "before the tick counts as failed",
    )
    p.add_argument(
        "--breaker-threshold",
        type=int,
        default=None,
        help="consecutive solve failures that open the circuit breaker "
        "(serve degraded, then half-open-probe back; default 5; 0 "
        "disables)",
    )
    p.add_argument(
        "--fault-plan",
        default=None,
        help="chaos mode: a FaultPlan JSON (see sched.faults) injected "
        "over the replay — solver exceptions, latency spikes, NaN "
        "poisoning, malformed events, dropout bursts",
    )
    p.add_argument(
        "--fault-seed",
        type=int,
        default=None,
        help="override the fault plan's seed (same seed = same injected "
        "schedule and same served placements)",
    )
    p.add_argument(
        "--chaos-check",
        action="store_true",
        help="exit 1 unless the chaos soak contract holds: a structurally "
        "valid placement served on every tick, every poisoned/malformed "
        "event quarantined and accounted, and health back to 'healthy' "
        "within the recovery budget",
    )
    p.add_argument(
        "--metrics-out",
        default=None,
        help="write the final metrics snapshot + replay summary JSON here",
    )
    p.add_argument("--quiet", action="store_true", help="summary line only")
    # Gateway tier (distilp_tpu.gateway). With --workers 1 and none of the
    # flags below, serve is byte-identical to the single-scheduler daemon:
    # no gateway object, no listener, no extra threads.
    p.add_argument(
        "--workers",
        type=int,
        default=1,
        help="solve workers behind consistent-hash shard ownership "
        "(> 1 routes the replay through the gateway tier; each "
        "(fleet, model) shard is owned by exactly one worker and keeps "
        "its own HealthState)",
    )
    p.add_argument(
        "--worker-backend", choices=("thread", "process"), default="thread",
        help="host shard workers in threads (default) or dedicated "
        "subprocesses (own GIL, own XLA runtime; README 'Closed-loop "
        "autoscaling & process workers')",
    )
    p.add_argument(
        "--scheduler-factory", default=None, metavar="MOD:FN",
        help="'module:callable' scheduler factory resolved in whichever "
        "process hosts the shard — the only factory form that crosses a "
        "process boundary (tests.procstub:make_scheduler is the no-jax "
        "stub the smokes use)",
    )
    # Crash tolerance (README "Crash recovery & supervision"). Default
    # off — unsupervised process serving is byte-identical to the
    # pre-supervision tier (no WAL, no snapshots, no supervisor state).
    p.add_argument(
        "--supervise", action="store_true",
        help="supervise process workers: a crashed child is respawned "
        "with bounded backoff and its shards restored WARM from "
        "per-shard micro-snapshots + WAL-tail replay (exactly-once; "
        "crash-looping workers are quarantined and the ring rebalanced); "
        "needs --worker-backend process",
    )
    p.add_argument(
        "--recovery-dir", default=None, metavar="DIR",
        help="with --supervise: root directory for the per-fleet WALs "
        "and micro-snapshots (default: a private tempdir removed at "
        "close)",
    )
    p.add_argument(
        "--snapshot-every", type=int, default=8, metavar="N",
        help="with --supervise: micro-snapshot each shard every N "
        "handled events (the WAL truncates at each boundary, bounding "
        "replay length)",
    )
    p.add_argument(
        "--listen",
        default=None,
        metavar="HOST:PORT",
        help="after the trace replay, keep serving the gateway's HTTP/1.1 "
        "JSON API (POST /events, GET /placement/<fleet>, /healthz, "
        "/metrics) until interrupted",
    )
    # Admission control (README "Overload & admission control"). Gateway
    # tier only; all default off — a sequential replay can never shed or
    # coalesce (depth is 0 at every ingest), so these matter for --listen
    # serving and the open-loop harness (`solver overload`).
    p.add_argument(
        "--max-queue-depth",
        type=int,
        default=None,
        metavar="N",
        help="bound each solve worker's queue at N commands; an event "
        "arriving at a full queue is shed — counted (events_shed), "
        "flight-recorded, and answered 429 + Retry-After over HTTP",
    )
    p.add_argument(
        "--coalesce",
        action="store_true",
        help="fold drift events queued for the same shard into ONE solve "
        "at the newest state (structural events are barriers; folded "
        "events counted events_coalesced, fleet seq still advances per "
        "event)",
    )
    p.add_argument(
        "--degrade-depth",
        type=int,
        default=None,
        metavar="N",
        help="queue depth at which a speculative shard may serve a banked "
        "NEAR-match (mode='spec_near', spec_near_hit counter) instead of "
        "queueing the solve past its deadline; needs --speculate to have "
        "anything banked",
    )
    p.add_argument(
        "--snapshot-dir",
        default=None,
        help="directory for the gateway warm-state snapshot "
        "(GatewaySnapshot JSON: per-shard fleet, incumbents, duals, "
        "IPM/PDHG iterates, margin anchors, health)",
    )
    p.add_argument(
        "--snapshot-at",
        type=int,
        default=None,
        metavar="N",
        help="take the snapshot after N handled events of this run "
        "(requires --snapshot-dir)",
    )
    p.add_argument(
        "--halt-after-snapshot",
        action="store_true",
        help="exit right after --snapshot-at's snapshot lands (the 'kill' "
        "half of a drain/restore cycle; pair with --resume)",
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="restore every shard's warm state from --snapshot-dir before "
        "replaying, skipping the events the snapshot already covers — "
        "the restored run's first tick per shard must ride warm "
        "(warm_resumes counter; zero cold re-solves)",
    )
    # Observability (distilp_tpu.obs; README "Observability"). All three
    # default off — serving without them is byte-identical to the
    # uninstrumented daemon.
    p.add_argument(
        "--trace-spans-dir",
        default=None,
        metavar="DIR",
        help="span tracing: record every event's span tree (HTTP ingest -> "
        "route -> worker queue wait -> tick -> solve -> publish) to "
        "DIR/spans.jsonl; convert with `solver spans` into Chrome "
        "trace-event JSON (Perfetto / chrome://tracing)",
    )
    p.add_argument(
        "--flight-dir",
        default=None,
        metavar="DIR",
        help="flight recorder: keep a bounded ring of the last N tick "
        "records per shard (mode, health, counter deltas, span ids, LP "
        "engine), auto-dumped to a post-mortem JSONL in DIR on "
        "breaker-open or a chaos-contract violation, and readable live "
        "via GET /debug/flight/<fleet> when --listen is up",
    )
    p.add_argument(
        "--flight-capacity",
        type=int,
        default=128,
        help="tick records kept per shard in the flight recorder's ring",
    )
    p.add_argument(
        "--jax-profile-dir",
        default=None,
        metavar="DIR",
        help="wrap the FIRST cold solve tick in jax.profiler.trace(DIR) "
        "(XLA profile for the TPU path; single-scheduler serving only — "
        "concurrent gateway workers would race the process-global "
        "profiler)",
    )
    p.add_argument(
        "--solver-diagnostics",
        action="store_true",
        help="solver-interior telemetry per tick (jax backend): every "
        "solve records its branch-and-bound round log + root LP "
        "convergence trace in-jit, and the conv_* digest (rounds, LP "
        "iterations, restarts, final gap/residuals) rides the sched.solve "
        "span and the flight recorder's tick records; see `solver "
        "diagnose` for the one-shot report",
    )
    # SLO engine (obs.timeline + obs.slo; README "SLOs & alerting"). All
    # default off — serving without them is byte-identical to the
    # pre-SLO daemon (no sampler thread, no new counters).
    p.add_argument(
        "--slo",
        default=None,
        metavar="SPEC.json",
        help="attach the SLO engine: a fixed-cadence sampler snapshots "
        "the live metrics into an in-process timeline and evaluates the "
        "spec's multi-window burn-rate alert rules on every tick (alert "
        "open/close -> counters + flight records + sched.alert spans; "
        "GET /slo and GET /signals serve live status under --listen)",
    )
    p.add_argument(
        "--timeline-dir",
        default=None,
        metavar="DIR",
        help="dump the sampled metrics timeline to DIR/timeline.jsonl at "
        "exit (replay it offline with `solver slo --timeline`); implies "
        "sampling even without --slo alert rules",
    )
    p.add_argument(
        "--timeline-period-ms",
        type=float,
        default=100.0,
        help="timeline sampler cadence (ms); each tick costs one metrics "
        "snapshot round trip per worker (bench-gated <= 5%% overhead)",
    )
    p.add_argument(
        "--capacity-eps",
        type=float,
        default=None,
        help="max-sustainable events/sec from a capacity probe (`solver "
        "overload` / bench overload section): the /signals payload "
        "reports autoscaling headroom against it",
    )
    # Compile ledger (obs.compile_ledger; README "Compilation
    # observability"). Default off — serving without it is byte-identical
    # to the unledgered daemon (the instrumented entry points are
    # passthroughs while no ledger is enabled).
    p.add_argument(
        "--compile-ledger",
        action="store_true",
        help="enable the process-wide XLA compile ledger: every compile "
        "event is attributed to its registered jit entry point and "
        "classified (cold / cache-hit / static-arg-flip / "
        "shape-bucket-change / recompile), ticks that paid a compile say "
        "so on their span + flight record, and the summary grows a "
        "'compile' block (render it with `solver compiles`)",
    )
    p.add_argument(
        "--compile-ledger-out",
        default=None,
        metavar="FILE",
        help="dump the compile ledger as JSONL at exit (implies "
        "--compile-ledger); reload with `solver compiles --load`",
    )
    p.add_argument(
        "--compile-warm-events",
        type=int,
        default=2,
        metavar="N",
        help="handled events per fleet after which the ledger's "
        "WARM-phase boundary is marked: the summary's "
        "compile.warm_phase_compiles counts compile events past it — the "
        "zero-recompile warm-serving invariant `make smoke-compile` "
        "gates on (default 2: the cold solve and the first warm tick "
        "each compile their own layout); with --memory-ledger the same "
        "boundary pins the leak-gate baseline (warm serving must stay "
        "FLAT in live-array bytes from there on)",
    )
    # Memory ledger (obs.memory; README "Memory observability"). Default
    # off — serving without it is byte-identical to the unledgered
    # daemon (the entry-point dispatch hook is one dormant module-global
    # read).
    p.add_argument(
        "--memory-ledger",
        action="store_true",
        help="enable the process-wide memory ledger: every registered "
        "jit entry point gets a static memory model on first dispatch "
        "(AOT XLA memory_analysis: temp/argument/output bytes + FLOPs), "
        "ticks carry live-array/RSS watermark attrs on their spans and "
        "flight records, mem.* series ride the metrics timeline, "
        "GET /signals grows mem_headroom_bytes, and the summary grows a "
        "'memory' block (render it with `solver memory`)",
    )
    p.add_argument(
        "--memory-out",
        default=None,
        metavar="FILE",
        help="dump the memory ledger as JSONL at exit (implies "
        "--memory-ledger); reload with `solver memory --load`",
    )
    p.add_argument(
        "--memory-budget-mb",
        type=float,
        default=None,
        help="memory-headroom budget in MB (default: /proc/meminfo "
        "MemTotal): mem_headroom_bytes in GET /signals = budget - RSS, "
        "and --mem-degrade-headroom-mb degrades against it",
    )
    p.add_argument(
        "--mem-degrade-headroom-mb",
        type=float,
        default=None,
        metavar="MB",
        help="gateway admission: when memory headroom (budget - RSS) "
        "drops below this many MB, ingest marks ticks under PRESSURE — "
        "composing with --degrade-depth, so a memory-squeezed gateway "
        "serves certified near-matches (mode='spec_near') instead of "
        "queueing fresh allocations; needs --memory-ledger",
    )
    return p


def build_evaluate_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver evaluate",
        description="digital-twin evaluation of a placement: deterministic "
        "simulated execution + seeded Monte-Carlo robustness report "
        "(latency quantiles under device drift, feasibility-violation "
        "probability, worst-device sensitivity ranking; see "
        "distilp_tpu.twin)",
    )
    p.add_argument(
        "--profile",
        "-p",
        required=True,
        help="folder containing model_profile.json and per-device JSONs",
    )
    p.add_argument(
        "--solution",
        default=None,
        help="placement JSON previously written by --save-solution; "
        "default: solve first (same backend/knob semantics as the solver)",
    )
    p.add_argument("--backend", choices=["cpu", "jax"], default="jax")
    p.add_argument("--mip-gap", type=float, default=1e-3)
    p.add_argument("--kv-bits", default="4bit")
    p.add_argument(
        "--k-candidates",
        default=None,
        help="comma-separated k values (used when solving; default: all "
        "proper factors of L)",
    )
    p.add_argument(
        "--moe",
        choices=["auto", "on", "off"],
        default="auto",
        help="expert+layer co-assignment mode the placement was solved with",
    )
    p.add_argument("--samples", type=int, default=1024, help="Monte-Carlo draws")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--sigma-compute", type=float, default=0.08)
    p.add_argument("--sigma-comm", type=float, default=0.15)
    p.add_argument("--sigma-disk", type=float, default=0.10)
    p.add_argument(
        "--sigma-mem", type=float, default=0.0,
        help="memory-headroom jitter; >0 makes the feasibility-violation "
        "probability a real tail statistic instead of a 0/1 flag",
    )
    p.add_argument(
        "--dropout-p", type=float, default=0.0,
        help="per-device straggler probability per sample (device runs "
        "--dropout-slowdown x slower)",
    )
    p.add_argument("--dropout-slowdown", type=float, default=8.0)
    p.add_argument(
        "--json", action="store_true",
        help="print the two reports as one JSON object instead of text",
    )
    p.add_argument(
        "--check-determinism",
        action="store_true",
        help="run the Monte-Carlo report twice with the same seed and fail "
        "unless the reports are identical (the smoke gate's assertion)",
    )
    return p


def evaluate_main(argv=None) -> int:
    """``solver evaluate``: render the digital-twin report for a placement."""
    args = build_evaluate_parser().parse_args(argv)

    from ..axon_guard import force_cpu_if_env_requested

    force_cpu_if_env_requested()

    from ..common import load_from_profile_folder

    folder = Path(args.profile)
    if not folder.is_dir():
        print(f"error: {folder} is not a directory", file=sys.stderr)
        return 2
    if args.samples < 1:
        print(
            f"error: --samples must be >= 1 (got {args.samples})",
            file=sys.stderr,
        )
        return 2
    devices, model = load_from_profile_folder(folder)

    k_candidates = None
    if args.k_candidates:
        k_candidates = [int(x) for x in args.k_candidates.split(",") if x.strip()]
    moe = {"auto": None, "on": True, "off": False}[args.moe]

    from ..solver import HALDAResult, halda_solve

    if args.solution:
        try:
            result = HALDAResult.model_validate(
                json.loads(Path(args.solution).read_text())
            )
        except (OSError, TypeError, ValueError) as e:
            print(f"error: cannot load --solution: {e}", file=sys.stderr)
            return 2
        # Full structural validation against THIS fleet+model — the same
        # gate the risk-aware scheduler runs on cached candidates. Without
        # it a solution saved against a different model/fleet would either
        # crash mid-report or be confidently mispriced.
        from ..twin import build_twin_arrays, placement_applicable

        try:
            arrays = build_twin_arrays(
                devices, model, kv_bits=args.kv_bits, moe=moe
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if not placement_applicable(
            arrays, result.w, result.n, y=result.y, k=result.k
        ):
            print(
                "error: the saved solution cannot execute on this profile "
                f"folder (devices={len(devices)}, L={model.L}, "
                f"moe={'on' if arrays.moe else 'off'}): check device "
                "count, window sums, offload counts and expert cover — "
                "was it solved for a different fleet/model, or with a "
                "different --moe mode?",
                file=sys.stderr,
            )
            return 2
    else:
        try:
            result = halda_solve(
                devices,
                model,
                k_candidates=k_candidates,
                mip_gap=args.mip_gap,
                kv_bits=args.kv_bits,
                backend=args.backend,
                moe=moe,
            )
        except (ValueError, RuntimeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    from ..twin import evaluate_placement, robustness_report

    evaluation = evaluate_placement(
        devices, model, result, kv_bits=args.kv_bits, moe=moe
    )
    mc_kwargs = dict(
        samples=args.samples,
        seed=args.seed,
        kv_bits=args.kv_bits,
        moe=moe,
        sigma_compute=args.sigma_compute,
        sigma_comm=args.sigma_comm,
        sigma_disk=args.sigma_disk,
        sigma_mem=args.sigma_mem,
        dropout_p=args.dropout_p,
        dropout_slowdown=args.dropout_slowdown,
    )
    report = robustness_report(devices, model, result, **mc_kwargs)
    if args.check_determinism:
        report2 = robustness_report(devices, model, result, **mc_kwargs)
        if report.model_dump() != report2.model_dump():
            print(
                "error: Monte-Carlo report is not deterministic for a "
                "fixed seed",
                file=sys.stderr,
            )
            return 1

    if args.json:
        print(
            json.dumps(
                {
                    "evaluation": evaluation.model_dump(),
                    "robustness": report.model_dump(),
                }
            )
        )
    else:
        print(evaluation.render_text())
        print()
        print(report.render_text())

    # The conformance contract: the twin's unperturbed execution must agree
    # with the objective the placement was priced at. A reloaded solution
    # evaluated under drifted profiles will legitimately disagree — the
    # exit code only gates when we solved in-process above.
    if args.solution is None and evaluation.rel_err is not None:
        if evaluation.rel_err > 1e-6:
            print(
                f"error: twin latency {evaluation.latency_s:.9f} disagrees "
                f"with the solver objective {evaluation.objective_s:.9f} "
                f"(rel err {evaluation.rel_err:.3e})",
                file=sys.stderr,
            )
            return 1
    return 0


def _build_obs(args):
    """(tracer, writer, flight) from the serve observability flags.

    One tracer + one flight recorder per process, shared across shards;
    None everywhere when the flags are off, so the scheduler/gateway run
    their uninstrumented default paths.
    """
    tracer = writer = flight = None
    if args.trace_spans_dir:
        from ..obs import JsonlSpanWriter, Tracer

        writer = JsonlSpanWriter(Path(args.trace_spans_dir) / "spans.jsonl")
        tracer = Tracer(capacity=65536, writer=writer)
    if args.flight_dir:
        from ..obs import FlightRecorder

        flight = FlightRecorder(
            capacity=max(1, args.flight_capacity), dump_dir=args.flight_dir
        )
    return tracer, writer, flight


def _obs_summary(writer, flight) -> dict:
    out = {}
    if writer is not None:
        out["spans_written"] = writer.written
        out["spans_path"] = str(writer.path)
    if flight is not None:
        out["flight_dumps"] = [str(p) for p in flight.dumps]
    return out


def _build_compile_ledger(args):
    """(ledger, owned) from the serve flags; (None, False) on the
    byte-identical default path (instrumented entry points stay
    passthroughs). ``owned`` means THIS run enabled the process ledger
    and must disable it on exit — a leaked global ledger would mint
    compile counters into every scheduler an in-process caller builds
    afterwards (the exact leak the test suite's byte-identical pins
    would trip over)."""
    if not (args.compile_ledger or args.compile_ledger_out):
        return None, False
    from ..obs import compile_ledger

    existing = compile_ledger.current()
    if existing is not None:
        return existing, False
    return compile_ledger.enable(), True


def _release_compile_ledger(owned: bool) -> None:
    if owned:
        from ..obs import compile_ledger

        compile_ledger.disable()


def _compile_summary(args, led, warm_token) -> dict:
    """The serve summary's "compile" block (+ the JSONL dump side effect).

    ``warm_token`` is the ledger seq at the warm-phase boundary (every
    fleet past ``--compile-warm-events`` handled events) — compile events
    after it are warm-phase compiles, the count the zero-recompile gate
    reads; None when the replay ended before the boundary was reached.
    """
    from ..obs import compile_ledger

    warm = (
        len(led.events_since(warm_token)) if warm_token is not None else None
    )
    summary = led.summary()
    out = {
        "counters": summary["counters"],
        "cache_hit_rate": summary["cache_hit_rate"],
        "causes": summary["causes"],
        "entries": summary["entries"],
        "registered": compile_ledger.registered_entry_points(),
        "unregistered_compiles": summary["counters"][
            "unattributed_compiles"
        ],
        "warm_boundary_marked": warm_token is not None,
        "warm_phase_compiles": warm,
        "fallback": summary["fallback"],
    }
    if args.compile_ledger_out:
        led.dump_jsonl(args.compile_ledger_out)
        out["ledger_path"] = str(args.compile_ledger_out)
    return out


def _build_memory_ledger(args):
    """(ledger, owned) from the serve memory flags; (None, False) on the
    byte-identical default path. Same ownership contract as the compile
    ledger: a serve-OWNED ledger is disable()d in the finally so
    in-process callers never inherit the process-global hook."""
    if not (args.memory_ledger or args.memory_out):
        return None, False
    from ..obs import memory

    existing = memory.current()
    if existing is not None:
        if args.memory_budget_mb is not None:
            # The explicit flag wins over whatever budget the reused
            # process ledger resolved: silently ignoring it would leave
            # --mem-degrade-headroom-mb degrading against MemTotal and
            # never firing, with nothing saying why.
            existing.budget_bytes = int(args.memory_budget_mb * 1e6)
        return existing, False
    kwargs = {}
    if args.memory_budget_mb is not None:
        kwargs["budget_bytes"] = int(args.memory_budget_mb * 1e6)
    return memory.enable(**kwargs), True


def _release_memory_ledger(owned: bool) -> None:
    if owned:
        from ..obs import memory

        memory.disable()


def _memory_summary(args, mled) -> dict:
    """The serve summary's "memory" block (+ the JSONL dump side effect):
    per-entry static models, watermarks, and the leak-gate verdict
    (marked at the --compile-warm-events boundary)."""
    # One forced sample first: the replay is drained here, and the leak
    # verdict must compare the baseline against the run's TRUE final
    # live bytes — a final tick that allocated inside the throttle
    # window would otherwise be judged on a stale cached sample (the
    # same hazard loadgen/openloop force-sample against).
    mled.sample(force=True)
    out = mled.summary()
    if args.memory_out:
        mled.dump_jsonl(args.memory_out)
        out["ledger_path"] = str(args.memory_out)
    return out


def _build_slo(args, metrics, sample_fn, tracer, flight):
    """(timeline, engine, sampler) from the serve SLO flags, all None
    when neither --slo nor --timeline-dir is set (the byte-identical
    default path). The sampler is returned STARTED; the caller stops it
    (or lets Gateway.close do so when it is attached there)."""
    if not (args.slo or args.timeline_dir):
        return None, None, None
    from ..obs import SLOConfig, SLOEngine, Timeline, TimelineSampler

    timeline = Timeline()
    engine = None
    if args.slo:
        config = SLOConfig.from_json(args.slo)
        engine = SLOEngine(
            config, timeline, metrics=metrics, tracer=tracer, flight=flight
        )
    sampler = TimelineSampler(
        timeline,
        sample_fn,
        period_s=max(0.001, args.timeline_period_ms / 1e3),
        metrics=metrics,
        on_sample=(
            None if engine is None
            else (lambda _tl, now: engine.evaluate(now))
        ),
    )
    sampler.start()
    return timeline, engine, sampler


def _slo_summary(args, timeline, engine, sampler) -> dict:
    """The serve summary's "slo" block (+ the timeline dump side effect)."""
    out: dict = {
        "samples": sampler.samples,
        "sample_errors": sampler.errors,
        "series": len(timeline.names()),
    }
    if engine is not None:
        out["alerts_open"] = len(engine.firing())
        out["events"] = list(engine.events)
    if args.timeline_dir:
        path = Path(args.timeline_dir) / "timeline.jsonl"
        timeline.dump(path)
        out["timeline_path"] = str(path)
    return out


def serve_main(argv=None) -> int:
    """``solver serve``: replay a churn trace through the scheduler daemon."""
    args = build_serve_parser().parse_args(argv)

    from ..axon_guard import force_cpu_if_env_requested

    force_cpu_if_env_requested()
    if (args.mesh_shards or 1) > 1:
        # Same pre-backend ordering as the one-shot solver: the daemon's
        # first tick initializes the backend, so the flag goes in now.
        from ..utils import shardcompat

        shardcompat.force_host_devices(args.mesh_shards)

    # Gateway tier: any of the scale-out flags (or a fleet-tagged trace)
    # diverts to the sharded multi-worker path. With none of them, the
    # code below runs exactly the PR 5/6 single-scheduler daemon.
    gateway_mode = bool(
        args.workers > 1
        or args.listen
        or args.snapshot_dir
        or args.resume
        # Admission control lives in the gateway tier (bounded queues
        # are per solve worker); asking for it engages that path.
        or args.max_queue_depth is not None
        or args.coalesce
        or args.degrade_depth is not None
        or args.mem_degrade_headroom_mb is not None
        # Process workers (and their supervision) ARE the gateway tier.
        or args.worker_backend != "thread"
        or args.scheduler_factory is not None
        or args.supervise
    )
    if args.mem_degrade_headroom_mb is not None and not (
        args.memory_ledger or args.memory_out
    ):
        # Degrading on headroom nobody measures would silently never
        # fire; make the dependency explicit instead.
        print(
            "error: --mem-degrade-headroom-mb needs --memory-ledger "
            "(headroom comes from the memory ledger's budget - RSS)",
            file=sys.stderr,
        )
        return 2
    if not gateway_mode and Path(args.trace).is_file():
        from ..gateway.traces import is_gateway_trace

        gateway_mode = is_gateway_trace(args.trace)
    if gateway_mode:
        if args.jax_profile_dir:
            # jax.profiler.trace is process-global; two shard workers
            # profiling their first ticks concurrently would race it.
            print(
                "error: --jax-profile-dir needs the single-scheduler path "
                "(no gateway flags, --workers 1, single-fleet trace)",
                file=sys.stderr,
            )
            return 2
        return _serve_gateway(args)
    if args.snapshot_at is not None or args.halt_after_snapshot:
        print(
            "error: --snapshot-at/--halt-after-snapshot need "
            "--snapshot-dir (the gateway path)",
            file=sys.stderr,
        )
        return 2

    from ..common import load_from_profile_folder, load_model_profile
    from ..sched import Scheduler, drift_warm_share, read_trace, replay
    from ..utils import make_synthetic_fleet

    folder = Path(args.profile)
    if not folder.is_dir():
        print(f"error: {folder} is not a directory", file=sys.stderr)
        return 2
    if args.synthetic_fleet > 0:
        model = load_model_profile(folder / "model_profile.json")
        devices = make_synthetic_fleet(args.synthetic_fleet, seed=args.fleet_seed)
    else:
        devices, model = load_from_profile_folder(folder)

    trace_path = Path(args.trace)
    if not trace_path.is_file():
        print(f"error: trace {trace_path} not found", file=sys.stderr)
        return 2
    try:
        events = read_trace(trace_path)
    except (OSError, ValueError) as e:  # ValidationError is a ValueError
        print(f"error: cannot parse trace: {e}", file=sys.stderr)
        return 2
    if not events:
        print("error: trace is empty", file=sys.stderr)
        return 2

    k_candidates = None
    if args.k_candidates:
        k_candidates = [int(x) for x in args.k_candidates.split(",") if x.strip()]

    plan = None
    if args.fault_plan:
        from ..sched import FaultPlan

        try:
            plan = FaultPlan.from_json(args.fault_plan)
        except (OSError, ValueError) as e:
            print(f"error: cannot load --fault-plan: {e}", file=sys.stderr)
            return 2
        if args.fault_seed is not None:
            plan = plan.model_copy(update={"seed": args.fault_seed})

    # The hardening knobs appear in the scheduler (and the summary) only
    # when asked for: a plain `serve` replay stays byte-identical to the
    # pre-chaos service, fault machinery and all.
    hardened = (
        plan is not None
        or args.deadline_ms is not None
        or args.max_retries
        or args.breaker_threshold is not None
    )
    harden_kw = {}
    if args.deadline_ms is not None:
        harden_kw["solve_deadline_s"] = args.deadline_ms / 1e3
    if args.max_retries:
        harden_kw["max_retries"] = args.max_retries
    if args.breaker_threshold is not None:
        harden_kw["breaker_threshold"] = args.breaker_threshold

    tracer, writer, flight = _build_obs(args)
    sched = Scheduler(
        devices,
        model,
        diagnostics=args.solver_diagnostics,
        mip_gap=args.mip_gap,
        kv_bits=args.kv_bits,
        backend=args.backend,
        k_candidates=k_candidates,
        warm_pool_size=args.warm_pool,
        cold_start=args.cold_start,
        lp_backend=args.lp_backend,
        pdhg_iters=args.pdhg_iters,
        pdhg_restart_tol=args.pdhg_restart_tol,
        mesh_shards=args.mesh_shards,
        pdhg_dtype=args.pdhg_dtype,
        risk_aware=args.risk_aware,
        risk_samples=args.risk_samples,
        risk_seed=args.risk_seed,
        speculative=args.speculate,
        spec_k=args.spec_k,
        spec_tolerance=args.spec_tolerance,
        tracer=tracer,
        flight=flight,
        jax_profile_dir=args.jax_profile_dir,
        **harden_kw,
    )

    def log_event(ev, view, ms):
        # The daemon's event log: one line per tick, streamed.
        if args.quiet:
            return
        r = view.result
        risk = ""
        if view.twin_p95_s is not None:
            star = "*" if view.risk_selected else ""
            risk = f" twin_p95={view.twin_p95_s:.4f}{star}"
        print(
            f"[{sched.fleet.seq:4d}] {ev.kind:<10s} "
            f"M={len(r.w):2d} mode={view.mode:<6s} "
            f"certified={str(r.certified):<5s} k={r.k:<3d} "
            f"obj={r.obj_value:.6f} {ms:8.1f} ms{risk}"
        )

    timeline, slo_engine, sampler = _build_slo(
        args, sched.metrics, sched.timeline_sample, tracer, flight
    )
    led, led_owned = _build_compile_ledger(args)
    mled, mled_owned = _build_memory_ledger(args)
    compile_state = {"handled": 0, "warm_token": None, "warm_marked": False}

    def on_event(ev, view, ms):
        log_event(ev, view, ms)
        if (led is None and mled is None) or compile_state["warm_marked"]:
            return
        compile_state["handled"] += 1
        if compile_state["handled"] >= args.compile_warm_events:
            # Warm boundary: everything this single fleet compiles (and
            # persistently allocates), it does in its first
            # --compile-warm-events ticks — the same boundary marks the
            # compile ledger's warm phase and pins the memory ledger's
            # leak-gate baseline.
            compile_state["warm_marked"] = True
            if led is not None:
                compile_state["warm_token"] = led.seq()
            if mled is not None:
                mled.mark_warm()

    chaos = None
    try:
        if plan is not None:
            from ..sched import chaos_replay

            chaos = chaos_replay(sched, events, plan, on_event=on_event)
            report = _chaos_to_replay_report(chaos, sched)
        else:
            report = replay(sched, events, on_event=on_event)
    except (RuntimeError, ValueError) as e:
        print(f"error: replay failed: {e}", file=sys.stderr)
        return 1
    finally:
        if sampler is not None:
            sampler.stop()  # before close: no sampling a torn-down daemon
        sched.close()  # release the deadline worker (no-op when unused)
        if tracer is not None:
            tracer.close()  # flush the span JSONL
        _release_compile_ledger(led_owned)
        _release_memory_ledger(mled_owned)

    summary = {
        "replay": report.summary(),
        "drift_warm_share": round(drift_warm_share(sched.metrics), 4),
        "metrics": sched.metrics_snapshot(),
    }
    if hardened:
        summary["health"] = sched.health_snapshot()
    if chaos is not None:
        summary["chaos"] = chaos.summary()
        if flight is not None and chaos.violations(sched.fleet.model.L):
            # A violated soak is exactly the post-mortem moment the flight
            # recorder exists for — dump before the process reports it.
            if flight.trigger("default", "chaos_violation") is not None:
                sched.metrics.inc("flight_dumps")
    if args.speculate:
        summary["speculation"] = sched.speculation_snapshot()
    if led is not None:
        summary["compile"] = _compile_summary(
            args, led, compile_state["warm_token"]
        )
    if mled is not None:
        summary["memory"] = _memory_summary(args, mled)
    if sampler is not None:
        summary["slo"] = _slo_summary(args, timeline, slo_engine, sampler)
    if writer is not None or flight is not None:
        summary["obs"] = _obs_summary(writer, flight)
    if args.risk_aware:
        c = sched.metrics.counters
        summary["risk"] = {
            "evals": c["risk_eval"],
            "candidates": c["risk_candidates"],
            "switches": c["risk_switch"],
            "errors": c["risk_error"],
        }
    print(json.dumps(summary))
    if args.metrics_out:
        Path(args.metrics_out).write_text(json.dumps(summary, indent=2))
    if args.chaos_check:
        if chaos is None:
            print(
                "error: --chaos-check needs --fault-plan (the soak "
                "contract is defined over an injected fault schedule)",
                file=sys.stderr,
            )
            return 2
        violations = chaos.violations(sched.fleet.model.L)
        if violations:
            for v in violations:
                print(f"chaos violation: {v}", file=sys.stderr)
            return 1
        print(
            f"chaos soak OK: {chaos.injected.get('injected_total', 0)} "
            f"fault(s) injected, {chaos.summary()['quarantined']} "
            f"quarantined, healthy after {chaos.ticks_to_healthy} clean "
            "tick(s)"
        )
    if args.fail_uncertified and (
        report.structural_uncertified or report.failed_ticks
    ):
        print(
            f"error: {report.structural_uncertified} structural event(s) "
            f"missed the optimality certificate, {report.failed_ticks} "
            "tick(s) produced no placement at all",
            file=sys.stderr,
        )
        return 1
    return 0


def _serve_gateway(args) -> int:
    """``solver serve`` through the gateway tier (``distilp_tpu.gateway``).

    Engaged by --workers > 1, --listen, --snapshot-dir/--resume, or a
    fleet-tagged (multi-fleet) trace. The replay itself is SEQUENTIAL in
    trace order — this path is the correctness/operations surface
    (deterministic replays, snapshot cycles, chaos soaks); concurrent
    throughput is the load generator's job (``gateway.loadgen``,
    ``bench.py`` gateway section).
    """
    import time as _time

    from ..common import load_from_profile_folder, load_model_profile
    from ..gateway import (
        Gateway,
        ShardFacade,
        load_snapshot,
        read_gateway_trace,
        save_snapshot,
    )
    from ..gateway.traces import is_gateway_trace, make_fleet_from_spec
    from ..sched import STRUCTURAL_KINDS, drift_warm_share, read_trace
    from ..sched.metrics import _quantile
    from ..utils import make_synthetic_fleet

    folder = Path(args.profile)
    if not folder.is_dir():
        print(f"error: {folder} is not a directory", file=sys.stderr)
        return 2
    trace_path = Path(args.trace)
    if not trace_path.is_file():
        print(f"error: trace {trace_path} not found", file=sys.stderr)
        return 2
    if args.snapshot_at is not None and not args.snapshot_dir:
        print("error: --snapshot-at needs --snapshot-dir", file=sys.stderr)
        return 2
    if args.resume and not args.snapshot_dir:
        print("error: --resume needs --snapshot-dir", file=sys.stderr)
        return 2

    model = load_model_profile(folder / "model_profile.json")
    try:
        multi = is_gateway_trace(trace_path)
        if multi:
            specs, items = read_gateway_trace(trace_path)
        else:
            events = read_trace(trace_path)
            specs = {}
            items = [("default", ev) for ev in events]
    except (OSError, ValueError) as e:
        print(f"error: cannot parse trace: {e}", file=sys.stderr)
        return 2
    if not items:
        print("error: trace is empty", file=sys.stderr)
        return 2

    k_candidates = None
    if args.k_candidates:
        k_candidates = [int(x) for x in args.k_candidates.split(",") if x.strip()]

    plan = None
    if args.fault_plan:
        if multi:
            # The fault plan's tick schedule is defined over ONE fleet's
            # replay; spraying it across interleaved fleets would make the
            # soak contract unverifiable.
            print(
                "error: --fault-plan needs a single-fleet trace (chaos "
                "per-shard isolation is pinned in tests/test_gateway.py)",
                file=sys.stderr,
            )
            return 2
        if args.snapshot_at is not None or args.halt_after_snapshot:
            # The chaos replay loop has no snapshot hook; silently running
            # the soak WITHOUT taking the requested snapshot would strand
            # the operator's next --resume with nothing on disk.
            print(
                "error: --fault-plan cannot combine with --snapshot-at/"
                "--halt-after-snapshot (the chaos soak does not snapshot "
                "mid-replay)",
                file=sys.stderr,
            )
            return 2
        from ..sched import FaultPlan

        try:
            plan = FaultPlan.from_json(args.fault_plan)
        except (OSError, ValueError) as e:
            print(f"error: cannot load --fault-plan: {e}", file=sys.stderr)
            return 2
        if args.fault_seed is not None:
            plan = plan.model_copy(update={"seed": args.fault_seed})

    scheduler_kwargs = dict(
        mip_gap=args.mip_gap,
        kv_bits=args.kv_bits,
        backend=args.backend,
        diagnostics=args.solver_diagnostics,
        k_candidates=k_candidates,
        warm_pool_size=args.warm_pool,
        cold_start=args.cold_start,
        lp_backend=args.lp_backend,
        pdhg_iters=args.pdhg_iters,
        pdhg_restart_tol=args.pdhg_restart_tol,
        mesh_shards=getattr(args, "mesh_shards", None),
        pdhg_dtype=getattr(args, "pdhg_dtype", None),
        risk_aware=args.risk_aware,
        risk_samples=args.risk_samples,
        risk_seed=args.risk_seed,
        speculative=args.speculate,
        spec_k=args.spec_k,
        spec_tolerance=args.spec_tolerance,
    )
    if args.deadline_ms is not None:
        scheduler_kwargs["solve_deadline_s"] = args.deadline_ms / 1e3
    if args.max_retries:
        scheduler_kwargs["max_retries"] = args.max_retries
    if args.breaker_threshold is not None:
        scheduler_kwargs["breaker_threshold"] = args.breaker_threshold

    if args.supervise and args.worker_backend != "process":
        print(
            "error: --supervise needs --worker-backend process (thread "
            "workers share the gateway's life; there is no child to "
            "respawn)",
            file=sys.stderr,
        )
        return 2
    tracer, writer, flight = _build_obs(args)
    try:
        gw = Gateway(
            n_workers=args.workers,
            scheduler_kwargs=scheduler_kwargs,
            scheduler_factory=args.scheduler_factory,
            tracer=tracer,
            flight=flight,
            max_queue_depth=args.max_queue_depth,
            coalesce=args.coalesce,
            degrade_depth=args.degrade_depth,
            mem_degrade_headroom_bytes=(
                args.mem_degrade_headroom_mb * 1e6
                if args.mem_degrade_headroom_mb is not None
                else None
            ),
            worker_backend=args.worker_backend,
            supervise=args.supervise,
            recovery_dir=args.recovery_dir,
            snapshot_every=args.snapshot_every,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    timeline, slo_engine, sampler = _build_slo(
        args, gw.metrics, gw.timeline_sample, tracer, flight
    )
    if sampler is not None:
        # Attached: Gateway.close() stops the sampler before the workers
        # and --listen keeps it (and /slo, /signals) live until ^C.
        gw.attach_sampler(sampler)
        gw.attach_slo(slo_engine, timeline, capacity_eps=args.capacity_eps)
    led, led_owned = _build_compile_ledger(args)
    mled, mled_owned = _build_memory_ledger(args)
    # Warm boundary for the ledger: marked once EVERY fleet actually
    # REPLAYED this run has handled --compile-warm-events events
    # (ordering-independent — the smoke trace interleaves fleets
    # round-robin, but nothing guarantees that). Targets are filled in
    # from run_items below, AFTER the resume cursor is applied: a fleet
    # fully covered by a snapshot (or one with fewer events than the
    # knob) must not hold the boundary open forever, so each fleet's
    # target is min(knob, its replayed-event count). Compile events past
    # the mark are warm-phase compiles: the zero-recompile invariant.
    compile_state = {
        "counts": {}, "targets": {}, "warm_token": None, "warm_marked": False,
    }

    def _note_handled_for_ledger(fleet_id: str) -> None:
        targets = compile_state["targets"]
        if (
            (led is None and mled is None)
            or compile_state["warm_marked"]
            or not targets
        ):
            return
        counts = compile_state["counts"]
        counts[fleet_id] = counts.get(fleet_id, 0) + 1
        if all(counts.get(f, 0) >= n for f, n in targets.items()):
            # One warm boundary for BOTH ledgers: compile events past it
            # are warm-phase compiles, live-array growth past it is a
            # leak.
            compile_state["warm_marked"] = True
            if led is not None:
                compile_state["warm_token"] = led.seq()
            if mled is not None:
                mled.mark_warm()

    try:
        if args.resume:
            try:
                snap = load_snapshot(args.snapshot_dir)
            except (OSError, ValueError) as e:
                print(f"error: cannot load snapshot: {e}", file=sys.stderr)
                return 2
            gw.load_snapshot(snap)
            for fleet_id in ([f for f in specs] if multi else ["default"]):
                if fleet_id not in gw.fleet_ids():
                    print(
                        f"error: trace fleet {fleet_id!r} is not in the "
                        "snapshot; resume needs the same trace",
                        file=sys.stderr,
                    )
                    return 2
        elif multi:
            for fleet_id, spec in specs.items():
                gw.register_fleet(
                    fleet_id, make_fleet_from_spec(fleet_id, spec), model
                )
        else:
            if args.synthetic_fleet > 0:
                devices = make_synthetic_fleet(
                    args.synthetic_fleet, seed=args.fleet_seed
                )
            else:
                devices, model = load_from_profile_folder(folder)
            gw.register_fleet("default", devices, model)

        # Resume cursor: skip the per-fleet prefix the snapshot already
        # covers (Gateway.uncovered owns the contract — quarantined
        # events advanced the cursor too and must not replay).
        run_items = gw.uncovered(items)
        if led is not None or mled is not None:
            totals: dict = {}
            for f, _ev in run_items:
                totals[f] = totals.get(f, 0) + 1
            compile_state["targets"] = {
                f: min(args.compile_warm_events, n)
                for f, n in totals.items()
            }

        def log_event(fleet_id, ev, view, ms):
            if args.quiet:
                return
            r = view.result
            print(
                f"[{fleet_id} {view.fleet_seq:4d}] {ev.kind:<10s} "
                f"M={len(r.w):2d} mode={view.mode:<6s} "
                f"certified={str(r.certified):<5s} k={r.k:<3d} "
                f"obj={r.obj_value:.6f} {ms:8.1f} ms"
            )

        chaos = None
        snapshot_taken = False
        lat = []
        uncert = 0
        final_views = {}
        if plan is not None:
            from ..sched import chaos_replay

            facade = ShardFacade(gw, "default")

            def _chaos_on_event(ev, view, ms):
                log_event("default", ev, view, ms)
                _note_handled_for_ledger("default")

            chaos = chaos_replay(
                facade,
                [ev for _, ev in run_items],
                plan,
                on_event=_chaos_on_event,
                # Process-channel faults (child_kill / rpc_torn /
                # rpc_delay) aim at whichever worker currently owns the
                # fleet's shard; the recovery probe stamps the report
                # with the supervision audit (events_lost, MTTR, ...).
                process_hook=(
                    gw.chaos_process_hook("default")
                    if args.supervise
                    else None
                ),
                recovery_probe=(
                    gw.recovery_status if args.supervise else None
                ),
            )
            report = _chaos_to_replay_report(chaos, facade)
            if chaos.views:
                final_views["default"] = chaos.views[-1]
        else:
            t_start = _time.perf_counter()
            for handled, (fleet_id, ev) in enumerate(run_items, 1):
                t0 = _time.perf_counter()
                view = gw.handle_event(fleet_id, ev)
                ms = (_time.perf_counter() - t0) * 1e3
                lat.append(ms)
                final_views[fleet_id] = view
                _note_handled_for_ledger(fleet_id)
                if (
                    ev.kind in STRUCTURAL_KINDS
                    and view.events_behind == 0
                    and not view.result.certified
                ):
                    uncert += 1
                log_event(fleet_id, ev, view, ms)
                if args.snapshot_at is not None and handled == args.snapshot_at:
                    save_snapshot(gw.snapshot(), args.snapshot_dir)
                    snapshot_taken = True
                    if not args.quiet:
                        print(
                            f"[snapshot] {len(gw.fleet_ids())} shard(s) -> "
                            f"{args.snapshot_dir} after {handled} event(s)"
                        )
                    if args.halt_after_snapshot:
                        break
            total_s = _time.perf_counter() - t_start
            srt = sorted(lat)
            report = None
            replay_summary = {
                "events": len(lat),
                "events_per_sec": round(len(lat) / total_s, 2)
                if total_s > 0
                else 0.0,
                "p50_ms": round(_quantile(srt, 0.50), 3),
                "p99_ms": round(_quantile(srt, 0.99), 3),
                "structural_uncertified": uncert,
            }

        mx = gw.metrics_snapshot()
        totals = mx["shard_totals"]
        if report is not None:  # chaos path reuses the ReplayReport shape
            replay_summary = report.summary()
        replay_summary["failed_ticks"] = totals.get("tick_failed", 0)
        summary = {
            "replay": replay_summary,
            "gateway": {
                "workers": args.workers,
                "fleets": len(gw.fleet_ids()),
                "resumed": bool(args.resume),
                "snapshot_taken": snapshot_taken,
                "warm_resumes": totals.get("warm_resumes", 0),
                "cold_resumes": totals.get("cold_resumes", 0),
                "tick_cold": totals.get("tick_cold", 0),
                "tick_warm": totals.get("tick_warm", 0),
                "tick_margin": totals.get("tick_margin", 0),
                "events_quarantined": totals.get("events_quarantined", 0),
            },
            "final_placements": {
                f: {
                    "k": v.result.k,
                    "w": v.result.w,
                    "n": v.result.n,
                    "y": v.result.y,
                    "obj_value": v.result.obj_value,
                    "certified": v.result.certified,
                }
                for f, v in sorted(final_views.items())
            },
            "health": gw.healthz(),
            "metrics": mx,
        }
        if (
            args.max_queue_depth is not None
            or args.coalesce
            or args.degrade_depth is not None
        ):
            summary["gateway"]["events_shed"] = mx["counters"].get(
                "events_shed", 0
            )
            summary["gateway"]["events_coalesced"] = totals.get(
                "events_coalesced", 0
            )
            summary["gateway"]["spec_near_hits"] = totals.get(
                "spec_near_hit", 0
            )
        if not multi:
            summary["drift_warm_share"] = round(
                drift_warm_share(gw.scheduler("default").metrics), 4
            )
        if args.speculate:
            # Tier-level speculation view: the shard-total counters (each
            # shard's bank and forecaster are worker-owned; this is the
            # aggregate the operator gates on).
            s_hits = totals.get("spec_hit", 0)
            s_probes = s_hits + totals.get("spec_miss", 0)
            summary["speculation"] = {
                "hits": s_hits,
                "misses": totals.get("spec_miss", 0),
                "presolved": totals.get("spec_presolve", 0),
                "presolve_failed": totals.get("spec_presolve_failed", 0),
                "stale": totals.get("spec_stale", 0),
                "hit_rate": round(s_hits / s_probes, 4) if s_probes else 0.0,
            }
        if led is not None:
            summary["compile"] = _compile_summary(
                args, led, compile_state["warm_token"]
            )
        if mled is not None:
            summary["memory"] = _memory_summary(args, mled)
        chaos_L = None
        if chaos is not None:
            # Proxy-safe L read: on the process backend the shard
            # scheduler is a SchedulerProxy (no ``.fleet``); the facade
            # rebuilds the fleet view over one RPC, and a factory-built
            # stub with no fleet degrades to None (records carry their
            # own per-tick L anyway).
            fl = getattr(ShardFacade(gw, "default"), "fleet", None)
            chaos_L = getattr(getattr(fl, "model", None), "L", None)
            summary["chaos"] = chaos.summary()
            if flight is not None and chaos.violations(chaos_L):
                if flight.trigger("default", "chaos_violation") is not None:
                    gw.metrics.inc("flight_dumps")
        if args.supervise:
            # The supervision audit rides the report even without chaos:
            # a clean supervised flood must show zero crashes and
            # events_lost == 0 (the WAL/snapshot machinery ran for real).
            summary["recovery"] = gw.recovery_status()
        if sampler is not None:
            summary["slo"] = _slo_summary(args, timeline, slo_engine, sampler)
        if writer is not None or flight is not None:
            summary["obs"] = _obs_summary(writer, flight)
        print(json.dumps(summary))
        if args.metrics_out:
            Path(args.metrics_out).write_text(json.dumps(summary, indent=2))

        if args.chaos_check:
            if chaos is None:
                print(
                    "error: --chaos-check needs --fault-plan",
                    file=sys.stderr,
                )
                return 2
            violations = chaos.violations(chaos_L)
            if violations:
                for v in violations:
                    print(f"chaos violation: {v}", file=sys.stderr)
                return 1
            ok_line = (
                f"chaos soak OK ({args.workers} workers): "
                f"{chaos.injected.get('injected_total', 0)} fault(s) "
                f"injected, {chaos.summary()['quarantined']} quarantined, "
                f"healthy after {chaos.ticks_to_healthy} clean tick(s)"
            )
            if chaos.recovery is not None:
                rec = chaos.recovery
                ok_line += (
                    f"; crash contract OK: {rec.get('worker_crashes', 0)} "
                    f"crash(es), {rec.get('child_respawns', 0)} "
                    f"respawn(s), {rec.get('workers_quarantined', 0)} "
                    f"quarantined, events_lost="
                    f"{rec.get('events_lost', 0)}, "
                    f"replayed={rec.get('events_replayed', 0)}"
                )
            print(ok_line)
        if args.fail_uncertified and (
            replay_summary.get("structural_uncertified")
            or replay_summary["failed_ticks"]
        ):
            print(
                f"error: {replay_summary.get('structural_uncertified', 0)} "
                "structural event(s) missed the optimality certificate, "
                f"{replay_summary['failed_ticks']} tick(s) produced no "
                "placement at all",
                file=sys.stderr,
            )
            return 1
        if args.listen:
            return _listen_forever(gw, args.listen, quiet=args.quiet)
        return 0
    finally:
        gw.close()
        if tracer is not None:
            tracer.close()  # flush the span JSONL
        _release_compile_ledger(led_owned)
        _release_memory_ledger(mled_owned)


def _listen_forever(gw, listen: str, quiet: bool = False) -> int:
    """Serve the gateway's HTTP API until interrupted (serve --listen)."""
    import asyncio

    from ..gateway import GatewayHTTPServer

    host, _, port_s = listen.rpartition(":")
    host = host or "127.0.0.1"
    try:
        port = int(port_s)
    except ValueError:
        print(f"error: --listen wants HOST:PORT (got {listen!r})", file=sys.stderr)
        return 2

    async def _run() -> None:
        server = GatewayHTTPServer(gw, host=host, port=port)
        await server.start()
        if not quiet:
            print(
                f"gateway listening on http://{host}:{server.port} "
                "(POST /events, GET /placement/<fleet>, /healthz, /metrics)"
            )
        await server.serve_forever()

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass
    return 0


def _chaos_to_replay_report(chaos, sched):
    """Adapt a ChaosReport to the ReplayReport summary the serve CLI
    prints, so the chaos path reuses the same summary/exit plumbing.

    Latency stats cover the TRACE events only — injected quarantine
    round-trips and recovery ticks are near-zero and would flatter the
    percentiles relative to a plain replay of the same trace (the
    injected/recovery side lives in the summary's "chaos" block instead).
    """
    from ..sched import STRUCTURAL_KINDS, ReplayReport
    from ..sched.metrics import _quantile

    trace_recs = [r for r in chaos.records if r.source == "trace"]
    lat = [r.ms for r in trace_recs]
    srt = sorted(lat)
    uncert = sum(
        1
        for r in trace_recs
        if r.kind in STRUCTURAL_KINDS
        and r.view.events_behind == 0
        and not r.view.result.certified
    )
    total_s = sum(r.ms for r in chaos.records) / 1e3
    return ReplayReport(
        views=chaos.views,
        latencies_ms=lat,
        events_per_sec=len(lat) / total_s if total_s > 0 else 0.0,
        p50_ms=_quantile(srt, 0.50),
        p99_ms=_quantile(srt, 0.99),
        structural_uncertified=uncert,
        # .get: on the process backend the facade hands a plain counter
        # dict (no defaultdict semantics) snapshotted over RPC.
        failed_ticks=sched.metrics.counters.get("tick_failed", 0),
    )


def build_overload_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver overload",
        description="replay an open-loop arrival schedule against the "
        "gateway (events fire at their scheduled time — lateness "
        "accumulates, the generator never throttles) and report "
        "scheduled-time latency, sheds, coalesces and goodput; see "
        "distilp_tpu.traffic and README 'Overload & admission control'",
    )
    p.add_argument(
        "--trace",
        required=True,
        help="open-loop JSONL schedule (fleet-tagged, timestamped; "
        "tests/traces/openloop_*.jsonl are committed seeded captures, "
        "traffic.generate_openloop_schedule makes new ones)",
    )
    p.add_argument(
        "--profile", "-p", required=True,
        help="profile folder; model_profile.json is the served model",
    )
    p.add_argument("--workers", type=int, default=2)
    p.add_argument(
        "--time-scale",
        type=float,
        default=1.0,
        help="compress (<1) or dilate (>1) the schedule's timeline: the "
        "same committed capture replays in real time or as a saturating "
        "flood, deterministically",
    )
    p.add_argument("--k-candidates", default=None)
    p.add_argument("--mip-gap", type=float, default=1e-3)
    p.add_argument("--kv-bits", default="4bit")
    p.add_argument("--warmup", type=int, default=2,
                   help="closed-loop warmup events per fleet (cold solve "
                   "+ jit compile, excluded from the open-loop phase)")
    p.add_argument(
        "--max-queue-depth", type=int, default=None, metavar="N",
        help="admission gate: shed events arriving at a queue holding N",
    )
    p.add_argument(
        "--coalesce", action="store_true",
        help="fold same-shard queued drift events into one solve",
    )
    p.add_argument(
        "--combine", action="store_true",
        help="batch pending ticks ACROSS shards into padded device "
        "batches behind the coalescer (one _solve_batched dispatch per "
        "bucket flush; README 'Cross-shard batched solving'); enables "
        "the compile ledger so the zero-recompile gate is auditable",
    )
    p.add_argument(
        "--degrade-depth", type=int, default=None, metavar="N",
        help="queue depth past which speculative shards may serve a "
        "banked near-match (mode='spec_near'); pair with --speculate",
    )
    p.add_argument(
        "--speculate", action="store_true",
        help="enable speculative replanning on every shard (the bank "
        "degraded-mode serving draws from)",
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="exit 1 unless the admission contract holds: every shed "
        "counted AND flight-recorded with reconciling per-fleet indices, "
        "every served placement structurally valid, no failed ticks",
    )
    p.add_argument(
        "--expect-sheds", action="store_true",
        help="with --check: additionally fail if NOTHING was shed (the "
        "smoke must actually overload the gate it is testing)",
    )
    p.add_argument(
        "--expect-coalesced", action="store_true",
        help="with --check: additionally fail if nothing was coalesced",
    )
    p.add_argument(
        "--expect-no-sheds", action="store_true",
        help="with --check: additionally fail if ANYTHING was shed (the "
        "coalesce smoke's contract: the flood folds instead of shedding)",
    )
    p.add_argument(
        "--expect-combined", action="store_true",
        help="with --check and --combine: fail unless combined batches "
        "actually served lanes, nothing fell back to a per-shard solve, "
        "and the measured phase compiled NOTHING (warm_phase_events == 0 "
        "— the committed-bucket zero-recompile contract)",
    )
    p.add_argument(
        "--slo", default=None, metavar="SPEC.json",
        help="attach the SLO engine to the flood: a timeline sampler "
        "runs for the arm's whole life, the executor feeds per-event "
        "scheduled-time latency, and burn-rate alerts open/close live "
        "(counters + flight records; report grows an 'slo' block)",
    )
    p.add_argument(
        "--settle-s", type=float, default=0.0,
        help="keep sampling this long AFTER the schedule drains — the "
        "recovery window a fired burn-rate alert needs to clear",
    )
    p.add_argument(
        "--timeline-out", default=None, metavar="FILE",
        help="dump the sampled timeline JSONL here (replay offline with "
        "`solver slo --timeline`)",
    )
    p.add_argument(
        "--expect-alert", action="append", default=None, metavar="SEV",
        help="with --check: fail unless an alert of this severity "
        "OPENED during the flood and CLOSED by the end of --settle-s, "
        "with the open/close counters reconciling record-by-record "
        "against the flight recorder's slo ring (repeatable)",
    )
    p.add_argument(
        "--worker-backend", choices=("thread", "process"), default="thread",
        help="host shard workers in threads (default) or dedicated "
        "subprocesses (own GIL, own XLA runtime; README 'Closed-loop "
        "autoscaling & process workers')",
    )
    p.add_argument(
        "--scheduler-factory", default=None, metavar="MOD:FN",
        help="'module:callable' scheduler factory resolved in whichever "
        "process hosts the shard — the only factory form that crosses a "
        "process boundary (tests.procstub:make_scheduler is the no-jax "
        "stub the smokes use)",
    )
    p.add_argument(
        "--autoscale", default=None, metavar="POLICY.json",
        help="close the loop: build the gateway dynamic and run a "
        "ControlLoop under this policy for the flood's whole life — "
        "spawning/retiring workers with live warm shard migration, "
        "flipping degrade admission; report grows a 'control' block "
        "with the action trail + flight reconciliation",
    )
    p.add_argument(
        "--control-period-s", type=float, default=0.1,
        help="control loop decision period (seconds)",
    )
    p.add_argument(
        "--capacity-probe", type=int, default=0, metavar="N",
        help="with --autoscale: closed-loop probe of N events/fleet "
        "post-warmup to auto-populate the /signals headroom denominator "
        "(refreshed per worker-count change; 0 skips the probe)",
    )
    p.add_argument(
        "--expect-scale", type=int, default=None, metavar="N",
        help="with --check and --autoscale: fail unless the controller "
        "scaled the fleet out to at least N workers during the flood "
        "(and the control accounting reconciled record-by-record)",
    )
    p.add_argument("--metrics-out", default=None,
                   help="write the report JSON here too")
    p.add_argument("--quiet", action="store_true", help="summary line only")
    return p


def overload_main(argv=None) -> int:
    """``solver overload``: open-loop schedule -> gateway, admission on."""
    args = build_overload_parser().parse_args(argv)

    from ..axon_guard import force_cpu_if_env_requested

    force_cpu_if_env_requested()

    from ..common import load_model_profile
    from ..obs import FlightRecorder
    from ..traffic import read_openloop_trace, run_openloop

    folder = Path(args.profile)
    model_path = (
        folder / "model_profile.json" if folder.is_dir() else folder
    )
    if not model_path.is_file():
        print(f"error: no model profile at {model_path}", file=sys.stderr)
        return 2
    model = load_model_profile(model_path)
    try:
        specs, items = read_openloop_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"error: cannot parse open-loop trace: {e}", file=sys.stderr)
        return 2
    if not items:
        print("error: schedule has no events", file=sys.stderr)
        return 2
    k_candidates = None
    if args.k_candidates:
        k_candidates = [
            int(x) for x in args.k_candidates.split(",") if x.strip()
        ]
    slo_config = None
    if args.slo:
        from ..obs import SLOConfig

        try:
            slo_config = SLOConfig.from_json(args.slo)
        except (OSError, ValueError) as e:
            print(f"error: cannot load --slo spec: {e}", file=sys.stderr)
            return 2
    autoscale = None
    if args.autoscale:
        from ..control import ControlPolicy

        try:
            autoscale = ControlPolicy.from_json(args.autoscale)
        except (OSError, ValueError) as e:
            print(f"error: cannot load --autoscale: {e}", file=sys.stderr)
            return 2
    timeline = None
    if args.slo or args.timeline_out or args.autoscale:
        from ..obs import Timeline

        timeline = Timeline()
    if args.combine:
        # The zero-recompile gate needs the ambient ledger: run_openloop
        # reads warm-phase compile events off compile_ledger.current().
        from ..obs import compile_ledger as _compile_ledger

        _compile_ledger.enable()
    # A recorder is always attached here: the --check reconciliation is
    # the point of the command, and sheds must be observable to audit.
    flight = FlightRecorder(capacity=max(256, 2 * len(items)))
    report = run_openloop(
        model,
        specs,
        items,
        args.workers,
        time_scale=args.time_scale,
        warmup_per_fleet=args.warmup,
        k_candidates=k_candidates,
        mip_gap=args.mip_gap,
        kv_bits=args.kv_bits,
        scheduler_kwargs=(
            {"speculative": True} if args.speculate else None
        ),
        max_queue_depth=args.max_queue_depth,
        coalesce=args.coalesce,
        combine=args.combine,
        degrade_depth=args.degrade_depth,
        flight=flight,
        slo_config=slo_config,
        timeline=timeline,
        settle_s=args.settle_s,
        worker_backend=args.worker_backend,
        scheduler_factory=args.scheduler_factory,
        autoscale=autoscale,
        control_period_s=args.control_period_s,
        capacity_probe_events=args.capacity_probe,
    )
    if args.timeline_out and timeline is not None:
        timeline.dump(args.timeline_out)
        report["timeline_path"] = args.timeline_out
    print(json.dumps(report))
    if args.metrics_out:
        Path(args.metrics_out).write_text(json.dumps(report, indent=2))
    if not args.quiet:
        print(
            f"open-loop: {report['offered']} offered @ "
            f"{report['offered_eps']} ev/s -> {report['served']} served "
            f"({report['goodput_eps']} ev/s goodput), "
            f"{report['shed']} shed, {report['events_coalesced']} "
            f"coalesced, p99 {report['p99_ms']} ms / p99.9 "
            f"{report['p999_ms']} ms",
            file=sys.stderr,
        )
    if args.check:
        problems = list(report.get("shed_violations", []))
        if report["shed"] != report["events_shed"]:
            problems.append(
                f"shed accounting: executor saw {report['shed']} "
                f"QueueFull raises but events_shed={report['events_shed']}"
            )
        if report["invalid"]:
            problems.append(
                f"{report['invalid']} served placement(s) structurally "
                "invalid"
            )
        if report["failed"]:
            problems.append(f"{report['failed']} tick(s) failed under load")
        if args.expect_sheds and report["shed"] == 0:
            problems.append(
                "expected sheds but nothing was shed (the smoke did not "
                "overload the admission gate)"
            )
        if args.expect_coalesced and report["events_coalesced"] == 0:
            problems.append("expected coalescing but nothing was folded")
        if args.expect_no_sheds and report["shed"]:
            problems.append(
                f"expected zero sheds but {report['shed']} event(s) were "
                "shed (the flood should have folded, not overflowed)"
            )
        if args.expect_combined:
            comb = report.get("combine") or {}
            if not comb.get("instances"):
                problems.append(
                    "expected combined batches but no lane was ever "
                    "solved in one"
                )
            if comb.get("combine_fallback"):
                problems.append(
                    f"{comb['combine_fallback']} combined tick(s) fell "
                    "back to a per-shard solve"
                )
            if comb.get("errors"):
                problems.append(
                    f"{comb['errors']} batched dispatch(es) raised"
                )
            warm_events = (report.get("compile") or {}).get(
                "warm_phase_events"
            )
            if warm_events is None:
                problems.append(
                    "no warm-phase compile accounting in the report "
                    "(compile ledger not enabled?)"
                )
            elif warm_events:
                problems.append(
                    f"{warm_events} compile event(s) in the measured "
                    "phase — the committed bucket policy must make "
                    "combined traffic compile NOTHING after warm_combine "
                    f"(entries: {report['compile']['warm_phase_entries']})"
                )
        if autoscale is not None:
            ctl = report.get("control") or {}
            problems.extend(ctl.get("violations", []))
            if args.expect_scale is not None:
                peak = max(
                    (
                        a["target_workers"]
                        for a in ctl.get("actions", [])
                        if a.get("kind") == "scale_out"
                    ),
                    default=args.workers,
                )
                if peak < args.expect_scale:
                    problems.append(
                        f"expected the controller to scale out to >= "
                        f"{args.expect_scale} workers but it peaked at "
                        f"{peak}"
                    )
        if args.expect_alert:
            slo_rep = report.get("slo") or {}
            events = slo_rep.get("events", [])
            # Record-by-record reconciliation, shed-contract style: the
            # engine's transition list, the counters and the flight
            # recorder's slo ring must all tell the same story.
            flight_alerts = [
                r for r in flight.snapshot("slo")
                if r.get("kind") == "slo_alert"
            ]
            if len(flight_alerts) != len(events):
                problems.append(
                    f"alert accounting: {len(events)} engine transition(s) "
                    f"but {len(flight_alerts)} flight record(s)"
                )
            opened = sum(1 for e in events if e["state"] == "open")
            closed = sum(1 for e in events if e["state"] == "close")
            if opened != slo_rep.get("alerts_opened") or closed != slo_rep.get(
                "alerts_closed"
            ):
                problems.append(
                    f"alert accounting: events say {opened} open/{closed} "
                    f"close but counters say "
                    f"{slo_rep.get('alerts_opened')}/"
                    f"{slo_rep.get('alerts_closed')}"
                )
            for sev in args.expect_alert:
                sev_open = [
                    e for e in events
                    if e["severity"] == sev and e["state"] == "open"
                ]
                sev_close = [
                    e for e in events
                    if e["severity"] == sev and e["state"] == "close"
                ]
                if not sev_open:
                    problems.append(
                        f"expected a {sev!r} alert to open during the "
                        "flood but none did"
                    )
                elif len(sev_close) < len(sev_open):
                    problems.append(
                        f"{sev!r} alert opened but never closed (recovery "
                        "window too short, or the burn never cleared)"
                    )
        if problems:
            for pmsg in problems:
                print(f"overload violation: {pmsg}", file=sys.stderr)
            return 1
        print(
            f"overload OK: {report['shed']} shed (reconciled record-by-"
            f"record), {report['events_coalesced']} coalesced, "
            f"{report['served']} served valid", file=sys.stderr,
        )
    return 0


def build_slo_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver slo",
        description="evaluate SLOs: replay a dumped metrics timeline "
        "(serve --timeline-dir / overload --timeline-out) against a "
        "spec file offline — a pure function of (timeline, spec), "
        "byte-deterministic — or fetch a live gateway's /slo status, "
        "or trend-check the committed bench history; see README "
        "'SLOs & alerting'",
    )
    p.add_argument(
        "--spec", default=None, metavar="SPEC.json",
        help="SLO spec file (obs.slo.SLOConfig JSON); required with "
        "--timeline",
    )
    p.add_argument(
        "--timeline", default=None, metavar="FILE",
        help="dumped timeline JSONL to replay the alert evaluation over "
        "(offline, deterministic)",
    )
    p.add_argument(
        "--url", default=None, metavar="http://HOST:PORT",
        help="fetch a live gateway's GET /slo instead (serve --listen "
        "--slo)",
    )
    p.add_argument(
        "--step-s", type=float, default=0.05,
        help="offline replay evaluation step (seconds of timeline time)",
    )
    p.add_argument(
        "--expect", default=None, metavar="FILE",
        help="expected alert sequence JSON ({bucket_s, events: [{slo, "
        "severity, state, bucket}]}): the replayed transitions must "
        "match EXACTLY — tier, window set, state and firing-timestamp "
        "bucket (exit 1 on any difference)",
    )
    p.add_argument(
        "--history", default=None, metavar="BENCH_HISTORY.jsonl",
        help="evaluate trend rules over the committed bench history "
        "(tools/bench_history.py appends one line per `make bench`): "
        "the newest round's headline keys may not regress past the "
        "prior-median tolerance",
    )
    p.add_argument(
        "--check", action="store_true",
        help="exit 1 on any violation: --expect mismatch, alert-counter "
        "vs transition-list drift, or a --history trend regression",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the evaluation as one JSON object instead of tables",
    )
    p.add_argument("--quiet", action="store_true", help="no tables")
    return p


def _slo_render_tables(status: dict, events: list) -> None:
    print(f"{'slo':<28s} {'sev':<6s} {'window':>9s} {'burn':>10s} "
          f"{'threshold':>9s} {'firing':>6s}")
    for slo in status.get("slos", []):
        for rule in slo.get("alerts", []):
            for w in rule.get("windows", []):
                burn = w.get("burn")
                print(
                    f"{slo['name']:<28s} {rule['severity']:<6s} "
                    f"{w['window_s']:>8.6g}s "
                    f"{'-' if burn is None else format(burn, '>10.3f')} "
                    f"{w['threshold']:>9.3g} "
                    f"{str(rule['firing']):>6s}"
                )
    if events:
        print(f"\n{'t':>10s} {'slo':<28s} {'sev':<6s} {'state':<6s} burn")
        for e in events:
            print(
                f"{e['t']:>10.3f} {e['slo']:<28s} {e['severity']:<6s} "
                f"{e['state']:<6s} {e['burn']}"
            )
    else:
        print("\nno alert transitions")


def slo_main(argv=None) -> int:
    """``solver slo``: offline timeline replay / live status / trends."""
    args = build_slo_parser().parse_args(argv)

    # Pure JSON-in, JSON-out: no profiles, no backend, no axon guard.
    violations: list = []
    payload: dict = {}

    if args.url:
        import urllib.request

        url = args.url.rstrip("/") + "/slo"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                status = json.loads(resp.read())
        except OSError as e:
            print(f"error: cannot fetch {url}: {e}", file=sys.stderr)
            return 2
        payload["live"] = status
        if not args.quiet and not args.json:
            _slo_render_tables(status, status.get("events", []))
        if status.get("alerts_open"):
            violations.append(
                f"{status['alerts_open']} alert(s) currently open on "
                f"{url}"
            )

    if args.timeline:
        if not args.spec:
            print("error: --timeline needs --spec", file=sys.stderr)
            return 2
        from ..obs import FlightRecorder, SLOConfig, SLOEngine, Timeline
        from ..sched.metrics import SchedulerMetrics

        try:
            config = SLOConfig.from_json(args.spec)
            timeline = Timeline.load(args.timeline)
        except (OSError, ValueError) as e:
            print(f"error: cannot load inputs: {e}", file=sys.stderr)
            return 2
        # The offline engine gets its own sink + flight ring so the
        # counter/record/transition reconciliation (the live contract)
        # is checkable on a replay too.
        metrics = SchedulerMetrics()
        flight = FlightRecorder(capacity=4096)
        engine = SLOEngine(config, timeline, metrics=metrics, flight=flight)
        events = engine.replay(step_s=args.step_s)
        status = engine.status()
        payload["replay"] = {
            "events": events,
            "alerts_open": status["alerts_open"],
            "step_s": args.step_s,
        }
        counters = metrics.snapshot()["counters"]
        opened = sum(1 for e in events if e["state"] == "open")
        closed = sum(1 for e in events if e["state"] == "close")
        if counters.get("slo_alert_opened", 0) != opened or counters.get(
            "slo_alert_closed", 0
        ) != closed:
            violations.append(
                "alert accounting: transitions "
                f"({opened} open/{closed} close) disagree with counters "
                f"({counters.get('slo_alert_opened', 0)}/"
                f"{counters.get('slo_alert_closed', 0)})"
            )
        flight_alerts = [
            r for r in flight.snapshot("slo") if r.get("kind") == "slo_alert"
        ]
        if len(flight_alerts) != len(events):
            violations.append(
                f"alert accounting: {len(events)} transition(s) but "
                f"{len(flight_alerts)} flight record(s)"
            )
        if args.expect:
            try:
                expect = json.loads(Path(args.expect).read_text())
            except (OSError, ValueError) as e:
                print(f"error: cannot load --expect: {e}", file=sys.stderr)
                return 2
            bucket_s = float(expect.get("bucket_s", 1.0))
            bounds = timeline.bounds()
            t0 = bounds[0] if bounds else 0.0
            got = [
                {
                    "slo": e["slo"],
                    "severity": e["severity"],
                    "state": e["state"],
                    "bucket": int((e["t"] - t0) / bucket_s),
                }
                for e in events
            ]
            if got != expect.get("events"):
                violations.append(
                    "alert sequence mismatch:\n  expected "
                    f"{json.dumps(expect.get('events'))}\n  got      "
                    f"{json.dumps(got)}"
                )
            payload["replay"]["expected_match"] = got == expect.get("events")
        if not args.quiet and not args.json:
            _slo_render_tables(status, events)

    if args.history:
        from ..obs.slo import evaluate_history

        try:
            rows = [
                json.loads(ln)
                for ln in Path(args.history).read_text().splitlines()
                if ln.strip()
            ]
        except (OSError, ValueError) as e:
            print(f"error: cannot load --history: {e}", file=sys.stderr)
            return 2
        table, trend_violations = evaluate_history(rows)
        payload["history"] = {"rows": len(rows), "table": table}
        violations.extend(trend_violations)
        if not args.quiet and not args.json:
            print(
                f"\nbench history ({len(rows)} round(s)): "
                f"{'key':<36s} {'median':>12s} {'latest':>12s} {'delta':>8s}"
            )
            for row in table:
                med = row["median"]
                lat = row["latest"]
                chg = row["change"]
                print(
                    f"{'':41s}{row['key']:<36s} "
                    f"{'-' if med is None else format(med, '>12.4g')} "
                    f"{'-' if lat is None else format(lat, '>12.4g')} "
                    f"{'-' if chg is None else format(chg, '>+8.1%')}"
                )

    if not (args.url or args.timeline or args.history):
        print(
            "error: nothing to evaluate (need --timeline, --url or "
            "--history)",
            file=sys.stderr,
        )
        return 2

    if args.json:
        payload["violations"] = violations
        print(json.dumps(payload))
    if violations:
        for v in violations:
            print(f"slo violation: {v}", file=sys.stderr)
        if args.check:
            return 1
    elif args.check and not args.quiet:
        print("slo check OK", file=sys.stderr)
    return 0


def build_autoscale_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver autoscale",
        description="replay a dumped metrics timeline through the "
        "closed-loop controller OFFLINE: a pure function of (timeline, "
        "policy, slo spec, step) — same inputs, same action sequence, "
        "byte for byte; the proof that the live loop's decisions are "
        "reproducible from its recorded signals (README 'Closed-loop "
        "autoscaling & process workers')",
    )
    p.add_argument(
        "--timeline", required=True, metavar="FILE",
        help="dumped timeline JSONL (serve --timeline-dir / overload "
        "--timeline-out)",
    )
    p.add_argument(
        "--policy", required=True, metavar="POLICY.json",
        help="control policy file (control.ControlPolicy JSON; "
        "tests/traces/control_policy.json is the committed smoke fixture)",
    )
    p.add_argument(
        "--spec", default=None, metavar="SLO.json",
        help="SLO spec evaluated alongside the replay so page/warn "
        "alerts feed the policy's alert-driven levers (omitting it "
        "leaves alerts_open at 0 for every step)",
    )
    p.add_argument(
        "--step-s", type=float, default=0.5,
        help="replay decision step (seconds of timeline time)",
    )
    p.add_argument(
        "--capacity-eps", type=float, default=None,
        help="max-sustainable events/sec pin for the headroom signal "
        "(the live loop measures this with a closed-loop probe; offline "
        "it must be pinned or the headroom levers stay dark)",
    )
    p.add_argument(
        "--workers", type=int, default=None,
        help="starting worker count (default: inferred from the "
        "timeline's queue_depth.w* series)",
    )
    p.add_argument(
        "--expect", default=None, metavar="FILE",
        help="expected action JSONL (actions_to_jsonl format, one "
        "key-sorted object per line): the replayed sequence must match "
        "BYTE FOR BYTE",
    )
    p.add_argument(
        "--out", default=None, metavar="FILE",
        help="write the replayed action JSONL here (the fixture "
        "regeneration path)",
    )
    p.add_argument(
        "--check", action="store_true",
        help="exit 1 on any violation: --expect mismatch, or a "
        "determinism failure (the replay runs TWICE from fresh "
        "controllers; the two byte streams must be identical)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the evaluation as one JSON object instead of a table",
    )
    p.add_argument("--quiet", action="store_true", help="no table")
    return p


def autoscale_main(argv=None) -> int:
    """``solver autoscale``: offline controller replay, byte-deterministic."""
    args = build_autoscale_parser().parse_args(argv)

    # Pure JSON-in, JSON-out: no profiles, no backend, no axon guard.
    from ..control import Controller, ControlPolicy, actions_to_jsonl
    from ..obs import Timeline

    try:
        timeline = Timeline.load(args.timeline)
        policy = ControlPolicy.from_json(args.policy)
    except (OSError, ValueError) as e:
        print(f"error: cannot load inputs: {e}", file=sys.stderr)
        return 2
    slo_config = None
    if args.spec:
        from ..obs import SLOConfig

        try:
            slo_config = SLOConfig.from_json(args.spec)
        except (OSError, ValueError) as e:
            print(f"error: cannot load --spec: {e}", file=sys.stderr)
            return 2

    def _run() -> str:
        return actions_to_jsonl(
            Controller.replay(
                timeline,
                policy,
                slo_config=slo_config,
                step_s=args.step_s,
                capacity_eps=args.capacity_eps,
                n_workers=args.workers,
            )
        )

    violations: list = []
    got = _run()
    if args.check and _run() != got:
        # A pure function cannot disagree with itself: any drift means a
        # clock or ambient-state leak into the decision path.
        violations.append(
            "determinism: two replays of the same (timeline, policy) "
            "produced different action streams"
        )
    if args.expect:
        try:
            expect = Path(args.expect).read_text()
        except OSError as e:
            print(f"error: cannot load --expect: {e}", file=sys.stderr)
            return 2
        if got != expect:
            violations.append(
                "action sequence mismatch:\n  expected "
                f"{expect!r}\n  got      {got!r}"
            )
    if args.out:
        Path(args.out).write_text(got)
    actions = [json.loads(ln) for ln in got.splitlines()]
    if args.json:
        print(json.dumps({
            "actions": actions,
            "step_s": args.step_s,
            "policy": policy.model_dump(),
            "violations": violations,
        }))
    elif not args.quiet:
        if actions:
            print(f"{'t':>10s} {'action':<12s} {'workers':>7s} reason")
            for a in actions:
                tw = a.get("target_workers")
                print(
                    f"{a['t']:>10.3f} {a['kind']:<12s} "
                    f"{'-' if tw is None else tw:>7} {a['reason']}"
                )
        else:
            print("no actions")
    if violations:
        for v in violations:
            print(f"autoscale violation: {v}", file=sys.stderr)
        if args.check:
            return 1
    elif args.check and not args.quiet:
        print(
            f"autoscale check OK: {len(actions)} action(s), "
            "byte-deterministic", file=sys.stderr,
        )
    return 0


def build_spans_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver spans",
        description="convert a span JSONL (serve --trace-spans-dir) into "
        "Chrome trace-event JSON loadable in Perfetto (ui.perfetto.dev) or "
        "chrome://tracing: one track per thread, spans as complete events, "
        "queue waits as flow arrows from the enqueuing thread to the "
        "worker that picked the tick up",
    )
    p.add_argument(
        "input",
        help="span JSONL file, or the --trace-spans-dir directory holding "
        "spans.jsonl",
    )
    p.add_argument(
        "--out",
        default=None,
        metavar="FILE",
        help="Chrome trace JSON output path (default: <input>.chrome.json)",
    )
    p.add_argument(
        "--top",
        type=int,
        default=3,
        help="also print the N slowest spans (0 disables)",
    )
    p.add_argument(
        "--stats",
        action="store_true",
        help="aggregate instead of convert: one row per span NAME "
        "(count, p50/p99/max duration, top slowest with trace ids) — "
        "the CI-log-readable view Perfetto cannot give; skips the "
        "Chrome JSON unless --out is also given",
    )
    p.add_argument("--quiet", action="store_true", help="no summary output")
    return p


def spans_main(argv=None) -> int:
    """``solver spans``: span JSONL -> Chrome trace-event JSON."""
    args = build_spans_parser().parse_args(argv)

    # Pure JSON-to-JSON: no profiles, no backend, no axon guard needed.
    from ..obs import read_spans, span_stats, spans_to_chrome, top_spans

    src = Path(args.input)
    if src.is_dir():
        src = src / "spans.jsonl"
    if not src.is_file():
        print(f"error: no span JSONL at {src}", file=sys.stderr)
        return 2
    try:
        spans = read_spans(src)
    except (OSError, ValueError) as e:  # JSONDecodeError is a ValueError
        print(f"error: cannot parse {src}: {e}", file=sys.stderr)
        return 2
    if not spans:
        print(f"error: {src} holds no spans", file=sys.stderr)
        return 1
    if args.stats:
        rows = span_stats(spans, top=max(0, args.top))
        print(
            f"{'span':<22s} {'count':>6s} {'total ms':>10s} "
            f"{'p50 ms':>9s} {'p99 ms':>9s} {'max ms':>9s}  slowest (trace ids)"
        )
        for r in rows:
            slow = ", ".join(
                f"{s['dur_ms']:.2f}ms@{s['trace_id']}" for s in r["slowest"]
            )
            print(
                f"{r['name']:<22s} {r['count']:>6d} {r['total_ms']:>10.3f} "
                f"{r['p50_ms']:>9.3f} {r['p99_ms']:>9.3f} "
                f"{r['max_ms']:>9.3f}  {slow}"
            )
        if not args.out:
            return 0
    chrome = spans_to_chrome(spans)
    out = Path(args.out) if args.out else src.with_suffix(".chrome.json")
    out.write_text(json.dumps(chrome))
    if not args.quiet:
        traces = len({s["trace_id"] for s in spans})
        print(
            f"wrote {out}: {len(chrome['traceEvents'])} trace events from "
            f"{len(spans)} spans across {traces} traces (load in "
            "ui.perfetto.dev or chrome://tracing)"
        )
        if args.top > 0:
            print(f"top {args.top} slowest spans:")
            for s in top_spans(spans, args.top):
                attrs = s.get("attrs") or {}
                extra = "".join(
                    f" {k}={attrs[k]}"
                    for k in ("fleet", "kind", "mode", "lp_backend")
                    if k in attrs
                )
                print(
                    f"  {s['dur_ms']:10.3f} ms  {s['name']:<20s} "
                    f"thread={s.get('thread', '?')}{extra}"
                )
    return 0


def build_diagnose_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver diagnose",
        description="solver-interior convergence report: run one HALDA "
        "solve with in-jit telemetry on (per-branch-and-bound-round "
        "search log + the root LP relaxations' per-chunk residual/"
        "restart traces), render the per-round tables, and optionally "
        "export the report as JSONL (reload with --load). The solve "
        "itself is the normal certified solve — tracing rides the same "
        "device program and only appends to its output",
    )
    p.add_argument(
        "--profile", "-p", default=None,
        help="folder containing model_profile.json and per-device JSONs "
        "(required unless --load)",
    )
    p.add_argument(
        "--synthetic-fleet", type=int, default=0, metavar="M",
        help="solve M synthetic devices instead of the folder's device "
        "JSONs (the 16-device north star: --synthetic-fleet 16 "
        "--fleet-seed 123)",
    )
    p.add_argument("--fleet-seed", type=int, default=0)
    p.add_argument("--mip-gap", type=float, default=1e-3)
    p.add_argument("--kv-bits", default="4bit")
    p.add_argument(
        "--k-candidates", default=None,
        help="comma-separated k values (default: all proper factors of L)",
    )
    p.add_argument(
        "--moe", choices=["auto", "on", "off"], default="auto",
        help="expert+layer co-assignment mode (see `solver --moe`)",
    )
    p.add_argument(
        "--lp-backend", choices=["ipm", "pdhg", "auto"], default="auto",
        help="LP relaxation engine to diagnose (the report's LP traces "
        "carry the engine's own gauges: Mehrotra complementarity for "
        "ipm, normalized duality gap + Halpern restart cadence for pdhg)",
    )
    p.add_argument("--pdhg-iters", type=int, default=None)
    p.add_argument("--pdhg-restart-tol", type=float, default=None)
    p.add_argument("--max-rounds", type=int, default=None)
    p.add_argument("--beam", type=int, default=None)
    p.add_argument("--ipm-iters", type=int, default=None)
    p.add_argument("--ipm-warm-iters", type=int, default=None)
    p.add_argument("--node-cap", type=int, default=None)
    p.add_argument(
        "--out", default=None, metavar="FILE",
        help="also export the report as JSONL (one 'search' header line, "
        "one 'round' line per round, one 'lp' line per root trace)",
    )
    p.add_argument(
        "--load", default=None, metavar="FILE",
        help="render a previously exported JSONL report instead of "
        "solving (no backend needed)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the full report as one JSON object (SearchTrace "
        "fields + digest + solver timings) instead of tables",
    )
    return p


def diagnose_main(argv=None) -> int:
    """``solver diagnose``: one traced solve -> convergence report."""
    args = build_diagnose_parser().parse_args(argv)

    from ..obs.convergence import (
        build_search_trace,
        search_trace_from_jsonl,
        search_trace_to_jsonl,
    )

    tm: dict = {}
    if args.load:
        try:
            trace = search_trace_from_jsonl(Path(args.load).read_text())
        except (OSError, ValueError, TypeError) as e:
            print(f"error: cannot load {args.load}: {e}", file=sys.stderr)
            return 2
    else:
        if not args.profile:
            print(
                "error: --profile is required unless --load", file=sys.stderr
            )
            return 2

        from ..axon_guard import force_cpu_if_env_requested

        force_cpu_if_env_requested()

        from ..common import load_from_profile_folder, load_model_profile
        from ..solver import halda_solve
        from ..utils import make_synthetic_fleet

        folder = Path(args.profile)
        if not folder.is_dir():
            print(f"error: {folder} is not a directory", file=sys.stderr)
            return 2
        if args.synthetic_fleet > 0:
            model = load_model_profile(folder / "model_profile.json")
            devices = make_synthetic_fleet(
                args.synthetic_fleet, seed=args.fleet_seed
            )
        else:
            devices, model = load_from_profile_folder(folder)

        k_candidates = None
        if args.k_candidates:
            k_candidates = [
                int(x) for x in args.k_candidates.split(",") if x.strip()
            ]

        conv: dict = {}
        try:
            halda_solve(
                devices,
                model,
                k_candidates=k_candidates,
                mip_gap=args.mip_gap,
                kv_bits=args.kv_bits,
                backend="jax",
                moe={"auto": None, "on": True, "off": False}[args.moe],
                max_rounds=args.max_rounds,
                beam=args.beam,
                ipm_iters=args.ipm_iters,
                ipm_warm_iters=args.ipm_warm_iters,
                node_cap=args.node_cap,
                lp_backend=args.lp_backend,
                pdhg_iters=args.pdhg_iters,
                pdhg_restart_tol=args.pdhg_restart_tol,
                timings=tm,
                convergence=conv,
            )
        except (ValueError, RuntimeError, NotImplementedError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        trace = build_search_trace(conv)

    if not trace.rounds:
        print(
            "error: empty convergence report (no branch-and-bound round "
            "executed — was the sweep structurally infeasible?)",
            file=sys.stderr,
        )
        return 1

    if args.json:
        payload = trace.model_dump()
        payload["digest"] = trace.digest()
        if tm:
            payload["timings"] = {
                k: v for k, v in tm.items()
                if isinstance(v, (int, float, str, bool))
            }
        print(json.dumps(payload))
    else:
        print(trace.render_text())
        if tm.get("solve_ms") is not None:
            print(
                f"solve: {tm.get('solve_ms', 0.0):.1f} ms on-device "
                f"(pack {tm.get('pack_ms', 0.0):.1f} ms, upload "
                f"{tm.get('upload_ms', 0.0):.1f} ms)"
                + (" [escalated]" if tm.get("escalated") else "")
            )
    if args.out:
        Path(args.out).write_text(search_trace_to_jsonl(trace))
        if not args.json:
            print(f"wrote {args.out}")
    return 0


def build_compiles_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver compiles",
        description="render the XLA compile ledger (obs.compile_ledger): "
        "per-entry-point compile/dispatch table, cause histogram (cold / "
        "cache-hit / static-arg-flip / shape-bucket-change / recompile), "
        "persistent-cache hit rate and the top recompile offenders — from "
        "a live run (--trace, replayed through `solver serve` with the "
        "ledger on) or a dumped JSONL (--load). Rendering a dump is a "
        "pure function: the same dump produces byte-identical reports on "
        "every replay",
    )
    p.add_argument(
        "--load", default=None, metavar="FILE",
        help="render a ledger JSONL previously dumped by "
        "`serve --compile-ledger-out` (or --out below); no backend needed",
    )
    p.add_argument(
        "--trace", default=None,
        help="live mode: replay this churn trace (single- or multi-fleet) "
        "with the ledger enabled and render the resulting ledger",
    )
    p.add_argument(
        "--profile", "-p", default=None,
        help="profile folder (required with --trace)",
    )
    p.add_argument("--synthetic-fleet", type=int, default=0, metavar="M")
    p.add_argument("--fleet-seed", type=int, default=0)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--mip-gap", type=float, default=1e-3)
    p.add_argument("--k-candidates", default=None)
    p.add_argument(
        "--lp-backend", choices=["ipm", "pdhg", "auto"], default="auto",
        help="LP engine pin for the live replay — flip it between two "
        "runs and the ledger attributes the recompile to the static-arg "
        "flip (walkthrough step 16)",
    )
    p.add_argument(
        "--compile-warm-events", type=int, default=2, metavar="N",
        help="warm-boundary events per fleet for the live replay (see "
        "`solver serve --compile-warm-events`)",
    )
    p.add_argument(
        "--out", default=None, metavar="FILE",
        help="also save the live run's ledger JSONL here",
    )
    p.add_argument("--top", type=int, default=5, help="top-N offenders/storms")
    p.add_argument(
        "--json", action="store_true",
        help="print the ledger summary as one JSON object instead of text",
    )
    p.add_argument(
        "--check", action="store_true",
        help="exit 1 unless the ledger is clean: every compile attributed "
        "to a REGISTERED entry point, no exact-signature recompiles, and "
        "the JSONL round-trips byte-stably (the smoke-compile contract)",
    )
    return p


def compiles_main(argv=None) -> int:
    """``solver compiles``: render/check the XLA compile ledger."""
    args = build_compiles_parser().parse_args(argv)

    from ..obs.compile_ledger import (
        ledger_from_jsonl,
        ledger_to_jsonl,
        render_report,
    )

    if bool(args.load) == bool(args.trace):
        print(
            "error: exactly one of --load or --trace is required",
            file=sys.stderr,
        )
        return 2

    if args.load:
        try:
            text = Path(args.load).read_text(encoding="utf-8")
            dump = ledger_from_jsonl(text)
        except (OSError, ValueError) as e:
            print(f"error: cannot load {args.load}: {e}", file=sys.stderr)
            return 2
    else:
        if not args.profile:
            print(
                "error: --trace needs --profile", file=sys.stderr
            )
            return 2
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            out_path = Path(args.out) if args.out else Path(tmp) / "ledger.jsonl"
            serve_argv = [
                "--trace", args.trace,
                "--profile", args.profile,
                "--quiet",
                "--workers", str(args.workers),
                "--mip-gap", str(args.mip_gap),
                "--lp-backend", args.lp_backend,
                "--compile-warm-events", str(args.compile_warm_events),
                "--compile-ledger-out", str(out_path),
            ]
            if args.synthetic_fleet:
                serve_argv += [
                    "--synthetic-fleet", str(args.synthetic_fleet),
                    "--fleet-seed", str(args.fleet_seed),
                ]
            if args.k_candidates:
                serve_argv += ["--k-candidates", args.k_candidates]
            # The delegated serve run's summary goes to stderr: stdout
            # must carry exactly the report (or the --json object), so
            # piping `solver compiles` stays machine-readable.
            import contextlib
            import io

            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = serve_main(serve_argv)
            if buf.getvalue():
                print(buf.getvalue(), end="", file=sys.stderr)
            if rc != 0:
                return rc
            text = out_path.read_text(encoding="utf-8")
            dump = ledger_from_jsonl(text)

    if args.check:
        failures = []
        registry = set(dump["header"].get("registry", []))
        for ev in dump["events"]:
            if ev["entry"] not in registry:
                failures.append(
                    f"compile of unregistered entry {ev['entry']!r} "
                    f"(seq {ev['seq']}) — an executable DLP020 missed"
                )
            if ev["cause"] == "recompile":
                failures.append(
                    f"exact-signature recompile of {ev['entry']} "
                    f"(seq {ev['seq']}, static=[{ev['static']}])"
                )
        if ledger_to_jsonl(dump) != text:
            failures.append("ledger JSONL does not round-trip byte-stably")
        if failures:
            for f in failures:
                print(f"compile-ledger check FAILED: {f}", file=sys.stderr)
            return 1

    if args.json:
        print(json.dumps(dump["header"].get("summary", {}), sort_keys=True))
    else:
        print(render_report(dump, top=args.top), end="")
    if args.check:
        n = len(dump["events"])
        print(
            f"compile-ledger check OK: {n} compile event(s), all "
            "registered, no exact-signature recompiles, dump byte-stable"
        )
    return 0


def build_memory_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solver memory",
        description="render the memory ledger (obs.memory): per-entry "
        "static memory models (AOT XLA memory_analysis temp/argument/"
        "output bytes + FLOPs per dispatch), live-array/RSS watermarks, "
        "and the warm-path leak-gate verdict — from a live run (--trace, "
        "replayed through `solver serve` with the ledger on) or a dumped "
        "JSONL (--load). Rendering a dump is a pure function: the same "
        "dump produces byte-identical reports on every replay",
    )
    p.add_argument(
        "--load", default=None, metavar="FILE",
        help="render a ledger JSONL previously dumped by "
        "`serve --memory-out` (or --out below); no backend needed",
    )
    p.add_argument(
        "--trace", default=None,
        help="live mode: replay this churn trace (single- or multi-fleet) "
        "with the memory ledger enabled and render the resulting ledger",
    )
    p.add_argument(
        "--profile", "-p", default=None,
        help="profile folder (required with --trace)",
    )
    p.add_argument("--synthetic-fleet", type=int, default=0, metavar="M")
    p.add_argument("--fleet-seed", type=int, default=0)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--mip-gap", type=float, default=1e-3)
    p.add_argument("--k-candidates", default=None)
    p.add_argument(
        "--lp-backend", choices=["ipm", "pdhg", "auto"], default="auto",
        help="LP engine pin for the live replay (each engine's entry "
        "points get their own static model)",
    )
    p.add_argument(
        "--warm-events", type=int, default=2, metavar="N",
        help="leak-gate baseline: marked once every replayed fleet has "
        "handled N events (see `solver serve --compile-warm-events`)",
    )
    p.add_argument(
        "--memory-budget-mb", type=float, default=None,
        help="headroom budget for the live replay (default: MemTotal)",
    )
    p.add_argument(
        "--out", default=None, metavar="FILE",
        help="also save the live run's ledger JSONL here",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the ledger summary as one JSON object instead of text",
    )
    p.add_argument(
        "--check", action="store_true",
        help="exit 1 unless the ledger is clean: the leak gate was "
        "marked AND live-array bytes stayed flat across the warm phase, "
        "no watermark sample failed, and the JSONL round-trips "
        "byte-stably (the smoke-memory contract)",
    )
    return p


def memory_main(argv=None) -> int:
    """``solver memory``: render/check the memory ledger."""
    args = build_memory_parser().parse_args(argv)

    from ..obs.memory import (
        memory_from_jsonl,
        memory_to_jsonl,
        render_report,
    )

    if bool(args.load) == bool(args.trace):
        print(
            "error: exactly one of --load or --trace is required",
            file=sys.stderr,
        )
        return 2

    if args.load:
        try:
            text = Path(args.load).read_text(encoding="utf-8")
            dump = memory_from_jsonl(text)
        except (OSError, ValueError) as e:
            print(f"error: cannot load {args.load}: {e}", file=sys.stderr)
            return 2
    else:
        if not args.profile:
            print("error: --trace needs --profile", file=sys.stderr)
            return 2
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            out_path = (
                Path(args.out) if args.out else Path(tmp) / "memory.jsonl"
            )
            serve_argv = [
                "--trace", args.trace,
                "--profile", args.profile,
                "--quiet",
                "--workers", str(args.workers),
                "--mip-gap", str(args.mip_gap),
                "--lp-backend", args.lp_backend,
                "--compile-warm-events", str(args.warm_events),
                "--memory-out", str(out_path),
            ]
            if args.memory_budget_mb is not None:
                serve_argv += [
                    "--memory-budget-mb", str(args.memory_budget_mb),
                ]
            if args.synthetic_fleet:
                serve_argv += [
                    "--synthetic-fleet", str(args.synthetic_fleet),
                    "--fleet-seed", str(args.fleet_seed),
                ]
            if args.k_candidates:
                serve_argv += ["--k-candidates", args.k_candidates]
            # The delegated serve run's summary goes to stderr: stdout
            # must carry exactly the report (or the --json object), the
            # `solver compiles` convention.
            import contextlib
            import io

            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = serve_main(serve_argv)
            if buf.getvalue():
                print(buf.getvalue(), end="", file=sys.stderr)
            if rc != 0:
                return rc
            text = out_path.read_text(encoding="utf-8")
            dump = memory_from_jsonl(text)

    if args.check:
        failures = []
        summary = dump["header"].get("summary", {})
        leak = summary.get("leak")
        if leak is None:
            failures.append(
                "leak gate never marked (the replay ended before the "
                "warm boundary — fewer events than --warm-events?)"
            )
        elif not leak.get("flat"):
            failures.append(
                f"warm serving GREW live-array bytes: "
                f"{leak['baseline_bytes']} -> {leak['last_bytes']} "
                f"({leak['growth_bytes']:+d} B) — drift/spec ticks must "
                "allocate nothing persistent"
            )
        marks = summary.get("watermarks", {})
        if marks.get("sample_errors", 0):
            failures.append(
                f"{marks['sample_errors']} watermark sample(s) failed"
            )
        if memory_to_jsonl(dump) != text:
            failures.append("memory JSONL does not round-trip byte-stably")
        if failures:
            for f in failures:
                print(f"memory-ledger check FAILED: {f}", file=sys.stderr)
            return 1

    if args.json:
        print(json.dumps(dump["header"].get("summary", {}), sort_keys=True))
    else:
        print(render_report(dump), end="")
    if args.check:
        summary = dump["header"].get("summary", {})
        analyzed = sum(
            1
            for e in summary.get("entries", {}).values()
            if e.get("memory")
        )
        print(
            f"memory-ledger check OK: {analyzed} entry model(s), warm "
            "phase flat, dump byte-stable"
        )
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "compiles":
        return compiles_main(argv[1:])
    if argv and argv[0] == "memory":
        return memory_main(argv[1:])
    if argv and argv[0] == "serve":
        # Subcommand dispatch; the bare flag form stays the one-shot solver
        # (reference-CLI compatible), so existing invocations are untouched.
        return serve_main(argv[1:])
    if argv and argv[0] == "evaluate":
        return evaluate_main(argv[1:])
    if argv and argv[0] == "spans":
        return spans_main(argv[1:])
    if argv and argv[0] == "diagnose":
        return diagnose_main(argv[1:])
    if argv and argv[0] == "overload":
        return overload_main(argv[1:])
    if argv and argv[0] == "slo":
        return slo_main(argv[1:])
    if argv and argv[0] == "autoscale":
        return autoscale_main(argv[1:])
    args = build_parser().parse_args(argv)

    from ..axon_guard import force_cpu_if_env_requested

    force_cpu_if_env_requested()
    if (args.mesh_shards or 1) > 1:
        # Must land in XLA_FLAGS before the first backend touch — a CPU
        # host exposes one device otherwise and the mesh cannot form.
        from ..utils import shardcompat

        shardcompat.force_host_devices(args.mesh_shards)

    from ..common import load_from_profile_folder
    from ..solver import halda_solve

    folder = Path(args.profile)
    if not folder.is_dir():
        print(f"error: {folder} is not a directory", file=sys.stderr)
        return 2
    devices, model = load_from_profile_folder(folder)

    k_candidates = None
    if args.k_candidates:
        k_candidates = [int(x) for x in args.k_candidates.split(",") if x.strip()]

    expert_loads = None
    if args.expert_loads:
        if args.moe == "off":
            print(
                "error: --expert-loads needs the MoE formulation; it cannot "
                "be combined with --moe off",
                file=sys.stderr,
            )
            return 2
        raw = args.expert_loads
        try:
            if Path(raw).is_file():
                expert_loads = json.loads(Path(raw).read_text())
            else:
                expert_loads = [float(x) for x in raw.split(",") if x.strip()]
            if not isinstance(expert_loads, list) or not all(
                isinstance(x, (int, float)) for x in expert_loads
            ):
                raise ValueError(
                    "expected a JSON array of numbers (one load per expert)"
                )
        except (OSError, TypeError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot parse --expert-loads: {e}", file=sys.stderr)
            return 2

    warm = None
    if args.warm_from:
        from ..solver import HALDAResult

        if args.backend != "jax":
            # The CPU/HiGHS path has no warm-start hook; silently solving
            # cold would contradict what the flag promises.
            print(
                "error: --warm-from needs --backend jax (the cpu backend "
                "has no warm-start hook and would ignore the seed)",
                file=sys.stderr,
            )
            return 2
        try:
            # model_validate: full type validation, extra keys (devices,
            # expert_of_device, ...) ignored — reload stays in sync with
            # whatever --save-solution writes.
            warm = HALDAResult.model_validate(
                json.loads(Path(args.warm_from).read_text())
            )
        except (OSError, TypeError, ValueError) as e:
            # ValidationError and JSONDecodeError are ValueError subclasses.
            print(f"error: cannot load --warm-from: {e}", file=sys.stderr)
            return 2
        if expert_loads is not None:
            # solve_load_aware manages warm-starting across its own
            # iterations; a user-supplied warm seed would be silently
            # dropped there — reject the combination instead.
            print(
                "error: --warm-from cannot be combined with --expert-loads "
                "(the load-aware loop manages its own warm starts)",
                file=sys.stderr,
            )
            return 2

    if args.per_k:
        if expert_loads is not None or warm is not None:
            print(
                "error: --per-k cannot combine with --expert-loads or "
                "--warm-from",
                file=sys.stderr,
            )
            return 2
        from ..solver import halda_solve_per_k

        try:
            per_k = halda_solve_per_k(
                devices,
                model,
                k_candidates=k_candidates,
                mip_gap=args.mip_gap,
                kv_bits=args.kv_bits,
                backend=args.backend,
                moe={"auto": None, "on": True, "off": False}[args.moe],
                max_rounds=args.max_rounds,
                beam=args.beam,
                ipm_iters=args.ipm_iters,
                ipm_warm_iters=args.ipm_warm_iters,
                node_cap=args.node_cap,
                lp_backend=args.lp_backend,
                pdhg_iters=args.pdhg_iters,
                pdhg_restart_tol=args.pdhg_restart_tol,
                mesh_shards=args.mesh_shards,
                pdhg_dtype=args.pdhg_dtype,
                batch_size=args.batch_size,
                time_limit=args.time_limit,
                debug=args.debug,
                plot=args.plot,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if not per_k:
            print("error: no feasible placement for any k", file=sys.stderr)
            return 1
        print(f"{'k':>5s} {'objective':>14s} {'certified':>9s}  assignment")
        for r in sorted(per_k, key=lambda r: r.k):
            w_txt = ",".join(str(w) for w in r.w)
            y_txt = f" y=[{','.join(str(y) for y in r.y)}]" if r.y else ""
            print(
                f"{r.k:5d} {r.obj_value:14.6f} {str(r.certified):>9s}  "
                f"w=[{w_txt}]{y_txt}"
            )
        winner = min(per_k, key=lambda r: r.obj_value)
        print(f"Best: k={winner.k} (objective {winner.obj_value:.6f})")
        if args.save_solution:
            _write_solution(args.save_solution, winner, devices)
        return 0

    mapping = None
    realized = None
    try:
        if expert_loads is not None:
            from ..solver.routing import solve_load_aware

            result, mapping, realized = solve_load_aware(
                devices,
                model,
                expert_loads=expert_loads,
                k_candidates=k_candidates,
                mip_gap=args.mip_gap,
                plot=args.plot,
                debug=args.debug,
                kv_bits=args.kv_bits,
                backend=args.backend,
                time_limit=args.time_limit,
                max_rounds=args.max_rounds,
                beam=args.beam,
                ipm_iters=args.ipm_iters,
                ipm_warm_iters=args.ipm_warm_iters,
                node_cap=args.node_cap,
                lp_backend=args.lp_backend,
                pdhg_iters=args.pdhg_iters,
                pdhg_restart_tol=args.pdhg_restart_tol,
                batch_size=args.batch_size,
            )
        else:
            result = halda_solve(
                devices,
                model,
                k_candidates=k_candidates,
                mip_gap=args.mip_gap,
                plot=args.plot,
                debug=args.debug,
                kv_bits=args.kv_bits,
                backend=args.backend,
                time_limit=args.time_limit,
                moe={"auto": None, "on": True, "off": False}[args.moe],
                warm=warm,
                max_rounds=args.max_rounds,
                beam=args.beam,
                ipm_iters=args.ipm_iters,
                ipm_warm_iters=args.ipm_warm_iters,
                node_cap=args.node_cap,
                lp_backend=args.lp_backend,
                pdhg_iters=args.pdhg_iters,
                pdhg_restart_tol=args.pdhg_restart_tol,
                mesh_shards=args.mesh_shards,
                pdhg_dtype=args.pdhg_dtype,
                batch_size=args.batch_size,
            )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    result.print_solution(devices)
    status = "certified" if result.certified else "NOT certified"
    gap_txt = f"{result.gap:.3g}" if result.gap is not None else "exact (HiGHS)"
    print(f"Optimality: {status} (achieved gap {gap_txt})")
    if mapping is not None:
        print("Expert routing (load-weighted):")
        for dev, ids, share in zip(
            devices, mapping.expert_of_device, mapping.load_share
        ):
            print(
                f"  {dev.name:40s}: {len(ids):3d} experts, "
                f"{share * 100:5.1f}% of routed load"
            )
        # The certificate above covers the linearized instance; this is the
        # end-to-end objective at the mapping's realized loads. None on
        # installs without the JAX backend (the exact pricer lives there).
        if realized is not None:
            print(
                f"Realized objective (at mapped expert loads): {realized:.6f}"
            )

    if args.save_solution:
        _write_solution(
            args.save_solution, result, devices, mapping=mapping,
            realized=realized,
        )
    return 0


def _write_solution(path, result, devices, mapping=None, realized=None):
    payload = {
        "k": result.k,
        "w": result.w,
        "n": result.n,
        "obj_value": result.obj_value,
        "sets": result.sets,
        "devices": [d.name for d in devices],
        "certified": result.certified,
        "gap": result.gap,
    }
    if result.y is not None:
        payload["y"] = result.y
    if result.duals is not None:
        # Persist the Lagrangian root multipliers so --warm-from can
        # re-certify a MoE re-solve without the full root ascent.
        payload["duals"] = result.duals
    if mapping is not None:
        payload["expert_of_device"] = mapping.expert_of_device
        payload["expert_load_share"] = [float(s) for s in mapping.load_share]
        if realized is not None:
            payload["realized_objective"] = realized
    Path(path).write_text(json.dumps(payload, indent=2))
    print(f"Saved solution to {path}")


if __name__ == "__main__":
    raise SystemExit(main())

"""Console entry points: ``profiler`` and ``solver``.

Parity with the reference CLIs (/root/reference/src/cli/), with its dead
flags wired for real (reference cli/solver.py parses --time-limit,
--k-candidates, --kv-bits equivalents but never forwards them; see SURVEY §8).
"""

"""Profiler CLI (reference /root/reference/src/cli/profiler.py).

``profiler model -r <source>`` writes a ModelProfileSplit JSON;
``profiler device -r <source>`` microbenchmarks this host and writes a
DeviceProfile JSON. ``<source>`` is a HF repo id, a local config.json path,
or a directory containing one (offline-first; the reference requires the
Hub).

The reference ships ``--max-batch-exp`` defaulting to 2 while its help text
and API say 6 (cli/profiler.py:67-72 vs api.py:57); here default and help
agree on 6.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="profiler",
        description="Profile this device or a model analytically",
    )
    p.add_argument("kind", choices=["device", "model"])
    p.add_argument(
        "-r",
        "--repo",
        required=True,
        help="HF repo id, path to config.json, or directory containing one",
    )
    p.add_argument("-o", "--output", default=None, help="output JSON path")
    p.add_argument("-s", "--seq-len", type=int, default=512)
    p.add_argument(
        "--max-batch-exp",
        type=int,
        default=6,
        help="device tables cover batches 2^0 .. 2^(N-1) (default 6)",
    )
    p.add_argument(
        "--batches",
        default=None,
        help="comma-separated batch sizes for model profiling (default 1,2,4,8)",
    )
    p.add_argument("--not-head", action="store_true", help="mark device as non-head")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.kind == "model":
        from ..profiler import profile_model

        batches = (
            [int(x) for x in args.batches.split(",") if x.strip()]
            if args.batches
            else None
        )
        profile = profile_model(
            args.repo, batch_sizes=batches, sequence_length=args.seq_len
        )
        out = Path(args.output or "model_profile.json")
    else:
        from ..profiler import profile_device

        profile = profile_device(
            args.repo, max_batch_exp=args.max_batch_exp, is_head=not args.not_head
        )
        out = Path(args.output or f"{profile.name or 'device'}.json")

    out.write_text(profile.model_dump_json(indent=2))
    print(f"Wrote {args.kind} profile to {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

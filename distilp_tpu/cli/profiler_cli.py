"""Profiler CLI (reference /root/reference/src/cli/profiler.py).

``profiler model -r <source>`` writes a ModelProfileSplit JSON;
``profiler device -r <source>`` microbenchmarks this host and writes a
DeviceProfile JSON. ``<source>`` is a HF repo id, a local config.json path,
or a directory containing one (offline-first; the reference requires the
Hub).

The reference ships ``--max-batch-exp`` defaulting to 2 while its help text
and API say 6 (cli/profiler.py:67-72 vs api.py:57); here default and help
agree on 6.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="profiler",
        description="Profile this device or a model analytically",
    )
    p.add_argument("kind", choices=["device", "model"])
    p.add_argument(
        "-r",
        "--repo",
        required=True,
        help="HF repo id, path to config.json, or directory containing one",
    )
    p.add_argument("-o", "--output", default=None, help="output JSON path")
    p.add_argument("-s", "--seq-len", type=int, default=512)
    p.add_argument(
        "--max-batch-exp",
        type=int,
        default=6,
        help="device tables cover batches 2^0 .. 2^(N-1) (default 6)",
    )
    p.add_argument(
        "--batches",
        default=None,
        help="comma-separated batch sizes for model profiling (default 1,2,4,8)",
    )
    p.add_argument("--not-head", action="store_true", help="mark device as non-head")
    p.add_argument(
        "--raw-out",
        default=None,
        help="device profiling only: also write the raw DeviceInfo JSON "
        "(per-measurement timing spreads, HBM capacity provenance, "
        "interconnect probe) that the solver-facing profile drops",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from ..axon_guard import force_cpu_if_env_requested

    force_cpu_if_env_requested()

    if args.kind == "model" and args.raw_out:
        print(
            "error: --raw-out applies to device profiling only "
            "(model profiling is analytic; there is no raw DeviceInfo)",
            file=sys.stderr,
        )
        return 2

    if args.kind == "model":
        from ..profiler import profile_model

        batches = (
            [int(x) for x in args.batches.split(",") if x.strip()]
            if args.batches
            else None
        )
        profile = profile_model(
            args.repo, batch_sizes=batches, sequence_length=args.seq_len
        )
        out = Path(args.output or "model_profile.json")
    else:
        from ..profiler import profile_device

        raw_info = [] if args.raw_out else None
        profile = profile_device(
            args.repo, max_batch_exp=args.max_batch_exp,
            is_head=not args.not_head, raw_info=raw_info,
        )
        out = Path(args.output or f"{profile.name or 'device'}.json")
        if args.raw_out and raw_info:
            Path(args.raw_out).write_text(raw_info[0].model_dump_json(indent=2))
            print(f"Wrote raw DeviceInfo to {args.raw_out}", file=sys.stderr)

    out.write_text(profile.model_dump_json(indent=2))
    print(f"Wrote {args.kind} profile to {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Containment for the tunneled TPU PJRT plugin ("axon") wedging backend init.

On this image a sitecustomize registers the axon PJRT plugin in every
interpreter. When the TPU tunnel is down, ANY JAX backend initialization
wedges the process forever — ``JAX_PLATFORMS=cpu`` alone does not help,
because the plugin factory latches before user code runs. The only reliable
guard is to unregister the factory before the first backend initializes.

This module is the single shared implementation of that guard (used by
``tests/conftest.py``, ``bench.py`` and ``__graft_entry__.py``); it touches a
private JAX API (``xla_bridge._backend_factories``) in exactly one place so a
JAX upgrade needs one fix, not three.
"""

from __future__ import annotations

import contextlib
import signal
import warnings
from collections.abc import Callable, Iterator


def force_cpu_platform() -> bool:
    """Pin the CPU platform and unregister the axon plugin factory.

    Must run before the first JAX backend initializes (importing jax is fine
    — the sitecustomize already did that; *initializing a backend* is the
    wedge). Returns True if the factory was popped (or was absent), False if
    backends were already initialized or the private API moved — in both
    False cases a warning explains the residual hang risk.
    """
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        # Late call, but if the process is ALREADY on the CPU platform the
        # guard's goal is met (an earlier caller — conftest, another CLI —
        # guarded first); only a live non-CPU backend leaves residual wedge
        # risk worth warning about.
        if jax.default_backend() == "cpu":
            return True
        warnings.warn(
            "force_cpu_platform() called after JAX backends initialized; "
            "platform cannot be changed now",
            stacklevel=2,
        )
        return False
    # NOT redundant with a JAX_PLATFORMS=cpu env var: the sitecustomize
    # imported jax first, so jax.config already latched the env value.
    jax.config.update("jax_platforms", "cpu")
    try:
        xla_bridge._backend_factories.pop("axon", None)
    except AttributeError:
        warnings.warn(
            "jax.xla_bridge._backend_factories is gone; the axon PJRT plugin "
            "cannot be unregistered and this process may hang at backend "
            "init if the TPU tunnel is down",
            stacklevel=2,
        )
        return False
    return True


def force_cpu_if_env_requested() -> bool:
    """Apply :func:`force_cpu_platform` when ``JAX_PLATFORMS=cpu`` is set.

    CLI entry points call this before their first backend-touching import:
    honoring the env var is what users expect, and on hosts with a tunneled
    TPU plugin the env var ALONE does not stop the plugin factory from
    wedging a dead tunnel at init. Returns True if the guard ran.
    """
    import os

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        try:
            return force_cpu_platform()
        except ImportError:
            # Backend-less install (schema/CPU-only extras): there is no
            # jax to wedge, and the pure-HiGHS solve paths that call this
            # guard unconditionally must keep working without one.
            return False
    return False


@contextlib.contextmanager
def backend_init_watchdog(
    timeout_s: float, on_timeout: Callable[[], None]
) -> Iterator[None]:
    """Best-effort SIGALRM watchdog around a first JAX-backend contact.

    A probe subprocess can report a live tunnel that drops before the parent
    initializes its own backend (TOCTOU); this arms an interval timer so the
    parent can still emit structured output instead of hanging silently.
    Best-effort because a wedge that never releases the GIL also never lets
    the Python signal handler run — but the tunnel's gRPC waits do release
    it. ``on_timeout`` should report and ``os._exit``; if it returns, the
    wedged call resumes.

    Main thread only (SIGALRM); nesting is not supported.
    """

    def _handler(signum, frame):  # noqa: ARG001 - signal handler signature
        on_timeout()

    old = signal.signal(signal.SIGALRM, _handler)
    signal.setitimer(signal.ITIMER_REAL, timeout_s)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)

"""The combiner flush thread: bucket pending tickets, dispatch, scatter.

One background thread owns the buckets. ``submit`` (called from shard
worker threads) files a prepared ticket under its packed signature and
wakes the thread; the thread flushes a bucket when it reaches the
policy's lane cap (``combine_flush_full``) or when its oldest lane has
waited ``max_wait_ms`` (``combine_flush_deadline``). A flush is ONE
``solver.batchlayout.solve_batch`` call — one ``_solve_batched``
dispatch — and each decoded lane is handed to its entry's ``deliver``
callback (the gateway enqueues the shard's ``adopt_combine`` there; a
dispatch failure delivers the error instead, and the shard falls back to
a local solve). The combiner never touches scheduler state itself: it
only moves packed blobs in and decoded results out, which is what makes
it safe to run off every shard's worker thread.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from .policy import BucketPolicy
from ..utils.lockwatch import make_lock


class CombineEntry:
    """One shard's pending lane: the scheduler ticket plus the delivery
    callback ``deliver(decoded, error)`` invoked on the combiner thread
    exactly once (decoded is the lane's ``(per_k_results, best)``)."""

    __slots__ = ("ticket", "deliver")

    def __init__(self, ticket, deliver: Callable):
        self.ticket = ticket
        self.deliver = deliver


class SolveCombiner:
    """Groups prepared tickets into signature buckets and dispatches one
    batched solve per bucket. Thread-safe ``submit``; ``stop()`` drains
    every pending bucket before joining, so no waiter is ever stranded."""

    def __init__(self, policy: Optional[BucketPolicy] = None, metrics=None):
        self.policy = policy if policy is not None else BucketPolicy()
        self.metrics = metrics
        self._cv = make_lock("combiner.buckets", kind="condition")
        # signature -> [(entry, enqueue_monotonic), ...] in arrival order.
        self._buckets: Dict[tuple, List[tuple]] = {}  # guarded-by: self._cv
        self._stopping = False  # guarded-by: self._cv
        self._stopped = False  # guarded-by: self._cv
        # Lifetime stats for /signals, guarded by the same condition lock.
        self._stats = {  # guarded-by: self._cv
            "batches": 0,
            "instances": 0,
            "flush_full": 0,
            "flush_deadline": 0,
            "errors": 0,
            "occupancy_sum": 0.0,
            "padding_waste_sum": 0.0,
        }
        self._thread = threading.Thread(
            target=self._run, name="solve-combiner", daemon=True
        )
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def submit(self, entry: CombineEntry) -> None:
        """File one prepared lane; wakes the flush thread. After ``stop``
        began, delivers an error immediately instead of queueing into a
        bucket nobody will flush."""
        with self._cv:
            if self._stopping:
                stopped = True
            else:
                stopped = False
                sig = entry.ticket.prep.instance.signature
                self._buckets.setdefault(sig, []).append(
                    (entry, time.monotonic())
                )
                self._cv.notify()
        if stopped:
            self._deliver(entry, None, RuntimeError("combiner is stopped"))

    def snapshot(self) -> dict:
        """Lifetime counters + live occupancy for /signals' combine block."""
        with self._cv:
            pending = sum(len(v) for v in self._buckets.values())
            s = dict(self._stats)
            live_buckets = len(self._buckets)
        batches = s.pop("batches")
        occ_sum = s.pop("occupancy_sum")
        waste_sum = s.pop("padding_waste_sum")
        return {
            "batches": batches,
            "instances": s["instances"],
            "flush_full": s["flush_full"],
            "flush_deadline": s["flush_deadline"],
            "errors": s["errors"],
            "pending": pending,
            "buckets": live_buckets,
            "occupancy_mean": (occ_sum / batches) if batches else None,
            "padding_waste_mean": (waste_sum / batches) if batches else None,
        }

    def stop(self) -> None:
        """Drain every pending bucket (final deadline flushes), then join."""
        with self._cv:
            self._stopping = True
            self._cv.notify()
        self._thread.join()

    # -- flush thread ------------------------------------------------------

    def _run(self) -> None:
        wait_s = max(self.policy.max_wait_ms, 0.1) / 1e3
        while True:
            with self._cv:
                while not self._stopping and self._take_ready(peek=True) is None:
                    self._cv.wait(timeout=wait_s)
                batch = self._take_ready(final=self._stopping)
                if batch is None and self._stopping:
                    self._stopped = True
                    return
            if batch is not None:
                reason, entries = batch
                self._flush(reason, entries)

    def _take_ready(self, peek: bool = False, final: bool = False):
        """Under the lock: the next flushable bucket, or None. ``final``
        (stop-time drain) makes every non-empty bucket flushable."""
        now = time.monotonic()
        deadline_s = self.policy.max_wait_ms / 1e3
        for sig, lanes in self._buckets.items():
            cap = self.policy.lane_cap(lanes[0][0].ticket.prep.instance.M_pad)
            if len(lanes) >= cap:
                if peek:
                    return True
                take, rest = lanes[:cap], lanes[cap:]
                if rest:
                    self._buckets[sig] = rest
                else:
                    del self._buckets[sig]
                return "full", [e for e, _ in take]
            if final or (now - lanes[0][1]) >= deadline_s:
                if peek:
                    return True
                del self._buckets[sig]
                return ("deadline", [e for e, _ in lanes])
        return None

    def _flush(self, reason: str, entries: List[CombineEntry]) -> None:
        from ..solver.batchlayout import solve_batch

        t0 = time.perf_counter()
        tm: dict = {}
        m_pad = entries[0].ticket.prep.instance.M_pad
        lanes = self.policy.quantize_lanes(len(entries), m_pad)
        try:
            decoded = solve_batch(
                [e.ticket.prep.instance for e in entries],
                timings=tm,
                lane_pad=lanes,
            )
        except BaseException as err:
            with self._cv:
                self._stats["errors"] += 1
            if self.metrics is not None:
                self.metrics.inc("combine_dispatch_error")
            for e in entries:
                self._deliver(e, None, err)
            return
        ms = (time.perf_counter() - t0) * 1e3
        n = len(entries)
        waste = sum(
            1.0 - e.ticket.prep.instance.M_real / e.ticket.prep.instance.M_pad
            for e in entries
        ) / n
        with self._cv:
            self._stats["batches"] += 1
            self._stats["instances"] += n
            self._stats["flush_full" if reason == "full" else "flush_deadline"] += 1
            self._stats["occupancy_sum"] += n
            self._stats["padding_waste_sum"] += waste
        if self.metrics is not None:
            self.metrics.inc("combine_batches")
            self.metrics.inc("combine_instances", n)
            self.metrics.inc(
                "combine_flush_full" if reason == "full"
                else "combine_flush_deadline"
            )
            self.metrics.observe("combine_bucket_occupancy", float(n))
            self.metrics.observe("combine_padding_waste", waste)
            self.metrics.observe("combine_batch_ms", ms)
            if "static_hit" in tm:
                self.metrics.observe("combine_static_hit", tm["static_hit"])
        for e, d in zip(entries, decoded):
            self._deliver(e, d, None)

    def _deliver(self, entry: CombineEntry, decoded, err) -> None:
        """Invoke one delivery callback; a dead callback must not kill the
        flush thread (same contract as the worker completion callbacks)."""
        try:
            entry.deliver(decoded, err)
        except Exception:
            if self.metrics is not None:
                self.metrics.inc("worker_callback_error")

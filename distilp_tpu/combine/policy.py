"""The committed bucket policy: which padded shapes exist, how full a
batch may get, and how long a lane may wait.

The policy is COMMITTED — fixed at configuration time, never adapted to
observed traffic — because the batch shape ladder is also the compile
surface: every ``(M_pad, batch <= lane cap)`` pair this policy can emit
is a shape ``_solve_batched`` may trace, and the PR 14 zero-recompile
gate only holds if that set is finite and warmed once. (An adaptive
bucketer that split or merged boundaries under load would mint fresh
shapes mid-flood — a recompile storm by construction.)

Note the batch dimension itself is ALSO a compile-shape dimension:
``_solve_batched`` vmaps over the lane axis, so XLA specializes on the
lane COUNT. ``quantize_lanes`` therefore snaps every dispatch to a
power-of-two lane count (clamped to the cap) and ``solve_batch`` fills
the extra lanes by repeating the last instance — at most 2x phantom
solve work buys a reachable executable set of exactly
``len(boundaries) x (log2(max_batch)+1)`` shapes, all of which
``Gateway.warm_combine`` traces before the measured phase begins.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

# Default padded-M ladder: powers of two through the fleet sizes the
# serving tier actually sees. Fleets above the top boundary bucket at
# exact M (no padding) — they are rare enough that shape sharing stops
# paying for the phantom work.
DEFAULT_BOUNDARIES: Tuple[int, ...] = (2, 4, 8, 16, 32, 64, 128)

# Default max lanes per dispatch. The real bound is usually the memory
# budget (``lane_cap``); 16 keeps the decode loop short even when memory
# is plentiful.
DEFAULT_MAX_BATCH = 16

# Default flush deadline: how long the FIRST lane of a bucket may wait
# for company before the bucket dispatches anyway. Two milliseconds is
# well under a warm solve, so a lone shard's latency floor barely moves
# while a flood fills buckets long before the deadline.
DEFAULT_MAX_WAIT_MS = 2.0


@dataclass(frozen=True)
class BucketPolicy:
    """Shape-bucket contract for the cross-shard combiner.

    ``boundaries`` — ascending padded fleet sizes; ``pad_for(M)`` snaps a
    fleet to the smallest boundary that fits (or exact M above the top).
    ``max_batch`` — hard lane cap per dispatch. ``max_wait_ms`` — flush
    deadline for an under-full bucket. ``mem_budget_bytes`` — optional
    analytic padding budget: when set, ``lane_cap`` shrinks the lane
    count so the bucket's peak working set (``ops.memmodel.peak_bytes``
    at the PADDED M, times lanes) stays inside it — the memory ledger's
    headroom signal stays honest under combined dispatches.
    """

    boundaries: Tuple[int, ...] = DEFAULT_BOUNDARIES
    max_batch: int = DEFAULT_MAX_BATCH
    max_wait_ms: float = DEFAULT_MAX_WAIT_MS
    mem_budget_bytes: Optional[int] = None
    engine: str = "ipm"  # memmodel engine the budget is priced against

    def __post_init__(self) -> None:
        bounds = tuple(int(b) for b in self.boundaries)
        if not bounds or any(b < 1 for b in bounds):
            raise ValueError(f"boundaries must be positive: {bounds}")
        if list(bounds) != sorted(set(bounds)):
            raise ValueError(
                f"boundaries must be strictly ascending: {bounds}"
            )
        object.__setattr__(self, "boundaries", bounds)
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1 (got {self.max_batch})")
        if self.max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0 (got {self.max_wait_ms})"
            )

    def pad_for(self, M: int) -> int:
        """The committed padded size for a fleet of ``M`` real devices:
        the smallest boundary >= M, or exact M above the top boundary."""
        if M < 1:
            raise ValueError(f"fleet size must be >= 1 (got {M})")
        for b in self.boundaries:
            if b >= M:
                return b
        return M

    def lane_cap(self, M_pad: int) -> int:
        """Max lanes a bucket at ``M_pad`` may batch: ``max_batch``,
        shrunk to the memory budget when one is set (at least one lane —
        a single-lane dispatch is the per-shard working set, which the
        per-shard path would have paid anyway)."""
        cap = self.max_batch
        if self.mem_budget_bytes is not None:
            from ..ops.memmodel import peak_bytes

            per_lane = peak_bytes(M_pad, self.engine)
            cap = min(cap, max(1, int(self.mem_budget_bytes // per_lane)))
        return cap

    def quantize_lanes(self, n: int, M_pad: int) -> int:
        """The committed lane count for an ``n``-instance flush: the
        smallest power of two >= n, clamped to ``lane_cap(M_pad)``. This
        is the lane-axis half of the zero-recompile contract — the set of
        lane counts a bucket can dispatch at is {1, 2, 4, ..., cap}, all
        of which warmup can enumerate."""
        if n < 1:
            raise ValueError(f"lane count must be >= 1 (got {n})")
        cap = self.lane_cap(M_pad)
        q = 1
        while q < n:
            q *= 2
        return min(q, max(cap, n))

    def lane_shapes(self, M_pad: int) -> Tuple[int, ...]:
        """Every lane count ``quantize_lanes`` can emit for this bucket:
        the powers of two up to the cap, plus the cap itself when it is
        not a power of two. Warmup iterates exactly this set."""
        cap = self.lane_cap(M_pad)
        shapes = []
        q = 1
        while q < cap:
            shapes.append(q)
            q *= 2
        shapes.append(cap)
        return tuple(shapes)

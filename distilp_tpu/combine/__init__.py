"""Cross-shard solve combiner: many shards' ticks, one vmapped dispatch.

At millions-of-users event rates the per-shard solve is the wrong grain:
a hundred fleets each paying a warm solve serially wastes exactly the
thing the jax backend is best at — vmapped batching. This package sits in
the gateway ingest path *behind* the coalescer: each shard's pending
drift run is packed (``Scheduler.prepare_combine`` →
``solver.batchlayout.pack_instance``) instead of solved, grouped into a
shape bucket by its packed signature, and one ``_solve_batched`` dispatch
per bucket prices every member at once. Results scatter back onto each
shard's worker (``Scheduler.adopt_combine``), so warm state, the
speculation bank, flight records and the published ``PlacementView`` are
exactly what the per-shard path would have produced (mode/metrics aside).

Two committed pieces:

- ``BucketPolicy`` — the shape-bucket contract: a fixed ladder of padded
  fleet sizes (mixed real M inside a bucket rides phantom padding — see
  ``solver.batchlayout``), a lane cap sized against the ``ops.memmodel``
  analytic padding budget, and the flush triggers (full bucket / max
  wait). *Committed* means the boundaries never adapt to traffic: every
  reachable batch shape is a finite, enumerable set, which is what keeps
  the compile ledger's zero-recompile gate holding across bucket churn.

- ``SolveCombiner`` — the flush thread: buckets pending tickets by
  signature, dispatches ``solve_batch`` per bucket, and delivers each
  lane back to its shard.
"""

from .policy import BucketPolicy
from .combiner import CombineEntry, SolveCombiner

__all__ = ["BucketPolicy", "SolveCombiner", "CombineEntry"]

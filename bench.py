#!/usr/bin/env python3
"""Headline benchmark: 16-device Llama-3-70B HALDA sweep wall-clock.

Workload (BASELINE.md north star): assign 80 layers across a 16-device
heterogeneous fleet, full k-candidate sweep, mip_gap<=1e-3. The JAX backend
solves the whole sweep as batched accelerator work; the baseline is the
equivalent scipy/HiGHS branch-and-cut sweep measured in-process (the same
engine the reference uses, see BASELINE.md).

Prints ONE JSON line:
    {"metric": ..., "value": <cold jax ms, median of N>, "unit": "ms",
     "vs_baseline": <speedup>,
     "warm_tick_ms": <warm-start streaming re-solve ms>,
     "placements_per_sec": <1000 / warm_tick_ms>,
     "pipelined_placements_per_sec": <submit/collect loop with one tick in
                          flight: host prep + upload overlap the previous
                          solve's execution + result transfer>,
     "moe_warm_tick_ms": <DeepSeek-V3 E=256 32-device streaming MoE
                          re-placement, certified, median ms>,
     "breakdown": {"pack_ms", "upload_ms", "solve_ms"}}

All headline numbers are medians of REPEATS runs (best-of flattered the
result; the median is what a user sees). The extra keys report the
streaming north star (BASELINE.json "placements/sec over k-sweep" and
config 5 "DeepSeek-V3 MoE real-time re-placement"): each tick perturbs the
fleet's measured t_comm and re-solves warm-started from the previous
placement — for MoE, the previous tick's Lagrangian multipliers certify
the re-solve without re-running the root ascent.
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

REPEATS = 10
MIP_GAP = 1e-3
M_DEVICES = 16
MOE_DEVICES = 32


def main() -> int:
    import numpy as np

    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.streaming import StreamingReplanner
    from distilp_tpu.utils import make_synthetic_fleet

    model = load_model_profile(
        REPO / "tests" / "profiles" / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(M_DEVICES, seed=123)

    # Baseline: the scipy/HiGHS branch-and-cut sweep (reference engine).
    t0 = time.perf_counter()
    ref = halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="cpu")
    cpu_ms = (time.perf_counter() - t0) * 1e3

    # JAX backend: warm up (compile), then median-of-N wall clock.
    got = halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
    agree = (
        abs(got.obj_value - ref.obj_value)
        <= 2 * MIP_GAP * abs(ref.obj_value) + 1e-9
    )
    if not (agree and got.certified):
        # Report the failure in the JSON rather than dying without a line.
        print(
            json.dumps(
                {
                    "metric": "halda_sweep_16dev_llama70b_wallclock",
                    "value": None,
                    "unit": "ms",
                    "error": (
                        f"north-star solve invalid: agree={agree} "
                        f"certified={got.certified} gap={got.gap} "
                        f"jax={got.obj_value} cpu={ref.obj_value}"
                    ),
                }
            )
        )
        return 1

    times = []
    breakdown: dict = {}
    for _ in range(REPEATS):
        tm: dict = {}
        t0 = time.perf_counter()
        halda_solve(
            devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax", timings=tm
        )
        times.append((time.perf_counter() - t0) * 1e3)
        for k, v in tm.items():
            breakdown.setdefault(k, []).append(v)
    jax_ms = statistics.median(times)
    breakdown = {k: round(statistics.median(v), 3) for k, v in breakdown.items()}

    # Streaming re-placement: warm-started ticks under drifting t_comm.
    planner = StreamingReplanner(mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
    planner.step(devs, model)
    rng = np.random.default_rng(7)
    warm_times = []
    for _ in range(REPEATS):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        t0 = time.perf_counter()
        planner.step(devs, model)
        warm_times.append((time.perf_counter() - t0) * 1e3)
    warm_ms = statistics.median(warm_times)

    # Pipelined streaming: one tick in flight while the next is prepared —
    # host assembly + upload overlap the previous solve's execution and
    # result transfer, so throughput beats 1/latency on RTT-bound links.
    # The timer covers EVERY counted tick end to end (first submit
    # included); an uncertified tick is reported, never asserted (the
    # headline JSON line must survive).
    planner.reset()
    n_pipe = 2 * REPEATS
    pipe_uncertified = 0
    t0 = time.perf_counter()
    planner.submit(devs, model)
    for _ in range(n_pipe):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        planner.submit(devs, model)
        if not planner.collect().certified:
            pipe_uncertified += 1
    if not planner.collect().certified:
        pipe_uncertified += 1
    pipe_s = time.perf_counter() - t0
    pipelined_per_sec = (n_pipe + 1) / pipe_s

    # MoE real-time re-placement (BASELINE.json config 5): DeepSeek-V3,
    # E=256 routed experts co-assigned over a 32-device fleet. Warm ticks
    # re-certify against the bound at the previous tick's multipliers. A
    # failure here must not cost the headline line: report it inline.
    payload = {
        "metric": "halda_sweep_16dev_llama70b_wallclock",
        "value": round(jax_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / jax_ms, 3),
        "warm_tick_ms": round(warm_ms, 3),
        "placements_per_sec": round(1000.0 / warm_ms, 1),
        "pipelined_placements_per_sec": round(pipelined_per_sec, 1),
        "breakdown": breakdown,
    }
    if pipe_uncertified:
        payload["pipelined_uncertified_ticks"] = pipe_uncertified
    try:
        moe_ms, moe_result = _moe_warm_tick(rng)
        payload["moe_warm_tick_ms"] = round(moe_ms, 3)
        payload["moe_certified"] = moe_result.certified
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["moe_error"] = f"{type(e).__name__}: {e}"

    print(json.dumps(payload))
    return 0


def _moe_warm_tick(rng):
    """Median certified warm-tick ms on the DeepSeek-V3 32-device flagship."""
    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver.streaming import StreamingReplanner
    from distilp_tpu.utils import make_synthetic_fleet

    split = profile_model(
        str(REPO / "tests" / "configs" / "deepseek_v3.json"),
        batch_sizes=[1],
        sequence_length=128,
    )
    model = split.to_model_profile()
    # Expert residency is hard-capped: the fleet must physically hold the
    # E=256 expert slices (~1.6 GB each), so give every pool 32 GB.
    devs = make_synthetic_fleet(MOE_DEVICES, seed=11, pool_bytes=int(32e9))
    planner = StreamingReplanner(mip_gap=MIP_GAP, kv_bits="8bit", backend="jax")
    planner.step(devs, model)  # cold solve + compile
    planner.step(devs, model)  # compile the warm layout
    times = []
    result = planner.last
    for _ in range(REPEATS):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        t0 = time.perf_counter()
        result = planner.step(devs, model)
        times.append((time.perf_counter() - t0) * 1e3)
    assert result.certified, f"MoE warm tick not certified (gap={result.gap})"
    assert sum(result.y) == model.n_routed_experts
    return statistics.median(times), result


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python3
"""Headline benchmark: 16-device Llama-3-70B HALDA sweep wall-clock.

Workload (BASELINE.md north star): assign 80 layers across a 16-device
heterogeneous fleet, full k-candidate sweep, mip_gap<=1e-3. The JAX backend
solves the whole sweep as batched accelerator work; the baseline is the
equivalent scipy/HiGHS branch-and-cut sweep measured in-process (the same
engine the reference uses, see BASELINE.md).

Prints ONE JSON line:
    {"metric": ..., "value": <cold jax ms, median of N>, "unit": "ms",
     "vs_baseline": <speedup>,
     "warm_tick_ms": <warm-start streaming re-solve ms>,
     "placements_per_sec": <1000 / warm_tick_ms>,
     "pipelined_placements_per_sec": <submit/collect loop with one tick in
                          flight: host prep + upload overlap the previous
                          solve's execution + result transfer>,
     "moe_warm_tick_ms": <DeepSeek-V3 E=256 32-device streaming MoE
                          re-placement, certified, median ms>,
     "scenario_batch_placements_per_sec": <8 what-if t_comm futures of the
                          16-device fleet, warm-seeded from the streaming
                          incumbent, solved in ONE vmapped dispatch — the
                          WIRE-COST ceiling for planning workloads (S
                          placements for one per-operation tunnel bill);
                          off-tunnel it reflects S full solves under
                          0.5-2.0x drift, not a throughput ceiling>,
     "tiny_put_ms": <median 16-byte device_put: the tunnel's per-operation
                          wire cost, the wall-clock floor of any
                          synchronous tick — recorded so captures taken
                          under different tunnel conditions compare>,
     "breakdown": {"pack_ms", "upload_ms", "solve_ms", "static_hit"}}

All headline numbers are medians of REPEATS runs (best-of flattered the
result; the median is what a user sees). The extra keys report the
streaming north star (BASELINE.json "placements/sec over k-sweep" and
config 5 "DeepSeek-V3 MoE real-time re-placement"): each tick perturbs the
fleet's measured t_comm and re-solves warm-started from the previous
placement — for MoE, the previous tick's Lagrangian multipliers certify
the re-solve without re-running the root ascent.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

REPEATS = 10
MIP_GAP = 1e-3
M_DEVICES = 16
MOE_DEVICES = 32

# Backend-availability probe. The tunneled TPU plugin ("axon") can wedge
# backend init forever when the tunnel is down — and JAX_PLATFORMS=cpu does
# not prevent it, because the plugin factory latches first. So the first JAX
# contact happens in a THROWAWAY SUBPROCESS with a hard timeout; the parent
# only initializes JAX after the probe reports a live backend. On repeated
# failure the parent unregisters the plugin factory (same guard as
# tests/conftest.py) and runs the bench on the CPU platform so the round
# still produces a parseable JSON line instead of a traceback.
# The probe prints a sentinel-tagged line; library chatter on stdout (before
# or after it) is ignored by scanning for the sentinel rather than trusting
# line position.
_PROBE_SENTINEL = "DPERF_PROBE"
# Phase markers the probe child prints as it advances (flushed, so a
# killed-at-timeout child leaves a partial trail in its temp-file stdout):
# the LAST marker seen tells a wedged probe's post-mortem WHERE init died
# — importing jax, initializing the backend (the axon-tunnel wedge class),
# or the first compile+dispatch. The first_dispatch marker carries the
# compile ledger's counters, so "backend up but nothing ever compiled"
# and "wedged mid-first-compile" are distinguishable states.
_PHASE_SENTINEL = "DPERF_PHASE"
_PROBE_SRC = (
    "import json; "
    f"print('{_PHASE_SENTINEL} interp', flush=True); "
    "import jax; "
    f"print('{_PHASE_SENTINEL} jax_import', flush=True); "
    "from distilp_tpu.obs import compile_ledger as _cl; _led = _cl.enable(); "
    "d = jax.devices(); "
    f"print('{_PHASE_SENTINEL} backend_init', flush=True); "
    "import jax.numpy as jnp; jnp.add(1, 1).block_until_ready(); "
    f"print('{_PHASE_SENTINEL} first_dispatch ' "
    "+ json.dumps(_led.counters(), sort_keys=True), flush=True); "
    f"print('{_PROBE_SENTINEL}', d[0].platform, len(d))"
)


def parse_probe_phases(stdout: str) -> list[dict]:
    """The probe child's phase trail: ``[{"phase": name, ...}]`` in print
    order (the last entry is how far init got before success/wedge); the
    first_dispatch entry carries the child's compile-ledger counters."""
    out: list[dict] = []
    for ln in stdout.splitlines():
        if not ln.startswith(_PHASE_SENTINEL + " "):
            continue
        parts = ln[len(_PHASE_SENTINEL) + 1:].split(None, 1)
        rec: dict = {"phase": parts[0]}
        if len(parts) > 1:
            try:
                rec["ledger"] = json.loads(parts[1])
            except json.JSONDecodeError:
                rec["detail"] = parts[1]
        out.append(rec)
    return out
_PROBE_BACKOFF_S = (15.0, 45.0)  # sleep between attempts


def _env_num(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def run_contained(
    cmd: list[str],
    timeout_s: float,
    env: dict | None = None,
    cwd: str | None = None,
) -> tuple[int | None, str, str]:
    """Run cmd wedge-contained; (rc, stdout, stderr), rc None on timeout.

    The child gets its own session and TEMP FILES for stdout/stderr (no
    pipes): the wedging plugin can spawn tunnel helpers that inherit pipe
    write-ends, and draining a pipe after a timeout would block on those
    grandchildren — the exact hang this containment exists for. On timeout
    the whole process group is killed. Shared by the bench probe and
    tools/tpu_watch.py so the containment has ONE implementation.
    """
    import signal
    import tempfile

    with tempfile.TemporaryFile("w+") as out, tempfile.TemporaryFile("w+") as err:
        proc = subprocess.Popen(
            cmd,
            stdout=out,
            stderr=err,
            text=True,
            start_new_session=True,
            env=env,
            cwd=cwd,
        )
        try:
            rc: int | None = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            rc = None
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait()
        out.seek(0)
        err.seek(0)
        return rc, out.read(), err.read()


def _run_probe_once(timeout_s: float) -> tuple[int | None, str, str]:
    """One backend-liveness probe attempt (see :func:`run_contained`).
    Pinned to the repo root: the probe child imports distilp_tpu (the
    compile-ledger phase trail), which must resolve regardless of the
    caller's cwd."""
    return run_contained(
        [sys.executable, "-c", _PROBE_SRC], timeout_s, cwd=str(REPO)
    )


def parse_probe_output(rc: int | None, stdout: str) -> str | None:
    """Platform string from a probe attempt's output, None if not live.

    The single parser of the probe's sentinel protocol (used here and by
    tools/tpu_watch.py): scans for the LAST sentinel-tagged line so library
    chatter before or after it never confuses the result.
    """
    if rc != 0:
        return None
    hits = [
        ln
        for ln in stdout.strip().splitlines()
        if ln.startswith(_PROBE_SENTINEL + " ")
    ]
    return hits[-1].split()[1] if hits else None


def _probe_timeout() -> tuple[float, str]:
    """(seconds, source) of the TPU probe timeout.

    ``DISTILP_TPU_PROBE_TIMEOUT`` is the documented knob (BENCH_r05 burned
    150 s x 3 retries on a wedged backend init before falling back to CPU —
    CI that knows its tunnel is down sets this to a few seconds);
    ``DPERF_BENCH_PROBE_TIMEOUT`` stays honored for older capture scripts.
    The chosen value and where it came from are surfaced in the probe-error
    string so a capture's JSON line records WHY it waited as long as it did.
    """
    for name in ("DISTILP_TPU_PROBE_TIMEOUT", "DPERF_BENCH_PROBE_TIMEOUT"):
        if name in os.environ:
            return max(5.0, _env_num(name, 150)), name
    return 150.0, "default"


def _probe_backend() -> tuple[str | None, dict]:
    """Return (platform, probe_info); platform is None if no backend came up.

    ``probe_info`` carries the full diagnostic trail a wedged probe leaves
    behind: per-attempt phase timings (spawn->outcome wall clock and the
    backoff slept before it), the chosen timeout AND where it came from
    (env knob vs default — BENCH_r05 burned 150 s x 3 on a wedged init
    with no record of why it waited that long), and the last failure
    detail. On fallback the whole block lands in the payload's
    ``tpu_error`` so a capture records why it is CPU, not just that it is.
    """
    timeout_s, timeout_src = _probe_timeout()
    retries = max(1, int(_env_num("DPERF_BENCH_PROBE_RETRIES", 3)))
    detail = ""
    attempts: list[dict] = []
    for attempt in range(retries):
        backoff = 0.0
        if attempt:
            backoff = _PROBE_BACKOFF_S[
                min(attempt - 1, len(_PROBE_BACKOFF_S) - 1)
            ]
            time.sleep(backoff)
        t0 = time.perf_counter()
        rc, stdout, stderr = _run_probe_once(timeout_s)
        elapsed = time.perf_counter() - t0
        rec = {
            "attempt": attempt,
            "backoff_s": backoff,
            "elapsed_s": round(elapsed, 2),
        }
        phases = parse_probe_phases(stdout)
        if phases:
            rec["phases"] = [p["phase"] for p in phases]
            ledger = next(
                (p["ledger"] for p in phases if "ledger" in p), None
            )
            if ledger is not None:
                rec["ledger"] = ledger
        if rc is None:
            detail = (
                f"probe timed out after {timeout_s}s (backend init wedged; "
                f"timeout from {timeout_src})"
            )
            rec["outcome"] = "timeout"
            # The phase trail is the wedge post-mortem: the last marker a
            # killed child flushed says exactly where init died.
            rec["wedged_after"] = (
                phases[-1]["phase"] if phases else "spawn"
            )
            attempts.append(rec)
            continue
        platform = parse_probe_output(rc, stdout)
        if platform is not None:
            rec["outcome"] = "ok"
            attempts.append(rec)
            return platform, {"attempts": attempts}
        detail = (stderr.strip().splitlines() or ["probe failed with no output"])[-1]
        rec["outcome"] = f"failed rc={rc}"
        rec["detail"] = detail
        attempts.append(rec)
    return None, {
        "error": detail,
        "timeout_s": timeout_s,
        "timeout_source": timeout_src,
        "retries": retries,
        "attempts": attempts,
    }


def _force_cpu_platform() -> None:
    """Unregister the wedging plugin factory and pin the CPU platform."""
    from distilp_tpu.axon_guard import force_cpu_platform

    force_cpu_platform()


_PLATFORM = "unknown"  # recorded by main() so _main_guarded can report it

# Metrics gated by `--against` (see _compare_against): a >20% regression of
# either fails the run — `value` is the headline cold sweep, `warm_tick_ms`
# the streaming fast path this repo exists to keep fast.
_REGRESSION_GATED = (
    "value", "warm_tick_ms",
    "fleet_scale_pdhg_512_solve_ms", "fleet_scale_pdhg_2048_solve_ms",
    # Solver-interior efficiency: LP iterations burned before the north
    # star's certificate closed, per engine. A >20% growth means the warm
    # plumbing, budgets or restart tuning regressed even if wall-clock
    # noise hides it.
    "conv_ipm_iters_to_certify", "conv_pdhg_iters_to_certify",
    # Crash-to-serving-again under the kill loop: respawn + snapshot
    # restore + WAL replay. A >20% growth means the recovery chain got
    # slower (bigger WAL tails, slower restores, lazier detection) even
    # if the exactly-once audit still holds.
    "recovery_mttr_p99_ms",
)
# Higher-better metrics that also gate: a >20% DROP fails the compare.
# The gateway's sustained multi-fleet rate is the serving tier's headline.
_REGRESSION_GATED_HIGHER = (
    "gateway_events_per_sec_100f_4w",
    # The combiner's aggregate rate at 100 fleets — the cross-shard
    # batching headline, compared at equal p99 (combine_p99_ms_100f
    # rides alongside as a reported delta).
    "combine_events_per_sec_100f",
    "spec_hit_rate",
    # Overload realism: the events/sec at which p99 first clears the SLO
    # — the serving tier's real capacity headline under open-loop load.
    "overload_max_sustainable_eps",
)
_REGRESSION_TOL = 0.20
# Reported-only deltas (no gate): ms-like keys where lower is better,
# rate-like keys where higher is better.
_COMPARE_LOWER_BETTER = (
    "value", "warm_tick_ms", "moe_warm_tick_ms", "tiny_put_ms",
    "scheduler_p50_ms", "scheduler_p99_ms",
    "cold_process_ms", "cold_process_cached_ms",
    "fleet_scale_pdhg_512_solve_ms", "fleet_scale_pdhg_2048_solve_ms",
    "fleet_scale_sharded_512_solve_ms", "fleet_scale_sharded_8192_solve_ms",
    "gateway_p99_ms_100f_4w",
    "combine_p99_ms_100f", "combine_padding_waste",
    "overload_p999_ms",
    "obs_overhead_pct",
    "spec_p99_hit_ms", "spec_p99_on_ms",
    "conv_ipm_iters_to_certify", "conv_pdhg_iters_to_certify",
    "conv_pdhg_restarts", "conv_overhead_pct",
    "slo_overhead_pct",
    "compile_overhead_pct", "compile_warm_phase_count",
    "memory_overhead_pct", "memory_leak_bytes",
    "recovery_mttr_p50_ms", "recovery_mttr_p99_ms", "recovery_goodput_dip",
)
# Instrumentation cost ceiling: tracing + Prometheus exposition may never
# cost more than this fraction of the loadgen arm's events/sec. Checked
# as an ABSOLUTE bound on the new capture (not a delta vs the reference):
# the obs budget does not grow because an old capture was already slow.
_OBS_OVERHEAD_MAX_PCT = 5.0
# Same contract for the solver-interior telemetry: a traced solve may cost
# at most this much over the untraced one (absolute ceiling, not a delta
# vs the reference — the trace budget does not inflate with a slow capture).
_CONV_OVERHEAD_MAX_PCT = 5.0
# And for the SLO layer's timeline sampler: full sampling (one metrics
# round trip per worker per tick) may cost at most this much of the
# loadgen arm's events/sec — absolute, like the other obs ceilings.
_SLO_OVERHEAD_MAX_PCT = 5.0
# And for the compile ledger: dispatch counting + signature hashing on
# every instrumented entry-point call — same absolute ceiling.
_COMPILE_OVERHEAD_MAX_PCT = 5.0
# And for the memory ledger: per-dispatch hook + throttled live-array/RSS
# watermark sampling — same absolute ceiling.
_MEM_OVERHEAD_MAX_PCT = 5.0
_COMPARE_HIGHER_BETTER = (
    "vs_baseline", "placements_per_sec", "pipelined_placements_per_sec",
    "scenario_batch_placements_per_sec", "scheduler_events_per_sec",
    "twin_mc_evals_per_sec", "twin_rank_agreement",
    "fleet_scale_certified_m_max",
    "gateway_events_per_sec_100f_4w", "gateway_scaling_100f_4w",
    "combine_events_per_sec_100f", "combine_vs_per_shard_100f",
    "combine_bucket_occupancy",
    "spec_hit_rate",
    "overload_max_sustainable_eps", "overload_plateau_ratio",
    "compile_cache_hit_rate",
    "federation_scaling_4w", "federation_vs_thread",
)
# Process-worker scaling floor at 4 workers, checked ABSOLUTE on the new
# capture — but only when the capture itself says the gate is armed
# (federation_gate_armed: the host has >= 4 cores, so 4 solve processes
# can physically run in parallel; a 2-core box honestly caps near 2x).
_FEDERATION_SCALING_MIN = 3.0
# Graceful-saturation floor, checked ABSOLUTE on the new capture (like
# the obs ceiling): at 10x sustainable load, goodput must stay within
# 20% of the ladder's best — a plateau, not a cliff.
_OVERLOAD_PLATEAU_MIN = 0.8


def _load_reference_payload(path: str) -> dict:
    """A reference bench payload from disk: either a raw JSON line this
    script printed, or the driver's capture wrapper with a ``parsed`` key
    (the committed BENCH_rNN.json files)."""
    data = json.loads(Path(path).read_text())
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data["parsed"]
    if not isinstance(data, dict) or "metric" not in data:
        raise ValueError(f"{path} does not look like a bench payload")
    return data


def _compare_against(payload: dict, against: str) -> int:
    """Print per-metric deltas vs a reference capture; exit nonzero on a
    >20% regression of a gated metric. Missing/None values on either side
    are reported as n/a and never gate (a capture that failed a section
    must not mask a regression report, nor fabricate one)."""
    ref = _load_reference_payload(against)
    print(f"--- bench-compare vs {against} ---")
    # Wire/box-condition sanity: tiny_put_ms is the per-operation dispatch
    # floor recorded with every capture. When it differs materially, the
    # reference was taken on a different machine (or wire) and absolute-ms
    # deltas measure the box as much as the code — say so up front rather
    # than let a hardware swap read as a code regression.
    new_put, ref_put = payload.get("tiny_put_ms"), ref.get("tiny_put_ms")
    if (
        isinstance(new_put, (int, float))
        and isinstance(ref_put, (int, float))
        and ref_put > 0
        and not 0.67 <= new_put / ref_put <= 1.5
    ):
        print(
            f"WARNING: tiny_put_ms differs {new_put / ref_put:.2f}x from the "
            f"reference ({ref_put} -> {new_put}): the capture boxes are not "
            "comparable; gate results below reflect the machine as much as "
            "the code. Re-capture a same-box reference for a meaningful "
            "gate."
        )
    failures: list[str] = []
    for key in _COMPARE_LOWER_BETTER + _COMPARE_HIGHER_BETTER:
        new_v, ref_v = payload.get(key), ref.get(key)
        if not isinstance(new_v, (int, float)) or not isinstance(
            ref_v, (int, float)
        ) or ref_v == 0:
            print(f"{key:40s} n/a (new={new_v} ref={ref_v})")
            continue
        lower_better = key in _COMPARE_LOWER_BETTER
        change = (new_v - ref_v) / abs(ref_v)
        better = change < 0 if lower_better else change > 0
        tag = "improved" if better else "regressed"
        if abs(change) < 0.02:
            tag = "unchanged"
        print(
            f"{key:40s} {ref_v:>12.3f} -> {new_v:>12.3f}  "
            f"({change:+.1%}, {tag})"
        )
        if (
            key in _REGRESSION_GATED
            and lower_better
            and change > _REGRESSION_TOL
        ):
            failures.append(f"{key} regressed {change:+.1%} (gate ±{_REGRESSION_TOL:.0%})")
        if (
            key in _REGRESSION_GATED_HIGHER
            and not lower_better
            and change < -_REGRESSION_TOL
        ):
            failures.append(f"{key} regressed {change:+.1%} (gate ±{_REGRESSION_TOL:.0%})")
    obs_pct = payload.get("obs_overhead_pct")
    if isinstance(obs_pct, (int, float)) and obs_pct > _OBS_OVERHEAD_MAX_PCT:
        failures.append(
            f"obs_overhead_pct {obs_pct:.1f} > {_OBS_OVERHEAD_MAX_PCT:g} "
            "(tracing+prom instrumentation cost ceiling)"
        )
    conv_pct = payload.get("conv_overhead_pct")
    if (
        isinstance(conv_pct, (int, float))
        and conv_pct > _CONV_OVERHEAD_MAX_PCT
    ):
        failures.append(
            f"conv_overhead_pct {conv_pct:.1f} > {_CONV_OVERHEAD_MAX_PCT:g} "
            "(solver-interior telemetry cost ceiling on the traced arm)"
        )
    slo_pct = payload.get("slo_overhead_pct")
    if isinstance(slo_pct, (int, float)) and slo_pct > _SLO_OVERHEAD_MAX_PCT:
        failures.append(
            f"slo_overhead_pct {slo_pct:.1f} > {_SLO_OVERHEAD_MAX_PCT:g} "
            "(timeline-sampler cost ceiling on the sampled arm)"
        )
    cmp_pct = payload.get("compile_overhead_pct")
    if (
        isinstance(cmp_pct, (int, float))
        and cmp_pct > _COMPILE_OVERHEAD_MAX_PCT
    ):
        failures.append(
            f"compile_overhead_pct {cmp_pct:.1f} > "
            f"{_COMPILE_OVERHEAD_MAX_PCT:g} "
            "(compile-ledger attribution cost ceiling on the ledgered arm)"
        )
    # The zero-recompile warm-serving gate, checked ABSOLUTE on the new
    # capture: a single compile event during the steady-state warm/spec
    # phase is a silent-recompile regression regardless of the reference
    # (today's invariant is zero; this is what keeps it an invariant).
    warm_compiles = payload.get("compile_warm_phase_count")
    if isinstance(warm_compiles, (int, float)) and warm_compiles != 0:
        failures.append(
            f"compile_warm_phase_count {warm_compiles:g} != 0 (the warm "
            "serving phase paid an XLA compile — see the compile "
            "section's warm_phase_entries for the offending entry points)"
        )
    # The combiner's twin of the same invariant, also absolute: bucket
    # traffic after warm_combine must never mint an executable — the
    # committed bucket policy exists precisely so churn cannot.
    comb_compiles = payload.get("combine_warm_phase_compiles")
    if isinstance(comb_compiles, (int, float)) and comb_compiles != 0:
        failures.append(
            f"combine_warm_phase_compiles {comb_compiles:g} != 0 (combined "
            "bucket traffic compiled after the warm boundary — a bucket "
            "or lane shape escaped warm_combine's committed set)"
        )
    # Process-federation floor, absolute and self-arming: the capture
    # records whether its own host could honestly reach 4x (>= 4 cores);
    # an unarmed capture reports the ratio but never gates on it.
    fed_scale = payload.get("federation_scaling_4w")
    if (
        payload.get("federation_gate_armed")
        and isinstance(fed_scale, (int, float))
        and fed_scale < _FEDERATION_SCALING_MIN
    ):
        failures.append(
            f"federation_scaling_4w {fed_scale:g} < "
            f"{_FEDERATION_SCALING_MIN:g} on a >=4-core host (process "
            "workers stopped scaling — see the federation section's "
            "per-arm events/sec)"
        )
    # The per-process twin of compile_warm_phase_count, also absolute:
    # a child that compiles during the timed phase is silently paying an
    # XLA compile inside its serving budget.
    fed_warm = payload.get("federation_warm_phase_compiles")
    if isinstance(fed_warm, (int, float)) and fed_warm != 0:
        failures.append(
            f"federation_warm_phase_compiles {fed_warm:g} != 0 (a worker "
            "subprocess compiled during the steady-state warm phase — "
            "see the federation section's proc_workers per-child counts)"
        )
    # Crash recovery's exactly-once audit, absolute: every accepted event
    # is applied exactly once across kill -9s. Positive means the WAL
    # lost accepted events; NEGATIVE means replay double-applied (the
    # snapshot/WAL-truncate ordering or the seq-cursor reconciliation
    # broke) — both fail regardless of the reference.
    rec_lost = payload.get("recovery_events_lost")
    if isinstance(rec_lost, (int, float)) and rec_lost != 0:
        failures.append(
            f"recovery_events_lost {rec_lost:g} != 0 (accepted events "
            f"{'lost across a crash' if rec_lost > 0 else 'double-applied by WAL replay'}"
            " — see the recovery section's per-audit counters)"
        )
    # Its warm-restore twin, also absolute: a recovered shard that
    # resumes cold threw away its micro-snapshot (or restored a stale
    # one) and is silently paying re-solve latency after every crash.
    rec_cold = payload.get("recovery_cold_resumes")
    if isinstance(rec_cold, (int, float)) and rec_cold != 0:
        failures.append(
            f"recovery_cold_resumes {rec_cold:g} != 0 (a respawned shard "
            "resumed without warm state — snapshot restore or WAL replay "
            "fell back to a cold solve)"
        )
    mem_pct = payload.get("memory_overhead_pct")
    if isinstance(mem_pct, (int, float)) and mem_pct > _MEM_OVERHEAD_MAX_PCT:
        failures.append(
            f"memory_overhead_pct {mem_pct:.1f} > {_MEM_OVERHEAD_MAX_PCT:g} "
            "(memory-ledger watermark/analysis cost ceiling on the "
            "ledgered arm)"
        )
    # The zero-leak warm-serving gate, absolute like its compile twin:
    # net live-array growth across >= 100 warm ticks on EITHER engine is
    # a leak regardless of the reference (warm ticks allocate nothing
    # persistent; growth compounds into an OOM at fleet scale).
    leak = payload.get("memory_leak_bytes")
    if isinstance(leak, (int, float)) and leak > 0:
        failures.append(
            f"memory_leak_bytes {leak:g} > 0 (the warm serving phase "
            "pinned live jax arrays — see the memory section's per-"
            "engine leak reports for which engine and how much per tick)"
        )
    if payload.get("mem_calibration_ok") is False:
        failures.append(
            "mem_calibration_ok is false (the ops/memmodel analytic "
            "proxy fell outside its measured calibration band vs XLA "
            "memory_analysis temp bytes — fleet_scale's skip decisions "
            "can no longer trust it; see the memory section's "
            "calibration block)"
        )
    # The per-shard twin of the same contract, also absolute: the sharded
    # arms' measured XLA temp bytes must sit inside the calibration band
    # over memmodel's per-shard prediction, or choose_mesh_shards' sizing
    # decisions stop being trustworthy.
    if payload.get("fleet_shard_calibration_ok") is False:
        failures.append(
            "fleet_shard_calibration_ok is false (a sharded fleet_scale "
            "arm's ledger-measured temp bytes fell outside the per-shard "
            "memmodel prediction's calibration band — see fleet_scale's "
            "sharded block)"
        )
    # SLO absolute contracts (checked on the new capture, never relative):
    # the committed overload capture must fire AND clear the expected
    # burn-rate alert, the offline replay must be deterministic against
    # the committed fixture, and the /signals payload must validate
    # against its pydantic schema (the federation consumer contract).
    if payload.get("slo_alerts_ok") is False:
        failures.append(
            "slo_alerts_ok is false (the flood's burn-rate alert did not "
            "fire and clear as the committed capture expects — see the "
            "slo section's events)"
        )
    if payload.get("slo_replay_deterministic") is False:
        failures.append(
            "slo_replay_deterministic is false (offline timeline replay "
            "diverged from the committed expected alert sequence)"
        )
    if payload.get("slo_signals_schema_ok") is False:
        failures.append(
            "slo_signals_schema_ok is false (/signals payload no longer "
            "validates against obs.slo.SignalsPayload — the autoscaling "
            "contract broke)"
        )
    # Overload's absolute contracts: graceful saturation (plateau, not
    # cliff) and every shed observable. Checked on the new capture, never
    # relative — a collapse is a collapse even if the reference also
    # collapsed.
    plateau = payload.get("overload_plateau_ratio")
    if (
        isinstance(plateau, (int, float))
        and plateau < _OVERLOAD_PLATEAU_MIN
    ):
        failures.append(
            f"overload_plateau_ratio {plateau} < {_OVERLOAD_PLATEAU_MIN:g} "
            "(throughput cliffed at 10x sustainable load)"
        )
    if payload.get("overload_shed_reconciled") is False:
        failures.append(
            "overload_shed_reconciled is false (sheds counted that the "
            "flight recorder cannot explain — see overload.shed_violations)"
        )
    # Speculation's absolute contract (like the obs ceiling, not relative
    # to the reference): on the bundled burst trace, speculation-on p99
    # must beat speculation-off and hits must actually happen.
    on_p99, off_p99 = payload.get("spec_p99_on_ms"), payload.get("spec_p99_off_ms")
    if isinstance(on_p99, (int, float)) and isinstance(off_p99, (int, float)):
        if on_p99 >= off_p99:
            failures.append(
                f"spec_p99_on_ms {on_p99} >= spec_p99_off_ms {off_p99} "
                "(speculation must strictly beat the plain tick path)"
            )
        hit_rate = payload.get("spec_hit_rate")
        if isinstance(hit_rate, (int, float)) and hit_rate <= 0:
            failures.append("spec_hit_rate is 0 with speculation measured")
    if failures:
        print("bench-compare FAILED: " + "; ".join(failures), file=sys.stderr)
        return 1
    print("bench-compare OK")
    return 0


def main(against: str | None = None, history: str | None = None) -> int:
    global _PLATFORM
    platform, probe_info = _probe_backend()
    if platform is None:
        _force_cpu_platform()
        platform = "cpu(fallback)"
    _PLATFORM = platform
    import numpy as np

    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.streaming import StreamingReplanner
    from distilp_tpu.utils import make_synthetic_fleet

    model = load_model_profile(
        REPO / "tests" / "profiles" / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(M_DEVICES, seed=123)

    # Baseline: the scipy/HiGHS branch-and-cut sweep (reference engine).
    t0 = time.perf_counter()
    ref = halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="cpu")
    cpu_ms = (time.perf_counter() - t0) * 1e3

    # JAX backend: warm up (compile), then median-of-N wall clock. The first
    # call is the parent's first backend contact — a tunnel drop between the
    # probe and here would wedge it, so arm a best-effort watchdog that still
    # emits the JSON line (the handler can only run if the wedge releases the
    # GIL, which the tunnel's gRPC waits do).
    from distilp_tpu.axon_guard import backend_init_watchdog

    def _abort_wedged() -> None:
        print(
            json.dumps(
                {
                    "metric": "halda_sweep_16dev_llama70b_wallclock",
                    "value": None,
                    "unit": "ms",
                    "platform": platform,
                    "error": "jax backend contact wedged after successful "
                    "probe (tunnel dropped mid-bench)",
                }
            ),
            flush=True,
        )
        os._exit(1)

    first_contact_s = max(60.0, _env_num("DPERF_BENCH_FIRST_CONTACT_TIMEOUT", 900))
    with backend_init_watchdog(first_contact_s, _abort_wedged):
        got = halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")

    # Wire-condition diagnostic: the tunnel's per-operation cost varies run
    # to run and IS the wall-clock floor for a synchronous tick, so record
    # it next to every capture (a 16-byte put isolates fixed overhead from
    # bandwidth). Watchdogged like the first contact: a tunnel that drops
    # mid-bench must still cost only this diagnostic, never the JSON line.
    import jax.numpy as jnp

    tiny = np.ones(4, np.float32)
    put_times = []
    with backend_init_watchdog(first_contact_s, _abort_wedged):
        for _ in range(5):
            t0 = time.perf_counter()
            jnp.asarray(tiny).block_until_ready()
            put_times.append((time.perf_counter() - t0) * 1e3)
    tiny_put_ms = statistics.median(put_times)
    agree = (
        abs(got.obj_value - ref.obj_value)
        <= 2 * MIP_GAP * abs(ref.obj_value) + 1e-9
    )
    if not (agree and got.certified):
        # Report the failure in the JSON rather than dying without a line.
        print(
            json.dumps(
                {
                    "metric": "halda_sweep_16dev_llama70b_wallclock",
                    "value": None,
                    "unit": "ms",
                    "platform": platform,
                    "error": (
                        f"north-star solve invalid: agree={agree} "
                        f"certified={got.certified} gap={got.gap} "
                        f"jax={got.obj_value} cpu={ref.obj_value}"
                    ),
                }
            )
        )
        return 1

    times = []
    breakdown: dict = {}
    for _ in range(REPEATS):
        tm: dict = {}
        t0 = time.perf_counter()
        halda_solve(
            devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax", timings=tm
        )
        times.append((time.perf_counter() - t0) * 1e3)
        for k, v in tm.items():
            breakdown.setdefault(k, []).append(v)
    jax_ms = statistics.median(times)
    breakdown = {k: round(statistics.median(v), 3) for k, v in breakdown.items()}
    _add_per_round_iters(breakdown)

    # Streaming re-placement: warm-started ticks under drifting t_comm. The
    # warm breakdown carries the same keys as the cold one above — the
    # warm-vs-cold solve_ms delta and the executed-iteration counts are what
    # make the iterate-carrying warm start's win attributable, not just
    # visible in the headline number.
    planner = StreamingReplanner(mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
    planner.step(devs, model)
    rng = np.random.default_rng(7)
    warm_times = []
    warm_breakdown: dict = {}
    for _ in range(REPEATS):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        tm = {}
        t0 = time.perf_counter()
        planner.step(devs, model, timings=tm)
        warm_times.append((time.perf_counter() - t0) * 1e3)
        for k, v in tm.items():
            warm_breakdown.setdefault(k, []).append(v)
    warm_ms = statistics.median(warm_times)
    warm_breakdown = {
        k: round(statistics.median(v), 3) for k, v in warm_breakdown.items()
    }
    _add_per_round_iters(warm_breakdown)

    # Pipelined streaming: one tick in flight while the next is prepared —
    # host assembly + upload overlap the previous solve's execution and
    # result transfer, so throughput beats 1/latency on RTT-bound links.
    # The timer covers EVERY counted tick end to end (first submit
    # included); an uncertified tick is reported, never asserted (the
    # headline JSON line must survive).
    planner.reset()
    n_pipe = 2 * REPEATS
    pipe_uncertified = 0
    t0 = time.perf_counter()
    planner.submit(devs, model)
    for _ in range(n_pipe):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        planner.submit(devs, model)
        if not planner.collect().certified:
            pipe_uncertified += 1
    if not planner.collect().certified:
        pipe_uncertified += 1
    pipe_s = time.perf_counter() - t0
    pipelined_per_sec = (n_pipe + 1) / pipe_s

    # Scenario batching: S what-if t_comm futures of the SAME fleet in ONE
    # dispatch (shared device-resident static half, stacked dynamic blobs,
    # vmapped solve). Every scenario is seeded warm from the incumbent the
    # streaming loop just produced — what-ifs ARE drifts of the current
    # placement, and the exact on-device re-pricing makes stale seeds safe
    # (measured: warm seeding cuts the batch ~2.6x). On a tunneled chip
    # every operation bills a fixed wire cost, so ONE dispatch for S
    # placements is the wire-cost ceiling for planning workloads; on a
    # local backend the batch does S solves' worth of compute (the vmapped
    # search runs until the LAST scenario settles, and these what-ifs
    # drift 0.5-2.0x, far past the streaming loop's per-tick +/-5%), so
    # comparing its placements/sec against the warm-tick loop is
    # apples-to-oranges off-tunnel.
    from distilp_tpu.solver import halda_solve_scenarios

    S = 8
    rng_s = np.random.default_rng(17)
    scenario_fleets = []
    for _ in range(S):
        snap = [d.model_copy(deep=True) for d in devs]
        for d in snap:
            d.t_comm = max(0.0, d.t_comm * float(rng_s.uniform(0.5, 2.0)))
        scenario_fleets.append(snap)
    # A failure here (e.g. a drift excursion crossing a row-scale boundary,
    # which makes the batch refuse to share one dispatch) must cost only
    # this metric, never the headline JSON line.
    sc_ms = None
    sc_uncertified = 0
    sc_error = None
    try:
        sc_warms = [planner.last] * S
        halda_solve_scenarios(  # compile the batched layout
            scenario_fleets, model, kv_bits="4bit", mip_gap=MIP_GAP,
            warms=sc_warms,
        )
        sc_times = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            sc_results = halda_solve_scenarios(
                scenario_fleets, model, kv_bits="4bit", mip_gap=MIP_GAP,
                warms=sc_warms,
            )
            sc_times.append((time.perf_counter() - t0) * 1e3)
        sc_ms = statistics.median(sc_times)
        sc_uncertified = sum(1 for r in sc_results if not r.certified)
    except Exception as e:  # pragma: no cover - defensive bench path
        sc_error = f"{type(e).__name__}: {e}"

    # MoE real-time re-placement (BASELINE.json config 5): DeepSeek-V3,
    # E=256 routed experts co-assigned over a 32-device fleet. Warm ticks
    # re-certify against the bound at the previous tick's multipliers. A
    # failure here must not cost the headline line: report it inline.
    payload = {
        "metric": "halda_sweep_16dev_llama70b_wallclock",
        "value": round(jax_ms, 3),
        "unit": "ms",
        "platform": platform,
        "vs_baseline": round(cpu_ms / jax_ms, 3),
        "warm_tick_ms": round(warm_ms, 3),
        "placements_per_sec": round(1000.0 / warm_ms, 1),
        "pipelined_placements_per_sec": round(pipelined_per_sec, 1),
        "scenario_batch_placements_per_sec": (
            round(S * 1000.0 / sc_ms, 1) if sc_ms else None
        ),
        # Methodology marker: rounds <= 4 solved scenarios cold; comparing
        # scen/s across that boundary compares seeding modes, not engines.
        "scenario_seeding": "warm",
        "tiny_put_ms": round(tiny_put_ms, 3),
        "breakdown": breakdown,
        "warm_breakdown": warm_breakdown,
    }
    if sc_uncertified:
        payload["scenario_uncertified"] = sc_uncertified
    if sc_error:
        payload["scenario_error"] = sc_error
    if platform == "cpu(fallback)":
        # Structured fallback record (was a single opaque string): the
        # failure summary PLUS the probe's phase timings and the chosen
        # timeout's provenance, so a capture explains its own wait.
        payload["tpu_error"] = {
            **probe_info,
            "error": probe_info.get("error") or "tpu backend unavailable",
        }
    if pipe_uncertified:
        payload["pipelined_uncertified_ticks"] = pipe_uncertified
    try:
        moe_ms, moe_result, moe_breakdown = _moe_warm_tick(rng)
        payload["moe_warm_tick_ms"] = round(moe_ms, 3)
        payload["moe_certified"] = moe_result.certified
        payload["moe_breakdown"] = moe_breakdown
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["moe_error"] = f"{type(e).__name__}: {e}"

    # Scheduler service (distilp_tpu.sched): the streaming loop packaged as
    # an event-driven daemon. A seeded churn trace (joins, leaves, decay,
    # load drift) replays through the warm-pooled scheduler; the metric is
    # sustained events/sec with p50/p99 event->placement latency over the
    # steady state (post-warmup: per-fleet-shape jit compiles belong to
    # deployment, not the replanning rate). A failure must cost only these
    # keys, never the headline line.
    try:
        payload.update(_scheduler_bench(model, devs))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["scheduler_error"] = f"{type(e).__name__}: {e}"

    # Gateway tier (distilp_tpu.gateway): K synthetic fleets replayed
    # through 1/2/4 sharded solve workers via the load generator. The
    # headline is sustained events/sec at 100 fleets with the 4-vs-1
    # worker scaling ratio; p50/p99 event->placement latency (queue wait
    # included) is reported per arm. A failure costs only these keys.
    try:
        payload.update(_gateway_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["gateway_error"] = f"{type(e).__name__}: {e}"

    # Federation (ISSUE 19): the same loadgen workload through
    # process-backed workers at 1/2/4 subprocesses vs thread workers —
    # the N-GILs/N-runtimes scaling the thread backend cannot reach.
    # The >=3x @ 4 proc workers floor arms only on >=4-core hosts, and
    # every child's compile ledger must show ZERO timed-phase compiles
    # (absolute in --against). A failure costs only these keys.
    try:
        payload.update(_federation_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["federation_error"] = f"{type(e).__name__}: {e}"

    # Crash recovery (ISSUE 20): kill -9 loop against the SUPERVISED
    # process tier — MTTR p50/p99 from crash detection to serving again
    # (respawn + snapshot restore + WAL-tail replay), the exactly-once
    # audit (recovery_events_lost == 0 ABSOLUTE in --against, negative
    # would mean double-apply), zero post-recovery cold resumes
    # (absolute), and the goodput-dip depth a kill costs the serving
    # path. A failure costs only these keys.
    try:
        payload.update(_recovery_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["recovery_error"] = f"{type(e).__name__}: {e}"

    # Overload realism (distilp_tpu.traffic): OPEN-loop arrivals against
    # the 100-fleet gateway — a rate ladder finds the max sustainable
    # throughput (highest offered rate whose p99 meets the SLO), then a
    # 10x-sustainable flood with admission control ON (bounded queues +
    # coalescing) must PLATEAU: goodput within 20% of the ladder's best,
    # every shed counted AND reconciled against the flight recorder.
    # Gated in `--against` (overload_max_sustainable_eps regression,
    # overload_plateau_ratio >= 0.8 absolute, shed reconciliation clean).
    # A failure costs only these keys.
    try:
        payload.update(_overload_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["overload_error"] = f"{type(e).__name__}: {e}"

    # Observability (distilp_tpu.obs): the 10-fleet loadgen arm replayed
    # with tracing + Prometheus exposition ON vs OFF; obs_overhead_pct is
    # the events/sec cost of full instrumentation, gated at <= 5% by
    # `--against` so the tracing layer can never silently grow into the
    # serving budget. A failure costs only these keys.
    try:
        payload.update(_obs_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["obs_error"] = f"{type(e).__name__}: {e}"

    # SLO engine (distilp_tpu.obs.timeline + obs.slo): (1) the committed
    # overload capture replayed as a flood with the SLO engine attached —
    # the availability page alert must OPEN at the shed onset and CLOSE
    # after recovery, reconciled against the flight recorder; (2) the
    # offline alert replay over the committed synthetic timeline must
    # reproduce the committed expected sequence exactly (byte-determinism
    # of the evaluator); (3) the /signals payload must validate against
    # its pydantic schema; (4) timeline-sampler overhead on the loadgen
    # arm, interleaved off/on, gated <= 5% absolute alongside
    # obs_overhead_pct. A failure costs only these keys.
    try:
        payload.update(_slo_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["slo_error"] = f"{type(e).__name__}: {e}"

    # Digital twin (distilp_tpu.twin): Monte-Carlo throughput of the
    # vmapped robustness report (1024 perturbed what-if executions per
    # dispatch) and the objective-vs-twin rank agreement over the
    # solver-enumerated k-candidates — the proxy-validation gauge. Rides
    # the `--against` compare like every other section; a failure costs
    # only these keys.
    try:
        payload.update(_twin_bench(model, devs))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["twin_error"] = f"{type(e).__name__}: {e}"

    # Speculative replanning (distilp_tpu.sched.speculate): the bundled
    # burst/flap traces replayed with speculation off vs on, interleaved,
    # on identical seeded events. The headline is steady-state p99
    # event->placement latency (the scheduler's own serve clock —
    # presolve runs after publish and is billed separately as overhead %)
    # plus the honest hit-rate counters. A failure costs only these keys.
    try:
        payload.update(_speculation_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["speculation_error"] = f"{type(e).__name__}: {e}"

    # Convergence diagnostics (distilp_tpu.obs.convergence): the north-star
    # solve with solver-interior telemetry on, per LP engine — iterations
    # to certify, restart counts, and the traced-vs-untraced overhead
    # (gated <= 5% absolute by `--against`, like the obs arm). A failure
    # costs only these keys.
    try:
        payload.update(_convergence_bench(model, devs))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["convergence_error"] = f"{type(e).__name__}: {e}"

    # Compile ledger (distilp_tpu.obs.compile_ledger): XLA compile
    # visibility on the serving path. The loadgen arm re-runs with the
    # ledger ON (interleaved with OFF for the <= 5% overhead ceiling);
    # its headline is the zero-recompile gate — NO compile event during
    # the steady-state warm serving phase (compile_warm_phase_count == 0,
    # absolute in --against). Cold-process children report the
    # persistent-cache hit rate as the ledger classifies it (miss-populate
    # then hit-serve). A failure costs only these keys.
    try:
        payload.update(_compile_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["compile_error"] = f"{type(e).__name__}: {e}"

    # Memory ledger (distilp_tpu.obs.memory): the last unobserved axis.
    # Three contracts, all absolute in `--against`: (1) ledger overhead
    # on the interleaved loadgen arm <= 5% like every obs ceiling; (2)
    # the zero-leak warm gate — live-array bytes FLAT across >= 100 warm
    # ticks on BOTH LP engines; (3) the analytic memory model
    # (ops/memmodel.py, the proxies fleet_scale skips arms on) calibrated
    # against XLA's measured memory_analysis temp bytes at two M sizes.
    # A failure costs only these keys.
    try:
        payload.update(_memory_bench(model))
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["memory_error"] = f"{type(e).__name__}: {e}"

    # Restart cost (VERDICT r5 item 3): fresh-process first-solve wall
    # clock, uncached vs against the env-gated persistent compilation
    # cache. Subprocess-contained; a failure costs only these keys.
    try:
        payload.update(_cold_process_bench())
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["cold_process_error"] = f"{type(e).__name__}: {e}"

    # Fleet scale (ISSUE 6 / ROADMAP item 1): the IPM-vs-PDHG engine
    # comparison at M=512..4096 devices, pinning the crossover point.
    # Subprocess-contained per (M, engine); a failure costs only these keys.
    try:
        payload.update(_fleet_scale_bench())
    except Exception as e:  # pragma: no cover - defensive bench path
        payload["fleet_scale_error"] = f"{type(e).__name__}: {e}"

    print(json.dumps(payload))
    if history:
        # The machine-readable trajectory: one committed-format line per
        # run (tools/bench_history.HISTORY_KEYS), the dataset
        # `solver slo --history` trend-checks. Appended best-effort — a
        # read-only checkout must not fail the bench over its log line.
        try:
            from tools.bench_history import append_history

            append_history(payload, history)
        except OSError as e:
            print(f"bench history append failed: {e}", file=sys.stderr)
    if against:
        return _compare_against(payload, against)
    return 0


def _add_per_round_iters(breakdown: dict) -> None:
    """Derive ipm_iters_per_round from the executed-iteration counters the
    solver reports (median-of-run values); no-op when the keys are absent
    (e.g. a failed tick left the dict empty)."""
    if "ipm_iters_executed" in breakdown and breakdown.get("bnb_rounds"):
        breakdown["ipm_iters_per_round"] = round(
            breakdown["ipm_iters_executed"] / max(1.0, breakdown["bnb_rounds"]),
            2,
        )


def _scheduler_bench(model, base_devs) -> dict:
    """Scheduler-service section of the headline JSON line."""
    from distilp_tpu.sched import Scheduler, drift_warm_share, generate_trace, replay

    devs = [d.model_copy(deep=True) for d in base_devs]
    trace = generate_trace(
        "mixed", 50, seed=23, base_fleet=devs, max_extra_devices=1
    )
    sched = Scheduler(
        devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax",
        warm_pool_size=4,
    )
    warmup = 10
    report = replay(sched, trace, warmup=warmup)
    lat = report.latencies_ms  # post-warmup only
    steady_eps = 1000.0 * len(lat) / sum(lat) if lat else 0.0
    return {
        "scheduler_events_per_sec": round(steady_eps, 1),
        "scheduler_p50_ms": round(report.p50_ms, 3),
        "scheduler_p99_ms": round(report.p99_ms, 3),
        "scheduler_events": len(trace),
        "scheduler_drift_warm_share": round(drift_warm_share(sched.metrics), 3),
        "scheduler_pool_hit_rate": round(sched.metrics.pool_hit_rate(), 3),
        "scheduler_structural_uncertified": report.structural_uncertified,
        "scheduler_failed_ticks": report.failed_ticks,
    }


def _gateway_bench(model) -> dict:
    """gateway_* section: multi-fleet serving throughput vs worker count.

    Every arm replays the IDENTICAL seeded trace set (K fleets x
    ``DPERF_GATEWAY_EVENTS`` drift events each, after one warmup event
    per fleet that pays the cold solve + any jit compile), so the
    events/sec ratio between worker counts is a like-for-like scaling
    measurement. All fleets share one shape (M = ``DPERF_GATEWAY_M``), so
    the compile is paid once per process, not per fleet. The scaling
    ceiling on a C-core host is min(workers, C)x — thread-backed workers
    overlap XLA execution (which releases the GIL), not Python host code —
    so ``gateway_scaling_100f_4w`` must be read next to the machine's
    core count (this repo's CI box has 2, capping the honest ratio at
    ~2x; the >=2.5x serving target needs >=4 cores).
    """
    from distilp_tpu.gateway.loadgen import run_loadgen

    fleet_counts = [
        int(x)
        for x in os.environ.get("DPERF_GATEWAY_FLEETS", "10,100").split(",")
        if x.strip()
    ]
    worker_counts = [
        int(x)
        for x in os.environ.get("DPERF_GATEWAY_WORKERS", "1,2,4").split(",")
        if x.strip()
    ]
    events = int(_env_num("DPERF_GATEWAY_EVENTS", 5))
    fleet_size = int(_env_num("DPERF_GATEWAY_M", 3))
    arms: dict = {}
    for n_fleets in fleet_counts:
        for n_workers in worker_counts:
            rep = run_loadgen(
                model,
                n_fleets=n_fleets,
                n_workers=n_workers,
                events_per_fleet=events,
                fleet_size=fleet_size,
                seed=0,
                k_candidates=[8, 10],
                mip_gap=MIP_GAP,
            )
            arms[f"{n_fleets}f_{n_workers}w"] = {
                "events_per_sec": rep["events_per_sec"],
                "p50_ms": rep["p50_ms"],
                "p99_ms": rep["p99_ms"],
                "tick_failed": rep["tick_failed"],
                "uncertified": rep["uncertified"],
                "worker_events": rep["worker_events"],
            }
    out: dict = {
        "gateway": {
            "events_per_fleet": events,
            "fleet_size": fleet_size,
            "host_cores": os.cpu_count(),
            "arms": arms,
        }
    }
    big = max(fleet_counts)
    hi = max(worker_counts)
    base = arms.get(f"{big}f_1w", {}).get("events_per_sec")
    top = arms.get(f"{big}f_{hi}w", {})
    if base and top.get("events_per_sec"):
        out[f"gateway_events_per_sec_{big}f_{hi}w"] = top["events_per_sec"]
        out[f"gateway_p99_ms_{big}f_{hi}w"] = top["p99_ms"]
        out[f"gateway_scaling_{big}f_{hi}w"] = round(
            top["events_per_sec"] / base, 2
        )
    try:
        out["gateway"]["combine"] = _combine_arms(model, out)
    except Exception as e:  # pragma: no cover - defensive bench path
        out["gateway"]["combine_error"] = f"{type(e).__name__}: {e}"
    return out


def _federation_bench(model) -> dict:
    """federation section: process-backed workers vs the thread backend.

    Every arm replays the IDENTICAL seeded trace set through the same
    gateway code; only the worker backend differs. Thread workers share
    one GIL and one XLA runtime, so their ceiling is overlap, not
    parallelism; each ``worker_backend='process'`` worker hosts its
    schedulers in a fresh subprocess behind the unix-socket RPC — N
    workers, N GILs, N device runtimes. The headline is the 4-vs-1
    process-worker events/sec ratio (``federation_scaling_4w``), gated
    >= 3x absolute in `--against` ONLY when the host actually has >= 4
    cores (``federation_gate_armed`` — on a 2-core box the honest
    ceiling is ~2x and the gate would measure the machine). Every child
    runs its own compile ledger, and the timed phase must compile
    NOTHING in ANY child (``federation_warm_phase_compiles == 0``,
    absolute — the per-process twin of compile_warm_phase_count).
    """
    from distilp_tpu.gateway.loadgen import run_loadgen

    worker_counts = [
        int(x)
        for x in os.environ.get("DPERF_FED_WORKERS", "1,2,4").split(",")
        if x.strip()
    ]
    n_fleets = int(_env_num("DPERF_FED_FLEETS", 8))
    events = int(_env_num("DPERF_FED_EVENTS", 4))
    fleet_size = int(_env_num("DPERF_FED_M", 3))
    host_cores = os.cpu_count() or 1
    arms: dict = {}
    warm_compiles = 0
    for backend in ("thread", "process"):
        for n_workers in worker_counts:
            rep = run_loadgen(
                model,
                n_fleets=n_fleets,
                n_workers=n_workers,
                events_per_fleet=events,
                fleet_size=fleet_size,
                seed=0,
                k_candidates=[8, 10],
                mip_gap=MIP_GAP,
                worker_backend=backend,
                compile_ledger=(backend == "process"),
            )
            arm = {
                "events_per_sec": rep["events_per_sec"],
                "p50_ms": rep["p50_ms"],
                "p99_ms": rep["p99_ms"],
                "tick_failed": rep["tick_failed"],
                "uncertified": rep["uncertified"],
            }
            if backend == "process":
                pw = rep.get("proc_workers") or {}
                arm["proc_workers"] = pw
                warm_compiles += sum(
                    w.get("warm_phase_compiles") or 0 for w in pw.values()
                )
            arms[f"{backend}_{n_workers}w"] = arm
    hi = max(worker_counts)
    out: dict = {
        "federation": {
            "host_cores": host_cores,
            "fleets": n_fleets,
            "events_per_fleet": events,
            "fleet_size": fleet_size,
            "arms": arms,
        },
        "federation_warm_phase_compiles": warm_compiles,
        # The >=3x scaling floor only means something when the host can
        # physically run 4 solve processes at once.
        "federation_gate_armed": bool(host_cores >= 4 and hi >= 4),
    }
    base = arms.get("process_1w", {}).get("events_per_sec")
    top = arms.get(f"process_{hi}w", {}).get("events_per_sec")
    if base and top:
        out[f"federation_events_per_sec_{hi}w"] = top
        out[f"federation_scaling_{hi}w"] = round(top / base, 2)
    thread_top = arms.get(f"thread_{hi}w", {}).get("events_per_sec")
    if thread_top and top:
        out["federation_vs_thread"] = round(top / thread_top, 2)
    return out


def _recovery_bench(model) -> dict:
    """recovery section: MTTR under a kill-loop flood of the supervised
    process tier.

    One supervised process-backed gateway serves a seeded drift trace
    while ``DPERF_RECOVERY_KILLS`` ``kill -9``s land on the worker child
    at evenly spaced event indices. Every kill exercises the full
    recovery chain — crash detection, respawn with backoff, snapshot
    restore, WAL-tail replay — inline on the serving path, so the
    kill-adjacent event's latency IS the mean-time-to-recovery the
    supervisor's ``recovery_mttr_ms`` histogram records (dominated on a
    cold cache by the respawned child's jit re-compile; the histogram is
    the honest number either way).

    Headlines: ``recovery_mttr_p50_ms``/``recovery_mttr_p99_ms`` (p99
    regression-gated in ``--against``) and the exactly-once audit,
    absolute-gated — ``recovery_events_lost`` must be 0 (positive means
    the WAL lost accepted events, negative means replay double-applied)
    and ``recovery_cold_resumes`` must be 0 (every recovered shard
    resumes warm from its micro-snapshot, or the restore chain broke).
    ``recovery_goodput_dip`` rides along: worst kill-adjacent event
    latency over the healthy median — the depth of the serving dip a
    crash costs, the knob snapshot cadence tuning would move first.
    """
    from distilp_tpu.gateway import Gateway, make_fleet_from_spec
    from distilp_tpu.gateway.loadgen import (
        make_fleet_specs,
        make_loadgen_trace,
    )

    n_fleets = int(_env_num("DPERF_RECOVERY_FLEETS", 2))
    events = int(_env_num("DPERF_RECOVERY_EVENTS", 8))
    kills = int(_env_num("DPERF_RECOVERY_KILLS", 2))
    fleet_size = int(_env_num("DPERF_RECOVERY_M", 3))
    warmup = 2  # cold solve + first warm tick, same boundary as loadgen
    specs = make_fleet_specs(n_fleets, fleet_size=fleet_size, seed=0)
    items = make_loadgen_trace(specs, events + warmup, seed=0)
    gw = Gateway(
        n_workers=1,
        scheduler_kwargs={
            "mip_gap": MIP_GAP,
            "kv_bits": "4bit",
            "backend": "jax",
            "k_candidates": [8, 10],
        },
        worker_backend="process",
        supervise=True,
        snapshot_every=4,
    )
    lat_ms: list = []
    kill_lat_ms: list = []
    try:
        for fleet_id, spec in specs.items():
            gw.register_fleet(
                fleet_id, make_fleet_from_spec(fleet_id, spec), model
            )
        head = n_fleets * warmup
        for fleet_id, ev in items[:head]:
            gw.handle_event(fleet_id, ev)
        # Kills aim at fleet 0's CURRENT owner (the hook re-resolves per
        # fault: a respawn keeps the slot, a quarantine would re-home it).
        hook = gw.chaos_process_hook(next(iter(specs)))
        timed = items[head:]
        stride = max(1, len(timed) // (kills + 1)) if kills else len(timed)
        kill_at = {stride * (i + 1) for i in range(kills)}
        for i, (fleet_id, ev) in enumerate(timed):
            if i in kill_at:
                hook("child_kill", None)
            t0 = time.perf_counter()
            gw.handle_event(fleet_id, ev)
            ms = (time.perf_counter() - t0) * 1e3
            (kill_lat_ms if i in kill_at else lat_ms).append(ms)
        rec = gw.recovery_status()
    finally:
        gw.close()
    out: dict = {
        "recovery": {
            "fleets": n_fleets,
            "events_per_fleet": events,
            "kills": kills,
            "snapshot_every": 4,
            **rec,
        },
        "recovery_events_lost": rec.get("events_lost", 0),
        "recovery_cold_resumes": rec.get("cold_resumes", 0),
    }
    if rec.get("mttr_p50_ms") is not None:
        out["recovery_mttr_p50_ms"] = rec["mttr_p50_ms"]
        out["recovery_mttr_p99_ms"] = rec["mttr_p99_ms"]
    if lat_ms and kill_lat_ms:
        med = statistics.median(lat_ms)
        if med > 0:
            out["recovery_goodput_dip"] = round(max(kill_lat_ms) / med, 2)
    return out


def _combine_arms(model, out: dict) -> dict:
    """Cross-shard combiner arms: the same saturating 100-fleet open-loop
    flood served per-shard (coalesce only) and combined (coalesce +
    cross-shard batching), on identical worker counts. Both arms run past
    saturation (time_scale compresses the schedule far below capacity) so
    goodput IS capacity and the ratio is the dispatch-amortization win.
    Headlines: ``combine_events_per_sec_100f`` (gated as a regression
    metric in ``--against``) with ``combine_p99_ms_100f`` next to the
    per-shard p99 — the rate comparison only counts at equal latency —
    and ``combine_warm_phase_compiles``, gated ABSOLUTE at zero: the
    committed bucket policy (padded-M boundaries x quantized lane counts,
    warm_combine tracing the whole set incl. the root-warm signature
    flip) must never mint a ``_solve_batched`` executable after the warm
    boundary (per-shard fallback escalations are attributed separately
    under ``warm_phase_entries``, not charged to the policy). Bucket occupancy and padding waste ride along — the
    efficiency knobs a policy change would move first.

    Platform caveat (same spirit as the ``gateway_scaling`` core-count
    note): the >=3x target is a DISPATCH-AMORTIZATION win and only
    manifests where per-dispatch cost dominates — the tunneled TPU whose
    ~ms/op wire overhead ``tiny_put_ms`` tracks, where one 16-lane flush
    replaces 16 round trips and the per-lane static cache
    (``lane_static_to_device``; ``combine_static_hit`` must sit at 1.0
    warm) makes a flush re-ship only dynamic KBs. On a CPU host there is
    no wire: vmapped lanes cost near-linear FLOPs, the batch's only win
    is XLA intra-op threading that ``n_workers`` per-shard solves already
    exploit, and quantized phantom lanes burn real compute — so expect
    ``combine_vs_per_shard_100f`` well BELOW 1 on the 2-core CI box
    (~0.25x measured). The ratio is therefore compared, not
    absolute-gated; the regression gate rides the events/sec headline
    against its own platform-matched history, and the zero-compile gate
    is absolute everywhere."""
    from distilp_tpu.obs import compile_ledger
    from distilp_tpu.traffic import generate_openloop_schedule, run_openloop
    from distilp_tpu.traffic.arrivals import ArrivalConfig

    n_fleets = int(_env_num("DPERF_COMBINE_FLEETS", 100))
    n_workers = int(_env_num("DPERF_COMBINE_WORKERS", 2))
    cfg = ArrivalConfig(
        seed=17,
        duration_s=float(_env_num("DPERF_COMBINE_DURATION_S", 40.0)),
        base_rate=float(_env_num("DPERF_COMBINE_RATE", 10.0)),
        n_regions=4,
        burst_rate_per_region=0.05,
        burst_factor=3.0,
        burst_duration_s=5.0,
        fleet_size=int(_env_num("DPERF_GATEWAY_M", 3)),
        fleet_seed=900,
    )
    specs, items = generate_openloop_schedule(cfg, n_fleets)
    common = dict(
        time_scale=0.001,
        k_candidates=[8, 10],
        mip_gap=MIP_GAP,
        max_queue_depth=512,
        coalesce=True,
    )
    per_shard = run_openloop(model, specs, items, n_workers, **common)
    led_was_on = compile_ledger.current() is not None
    if not led_was_on:
        compile_ledger.enable()
    try:
        combined = run_openloop(
            model, specs, items, n_workers, combine=True, **common
        )
    finally:
        if not led_was_on:
            compile_ledger.disable()
    comb = combined.get("combine", {})
    res = {
        "n_fleets": n_fleets,
        "n_workers": n_workers,
        "offered": per_shard["offered"],
        "per_shard": {
            "events_per_sec": per_shard["goodput_eps"],
            "p99_ms": per_shard["p99_ms"],
            "failed": per_shard["failed"],
        },
        "combined": {
            "events_per_sec": combined["goodput_eps"],
            "p99_ms": combined["p99_ms"],
            "failed": combined["failed"],
            "batches": comb.get("batches"),
            "instances": comb.get("instances"),
            "bucket_occupancy_mean": comb.get("occupancy_mean"),
            "padding_waste_mean": comb.get("padding_waste_mean"),
            "combine_local": comb.get("combine_local"),
            "combine_stale": comb.get("combine_stale"),
            "combine_fallback": comb.get("combine_fallback"),
            "warmup": comb.get("warmup"),
        },
    }
    out[f"combine_events_per_sec_{n_fleets}f"] = combined["goodput_eps"]
    out[f"combine_p99_ms_{n_fleets}f"] = combined["p99_ms"]
    if per_shard["goodput_eps"]:
        out[f"combine_vs_per_shard_{n_fleets}f"] = round(
            combined["goodput_eps"] / per_shard["goodput_eps"], 2
        )
    # Absolute-gated at zero: compiles of the BUCKET executable after the
    # warm boundary. Total warm-phase events ride along in the nested res
    # (a per-shard fallback escalation — an uncertified lane re-solving
    # locally — is attributed there, not charged to the bucket policy).
    out["combine_warm_phase_compiles"] = (
        combined.get("compile", {}).get("warm_phase_combine_events")
    )
    res["combined"]["warm_phase_events"] = (
        combined.get("compile", {}).get("warm_phase_events")
    )
    res["combined"]["warm_phase_entries"] = (
        combined.get("compile", {}).get("warm_phase_entries")
    )
    occ = comb.get("occupancy_mean")
    waste = comb.get("padding_waste_mean")
    if occ is not None:
        out["combine_bucket_occupancy"] = round(occ, 2)
    if waste is not None:
        out["combine_padding_waste"] = round(waste, 3)
    return res


def _overload_bench(model) -> dict:
    """overload_* section: saturation behavior under OPEN-loop arrivals.

    Closed-loop replay (the gateway section above) cannot exceed
    capacity by construction; this section can, and measures what
    happens when it does. One warm 100-fleet gateway serves every arm
    (the ~100 cold solves are paid once):

    1. a closed-loop probe measures capacity C on the warm fleets;
    2. a ladder of open-loop arms at ``DPERF_OVERLOAD_LADDER`` x C finds
       ``overload_max_sustainable_eps`` — the highest offered rate whose
       p99 still meets the SLO (``DPERF_OVERLOAD_SLO_MS``; default
       max(250, 4 x closed-loop p50) recorded in the payload) — and
       ``overload_p999_ms``, the p99.9 at that rate;
    3. a flood at ``DPERF_OVERLOAD_FACTOR`` (10x) sustainable with
       admission ON (bounded queues, coalescing, degrade pressure) must
       hold ``overload_plateau_ratio`` = flood goodput / best ladder
       goodput >= 0.8 — a plateau, not a cliff — with every shed
       counted + flight-reconciled (``overload_shed_reconciled``).

    Ladder arms run admission-OFF on purpose: the sustainable-rate
    search characterizes the raw service; only the flood arm exercises
    the gate.
    """
    import asyncio

    from distilp_tpu.gateway.gateway import Gateway
    from distilp_tpu.gateway.traces import make_fleet_from_spec
    from distilp_tpu.obs import FlightRecorder
    from distilp_tpu.traffic import ArrivalConfig, generate_openloop_schedule
    from distilp_tpu.traffic.openloop import (
        _warmup,
        execute_openloop,
        measure_closed_loop,
        shed_violations,
    )

    n_fleets = int(_env_num("DPERF_OVERLOAD_FLEETS", 100))
    n_workers = int(_env_num("DPERF_OVERLOAD_WORKERS", 2))
    fleet_size = int(_env_num("DPERF_OVERLOAD_M", 3))
    arm_s = _env_num("DPERF_OVERLOAD_SECONDS", 6.0)
    slo_env = _env_num("DPERF_OVERLOAD_SLO_MS", 0.0)
    factor = _env_num("DPERF_OVERLOAD_FACTOR", 10.0)
    depth = int(_env_num("DPERF_OVERLOAD_DEPTH", 8))
    ladder = [
        float(x)
        for x in os.environ.get(
            "DPERF_OVERLOAD_LADDER", "0.5,0.75,1.0,1.25"
        ).split(",")
        if x.strip()
    ]

    def _cfg(seed: int, rate: float) -> ArrivalConfig:
        return ArrivalConfig(
            seed=seed,
            duration_s=arm_s,
            base_rate=rate,
            scenario="drift",
            fleet_size=fleet_size,
            fleet_seed=0,
        )

    flight = FlightRecorder(capacity=8192)
    gw = Gateway(
        n_workers=n_workers,
        scheduler_kwargs={
            "mip_gap": MIP_GAP,
            "kv_bits": "4bit",
            "backend": "jax",
            "k_candidates": [8, 10],
        },
        flight=flight,
    )
    try:
        specs, _ = generate_openloop_schedule(_cfg(1, 1.0), n_fleets)
        for fleet_id, spec in specs.items():
            gw.register_fleet(
                fleet_id, make_fleet_from_spec(fleet_id, spec), model
            )
        asyncio.run(_warmup(gw, specs, 2, seed=0))
        closed = measure_closed_loop(gw, specs, events_per_fleet=3, seed=1)
        capacity = max(1.0, closed["events_per_sec"])
        slo_ms = slo_env if slo_env > 0 else max(250.0, 4 * closed["p50_ms"])

        arms: dict = {}
        sustainable = None  # (offered_eps, p999_ms)
        best_goodput = 0.0
        for i, frac in enumerate(ladder):
            _, items = generate_openloop_schedule(
                _cfg(100 + i, capacity * frac), n_fleets
            )
            if not items:
                continue
            rep = asyncio.run(execute_openloop(gw, items))
            arms[f"{frac:g}x"] = {
                k: rep[k]
                for k in (
                    "offered", "offered_eps", "goodput_eps",
                    "p50_ms", "p99_ms", "p999_ms", "failed",
                )
            }
            best_goodput = max(best_goodput, rep["goodput_eps"])
            if rep["p99_ms"] <= slo_ms and (
                sustainable is None or rep["offered_eps"] > sustainable[0]
            ):
                sustainable = (rep["offered_eps"], rep["p999_ms"])
        if sustainable is None:
            # Even the lowest rung blew the SLO: report the rung itself
            # as the (non-)sustainable point rather than fabricating one.
            first = arms[min(arms, key=lambda k: arms[k]["offered_eps"])]
            sustainable = (first["offered_eps"], first["p999_ms"])

        # The flood: 10x sustainable, admission ON.
        gw.configure_admission(
            max_queue_depth=depth,
            coalesce=True,
            degrade_depth=max(1, depth // 2),
        )
        _, flood_items = generate_openloop_schedule(
            _cfg(997, sustainable[0] * factor), n_fleets
        )
        flood = asyncio.run(execute_openloop(gw, flood_items))
        violations = shed_violations(gw, flight)
        snap = gw.metrics_snapshot()
        plateau_ratio = (
            flood["goodput_eps"] / best_goodput if best_goodput else 0.0
        )
        out = {
            "overload": {
                "fleets": n_fleets,
                "workers": n_workers,
                "host_cores": os.cpu_count(),
                "arm_seconds": arm_s,
                "slo_ms": round(slo_ms, 3),
                "closed_loop_eps": capacity,
                "ladder": arms,
                "flood": {
                    **{
                        k: flood[k]
                        for k in (
                            "offered", "offered_eps", "served", "shed",
                            "goodput_eps", "p50_ms", "p99_ms", "p999_ms",
                            "failed", "max_queue_depth_seen",
                        )
                    },
                    "events_coalesced": snap["shard_totals"].get(
                        "events_coalesced", 0
                    ),
                    "admission_depth": depth,
                },
                "shed_violations": violations,
            },
            "overload_max_sustainable_eps": sustainable[0],
            "overload_p999_ms": sustainable[1],
            "overload_plateau_ratio": round(plateau_ratio, 3),
            "overload_sheds": flood["shed"],
            "overload_shed_reconciled": not violations,
        }
        return out
    finally:
        gw.close()


def _obs_bench(model) -> dict:
    """obs_* section: what does full observability cost the serving tier?

    Re-runs the 10-fleet loadgen arm per mode, INTERLEAVED (off/on/off/on
    — box drift lands on both modes evenly), with the "on" arms carrying
    a live tracer (64k-span ring, every event traced end to end) plus a
    background Prometheus scrape thread hitting the labeled exposition
    every 50 ms — the realistic sidecar load. ``DPERF_OBS_EVENTS``
    defaults to 40 measured events per fleet: the timed phase must be
    SECONDS, not the ~0.2 s that 5 events leave after warmup, or
    scheduler jitter on a 2-core box swamps the percent-level signal this
    section exists to measure (measured spread at 5 events: ±12% between
    identical arms). The reported ``obs_overhead_pct`` divides the MEDIAN
    events/sec of each mode; ``--against`` fails when it exceeds 5% — an
    ABSOLUTE gate, deliberately not relative to the reference capture:
    the instrumentation budget does not inflate just because last month's
    capture was slow.
    """
    from distilp_tpu.gateway.loadgen import run_loadgen
    from distilp_tpu.obs import Tracer

    n_fleets = int(_env_num("DPERF_OBS_FLEETS", 10))
    n_workers = int(_env_num("DPERF_OBS_WORKERS", 2))
    events = int(_env_num("DPERF_OBS_EVENTS", 40))
    repeats = max(1, int(_env_num("DPERF_OBS_REPEATS", 2)))

    def arm(obs_on: bool) -> dict:
        tracer = Tracer(capacity=65536) if obs_on else None
        rep = run_loadgen(
            model,
            n_fleets=n_fleets,
            n_workers=n_workers,
            events_per_fleet=events,
            fleet_size=int(_env_num("DPERF_GATEWAY_M", 3)),
            seed=0,
            k_candidates=[8, 10],
            mip_gap=MIP_GAP,
            tracer=tracer,
            prom_scrape_s=0.05 if obs_on else None,
        )
        if tracer is not None:
            rep["spans_recorded"] = len(tracer.spans())
        return rep

    runs = {"off": [], "on": []}
    for _ in range(repeats):
        runs["off"].append(arm(False))
        runs["on"].append(arm(True))
    med_off = statistics.median(r["events_per_sec"] for r in runs["off"])
    med_on = statistics.median(r["events_per_sec"] for r in runs["on"])
    overhead = (med_off - med_on) / med_off * 100.0 if med_off > 0 else 0.0
    return {
        "observability": {
            "fleets": n_fleets,
            "workers": n_workers,
            "events_per_fleet": events,
            "repeats": repeats,
            "events_per_sec_off": [r["events_per_sec"] for r in runs["off"]],
            "events_per_sec_on": [r["events_per_sec"] for r in runs["on"]],
            "p99_ms_off": statistics.median(r["p99_ms"] for r in runs["off"]),
            "p99_ms_on": statistics.median(r["p99_ms"] for r in runs["on"]),
            "spans_recorded": runs["on"][-1].get("spans_recorded", 0),
            "prom_scrape_errors": runs["on"][-1].get("prom_scrape_errors", 0),
        },
        # Two views of the same number: the compared/gated key is floored
        # at zero (a negative reading means the obs arm measured FASTER —
        # pure box noise — and a negative reference made every honest
        # ~0% capture print as a "regression" in --against diffs), while
        # the raw value stays reported so the noise itself is visible.
        # Gate semantics unchanged: the >5% ceiling check fires on exactly
        # the same captures either way.
        "obs_overhead_pct": round(max(0.0, overhead), 2),
        "obs_overhead_pct_raw": round(overhead, 2),
    }


def _slo_bench(model) -> dict:
    """slo_* section: alerting correctness + timeline-sampler cost.

    Alert correctness rides the committed diurnal+burst open-loop capture
    at time-scale 0.001 (the smoke-slo flood): a tiny bounded queue sheds
    ~90% of the schedule, the availability SLO's page tier must open on
    the burst and close during the settle window, and the open/close
    trail must reconcile (engine transitions == counters == flight
    records — the same record-by-record contract as sheds). Offline
    determinism replays the committed synthetic timeline against the
    committed spec and compares to the committed expected sequence —
    a pure function, so any diff is evaluator drift, not noise. The
    overhead arm interleaves the 10-fleet loadgen with and without a
    50 ms timeline sampler (one metrics round trip per worker per tick,
    the realistic cost); ``slo_overhead_pct`` is floored at zero like
    the other obs overheads (raw alongside) and gated <= 5% absolute.
    """
    from distilp_tpu.gateway.loadgen import run_loadgen
    from distilp_tpu.obs import (
        SignalsPayload,
        SLOConfig,
        SLOEngine,
        synthesize_overload_timeline,
    )
    from distilp_tpu.obs.flight import FlightRecorder
    from distilp_tpu.traffic import read_openloop_trace, run_openloop

    out: dict = {"slo": {}}

    # -- (1) live alert fire/clear on the committed overload capture -------
    spec_path = REPO / "tests" / "traces" / "slo_live_spec.json"
    capture = REPO / "tests" / "traces" / "openloop_diurnal_burst.jsonl"
    specs, items = read_openloop_trace(capture)
    flight = FlightRecorder(capacity=max(256, 2 * len(items)))
    flood = run_openloop(
        model,
        specs,
        items,
        n_workers=int(_env_num("DPERF_SLO_WORKERS", 2)),
        time_scale=0.001,
        k_candidates=[8, 10],
        mip_gap=MIP_GAP,
        max_queue_depth=2,
        flight=flight,
        slo_config=SLOConfig.from_json(spec_path),
        settle_s=_env_num("DPERF_SLO_SETTLE_S", 3.0),
    )
    slo_rep = flood.get("slo", {})
    events = slo_rep.get("events", [])
    page_open = [
        e for e in events
        if e["severity"] == "page" and e["state"] == "open"
    ]
    page_close = [
        e for e in events
        if e["severity"] == "page" and e["state"] == "close"
    ]
    flight_alerts = [
        r for r in flight.snapshot("slo") if r.get("kind") == "slo_alert"
    ]
    # Reconcile ALL severities against the counters (the counters count
    # every tier; comparing page-only would spuriously fail the moment
    # the live spec grows a warn tier) — same shape as overload --check.
    opened_all = sum(1 for e in events if e["state"] == "open")
    closed_all = sum(1 for e in events if e["state"] == "close")
    reconciled = (
        len(flight_alerts) == len(events)
        and opened_all == slo_rep.get("alerts_opened")
        and closed_all == slo_rep.get("alerts_closed")
    )
    out["slo"]["flood"] = {
        "offered": flood["offered"],
        "shed": flood["shed"],
        "alerts_opened": slo_rep.get("alerts_opened", 0),
        "alerts_closed": slo_rep.get("alerts_closed", 0),
        "timeline_samples": slo_rep.get("timeline_samples", 0),
        "events": events,
        "reconciled": reconciled,
    }
    out["slo_alerts_fired"] = len(page_open)
    out["slo_alerts_ok"] = bool(page_open) and bool(page_close) and reconciled

    # -- (2) offline determinism vs the committed fixtures -----------------
    tl = synthesize_overload_timeline()
    committed = (
        REPO / "tests" / "traces" / "slo_timeline_overload.jsonl"
    ).read_text()
    config = SLOConfig.from_json(
        REPO / "tests" / "traces" / "slo_overload_spec.json"
    )
    replayed = SLOEngine(config, tl).replay(step_s=0.1)
    expect = json.loads(
        (REPO / "tests" / "traces" / "slo_expected_alerts.json").read_text()
    )
    bucket_s = float(expect["bucket_s"])
    t0 = tl.bounds()[0]
    got = [
        {
            "slo": e["slo"], "severity": e["severity"],
            "state": e["state"], "bucket": int((e["t"] - t0) / bucket_s),
        }
        for e in replayed
    ]
    deterministic = tl.to_jsonl() == committed and got == expect["events"]
    out["slo"]["offline"] = {
        "transitions": len(replayed),
        "timeline_regenerated_byte_exact": tl.to_jsonl() == committed,
        "expected_sequence_match": got == expect["events"],
    }
    out["slo_replay_deterministic"] = deterministic

    # -- (3) /signals schema (the federation consumer contract) ------------
    signals = slo_rep.get("signals")
    try:
        SignalsPayload.model_validate(signals)
        out["slo_signals_schema_ok"] = True
    except Exception as e:
        out["slo_signals_schema_ok"] = False
        out["slo"]["signals_error"] = f"{type(e).__name__}: {e}"

    # -- (4) sampler overhead, interleaved off/on --------------------------
    n_fleets = int(_env_num("DPERF_SLO_FLEETS", 10))
    n_workers = int(_env_num("DPERF_SLO_WORKERS", 2))
    events_pf = int(_env_num("DPERF_SLO_EVENTS", 40))
    repeats = max(1, int(_env_num("DPERF_SLO_REPEATS", 2)))

    def arm(sampled: bool) -> dict:
        return run_loadgen(
            model,
            n_fleets=n_fleets,
            n_workers=n_workers,
            events_per_fleet=events_pf,
            fleet_size=int(_env_num("DPERF_GATEWAY_M", 3)),
            seed=0,
            k_candidates=[8, 10],
            mip_gap=MIP_GAP,
            timeline_period_s=0.05 if sampled else None,
        )

    runs = {"off": [], "on": []}
    for _ in range(repeats):
        runs["off"].append(arm(False))
        runs["on"].append(arm(True))
    med_off = statistics.median(r["events_per_sec"] for r in runs["off"])
    med_on = statistics.median(r["events_per_sec"] for r in runs["on"])
    overhead = (med_off - med_on) / med_off * 100.0 if med_off > 0 else 0.0
    out["slo"]["overhead"] = {
        "fleets": n_fleets,
        "workers": n_workers,
        "events_per_fleet": events_pf,
        "repeats": repeats,
        "events_per_sec_off": [r["events_per_sec"] for r in runs["off"]],
        "events_per_sec_on": [r["events_per_sec"] for r in runs["on"]],
        "timeline_samples": runs["on"][-1].get("timeline_samples", 0),
        "timeline_sample_errors": runs["on"][-1].get(
            "timeline_sample_errors", 0
        ),
    }
    # Floored like obs_overhead_pct: negative = box noise, raw alongside.
    out["slo_overhead_pct"] = round(max(0.0, overhead), 2)
    out["slo_overhead_pct_raw"] = round(overhead, 2)
    return out


def _twin_bench(model, base_devs) -> dict:
    """twin_* section: MC evals/sec + objective-vs-twin rank agreement."""
    from distilp_tpu.solver import halda_solve_per_k
    from distilp_tpu.twin import rank_agreement, robustness_report

    devs = [d.model_copy(deep=True) for d in base_devs]
    per_k = halda_solve_per_k(devs, model, mip_gap=MIP_GAP, kv_bits="4bit")
    ra = rank_agreement(devs, model, per_k, kv_bits="4bit")
    best = min(per_k, key=lambda r: r.obj_value)
    samples = 1024
    mc = dict(samples=samples, seed=0, kv_bits="4bit", dropout_p=0.05)
    robustness_report(devs, model, best, **mc)  # compile the kernel
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        robustness_report(devs, model, best, **mc)
        times.append((time.perf_counter() - t0) * 1e3)
    ms = statistics.median(times)
    return {
        "twin_mc_samples": samples,
        "twin_mc_ms": round(ms, 3),
        "twin_mc_evals_per_sec": round(samples * 1000.0 / ms, 1),
        "twin_rank_agreement": round(ra["spearman"], 4),
        "twin_rank_inversions": ra["pairwise_inversions"],
        "twin_k_candidates": len(per_k),
    }


def _speculation_bench(model) -> dict:
    """speculation section: cache-hit serving vs the plain tick path.

    Both arms replay the IDENTICAL bundled seeded trace (burst: correlated
    multi-device spikes that relax exactly; flap: oscillating up/down
    drift on a channel subset), interleaved off/on so box drift lands on
    both evenly. Latency per tick is the scheduler's ``last_serve_ms``
    (event ingress -> placement published): the speculative presolve runs
    AFTER publish, off the serving path, and is reported separately as
    ``presolve_overhead_pct`` of the arm's wall clock rather than billed
    to event->placement. The first ``DPERF_SPEC_WARMUP`` (default 12)
    events are excluded from the percentiles on BOTH arms — they cover
    jit compiles and the deterministic cold-bank misses while the
    forecaster learns the trace's two states; the hit-rate counters are
    reported over the WHOLE trace, warmup included, so the miss cost is
    never hidden. The gate (``--against``): ``spec_hit_rate`` may not
    regress, and the absolute contract p99(on) < p99(off) with a nonzero
    hit count must hold on the burst trace.
    """
    from distilp_tpu.sched import Scheduler, read_trace
    from distilp_tpu.sched.metrics import _quantile
    from distilp_tpu.utils import make_synthetic_fleet

    repeats = max(1, int(_env_num("DPERF_SPEC_REPEATS", 2)))
    warmup = int(_env_num("DPERF_SPEC_WARMUP", 12))
    arms: dict = {}
    for trace_name in ("spec_burst", "spec_flap"):
        events = read_trace(REPO / "tests" / "traces" / f"{trace_name}.jsonl")
        runs: dict = {"off": [], "on": []}
        for _ in range(repeats):
            for mode in ("off", "on"):  # interleaved: off/on/off/on...
                devs = make_synthetic_fleet(4, seed=11)
                sched = Scheduler(
                    devs, model, mip_gap=MIP_GAP, kv_bits="4bit",
                    backend="jax", k_candidates=[8, 10],
                    speculative=(mode == "on"),
                )
                lat = []
                full_lat = []  # handle() wall: presolve INCLUDED
                t0 = time.perf_counter()
                for i, ev in enumerate(events):
                    t_ev = time.perf_counter()
                    view = sched.handle(ev)
                    ev_ms = (time.perf_counter() - t_ev) * 1e3
                    # Freshly published ticks only: a failed/quarantined
                    # tick never reaches _publish, so last_serve_ms would
                    # silently re-report the PREVIOUS tick's latency.
                    if i >= warmup and view.events_behind == 0:
                        lat.append(sched.last_serve_ms)
                        full_lat.append(ev_ms)
                wall_ms = (time.perf_counter() - t0) * 1e3
                snap = sched.metrics_snapshot()
                spec = sched.speculation_snapshot()
                srt = sorted(lat)
                runs[mode].append(
                    {
                        "p50_ms": _quantile(srt, 0.50),
                        "p99_ms": _quantile(srt, 0.99),
                        # Full handle() wall percentile: the presolve a
                        # miss tick runs after publish delays the NEXT
                        # event on this (synchronous) thread — the gated
                        # serve-path p99 cannot see that, so report it
                        # alongside instead of letting it hide.
                        "p99_incl_presolve_ms": _quantile(
                            sorted(full_lat), 0.99
                        ),
                        "wall_ms": wall_ms,
                        "hit_rate": spec["hit_rate"],
                        "hits": spec["hits"],
                        "misses": spec["misses"],
                        "presolved": spec["presolved"],
                        "presolve_ms": snap["latency"]
                        .get("spec_presolve_ms", {})
                        .get("total_ms", 0.0),
                        "hit_p99_ms": snap["latency"]
                        .get("spec_hit_ms", {})
                        .get("p99_ms"),
                        "failed": snap["counters"].get("tick_failed", 0),
                    }
                )
                sched.close()

        def med(key: str, mode: str):
            vals = [r[key] for r in runs[mode] if r[key] is not None]
            return statistics.median(vals) if vals else None

        # Overhead from the LAST on-repeat: the first pays the scenario
        # batch's one-off jit compile, which belongs to deployment, not to
        # the steady-state presolve bill this number reports.
        last_on = runs["on"][-1]
        arms[trace_name] = {
            "events": len(events),
            "warmup": warmup,
            "repeats": repeats,
            "p50_off_ms": round(med("p50_ms", "off"), 3),
            "p50_on_ms": round(med("p50_ms", "on"), 3),
            "p99_off_ms": round(med("p99_ms", "off"), 3),
            "p99_on_ms": round(med("p99_ms", "on"), 3),
            "p99_on_incl_presolve_ms": round(
                med("p99_incl_presolve_ms", "on"), 3
            ),
            "hit_rate": round(med("hit_rate", "on"), 4),
            "hits": runs["on"][-1]["hits"],
            "misses": runs["on"][-1]["misses"],
            "presolved": runs["on"][-1]["presolved"],
            "spec_p99_hit_ms": (
                round(med("hit_p99_ms", "on"), 3)
                if med("hit_p99_ms", "on") is not None
                else None
            ),
            "presolve_overhead_pct": (
                round(100.0 * last_on["presolve_ms"] / last_on["wall_ms"], 2)
                if last_on["wall_ms"]
                else None
            ),
            "failed_ticks": runs["on"][-1]["failed"],
        }
    burst = arms["spec_burst"]
    return {
        "speculation": arms,
        "spec_hit_rate": burst["hit_rate"],
        "spec_p99_hit_ms": burst["spec_p99_hit_ms"],
        "spec_p99_on_ms": burst["p99_on_ms"],
        "spec_p99_off_ms": burst["p99_off_ms"],
    }


def _convergence_bench(model, base_devs) -> dict:
    """convergence section: solver-interior telemetry on the north star.

    Per LP engine (the ipm default and pdhg forced onto the same 16-device
    instance), solve the golden fixture with ``convergence={}`` and report
    the SearchTrace facts the solver-scaling work tunes against: rounds
    and LP iterations to certify, Halpern restart counts, the final
    certified gap. The overhead arm interleaves untraced/traced repeats
    (median of each; box drift lands on both) — ``conv_overhead_pct`` is
    floored at zero like ``obs_overhead_pct`` (raw value alongside) and
    gated at <= 5% absolute by ``--against``. One fleet_scale arm also
    carries a ``conv`` block (see ``_FLEET_SCALE_SRC``), so the M=512+
    restart/iteration trail rides the same capture.
    """
    from distilp_tpu.obs.convergence import build_search_trace
    from distilp_tpu.solver import halda_solve

    devs = [d.model_copy(deep=True) for d in base_devs]
    kw = dict(mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
    out: dict = {"convergence": {}}
    overheads: list[float] = []
    for engine in ("ipm", "pdhg"):
        conv: dict = {}
        halda_solve(devs, model, lp_backend=engine, convergence=conv, **kw)
        halda_solve(devs, model, lp_backend=engine, **kw)  # compile untraced
        plain_ms: list[float] = []
        traced_ms: list[float] = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            halda_solve(devs, model, lp_backend=engine, **kw)
            plain_ms.append((time.perf_counter() - t0) * 1e3)
            conv = {}
            t0 = time.perf_counter()
            halda_solve(
                devs, model, lp_backend=engine, convergence=conv, **kw
            )
            traced_ms.append((time.perf_counter() - t0) * 1e3)
        trace = build_search_trace(conv)
        med_plain = statistics.median(plain_ms)
        med_traced = statistics.median(traced_ms)
        arm_overhead = (
            (med_traced - med_plain) / med_plain * 100.0 if med_plain else 0.0
        )
        overheads.append(arm_overhead)
        out["convergence"][engine] = {
            "certified": trace.certified,
            "final_gap": trace.final_gap,
            "rounds": len(trace.rounds),
            "lp_iters": trace.lp_iters_executed,
            "rounds_to_certify": trace.rounds_to_certify,
            "iters_to_certify": trace.iters_to_certify,
            "restarts": trace.restarts,
            "untraced_ms": round(med_plain, 3),
            "traced_ms": round(med_traced, 3),
            "overhead_pct_raw": round(arm_overhead, 2),
        }
        if trace.iters_to_certify is not None:
            out[f"conv_{engine}_iters_to_certify"] = trace.iters_to_certify
        if engine == "pdhg":
            out["conv_pdhg_restarts"] = trace.restarts
    worst = max(overheads) if overheads else 0.0
    out["conv_overhead_pct"] = round(max(0.0, worst), 2)
    out["conv_overhead_pct_raw"] = round(worst, 2)
    return out


_COMPILE_COLD_SRC = r"""
import json
from distilp_tpu.obs import compile_ledger as cl
led = cl.enable()
from distilp_tpu.common import load_model_profile
from distilp_tpu.solver import halda_solve
from distilp_tpu.utils import make_synthetic_fleet

model = load_model_profile("tests/profiles/llama_3_70b/online/model_profile.json")
devs = make_synthetic_fleet(4, seed=11)
res = halda_solve(devs, model, k_candidates=[8, 10], mip_gap=1e-3,
                  kv_bits="4bit", backend="jax")
c = led.counters()
print("DPERF_COMPILE", json.dumps({
    "certified": bool(res.certified),
    "compiles": c["compiles"],
    "cache_hits": c["compile_cache_hits"],
    "cache_misses": c["compile_cache_misses"],
    "hit_rate": led.cache_hit_rate(),
    "unattributed": c["unattributed_compiles"],
}))
"""


def _compile_bench(model) -> dict:
    """compile section: ledger overhead, the zero-recompile warm gate,
    and the persistent-cache hit rate in cold processes.

    (1) The 10-fleet loadgen arm re-runs ledger-ON vs ledger-OFF,
    interleaved (ON FIRST so the process's true cold compiles land in a
    ledgered arm): ``compile_overhead_pct`` is the events/sec cost of
    full compile attribution, gated <= 5% absolute like the other obs
    ceilings. (2) The headline gate: across every ON arm's TIMED phase
    (post-warmup steady-state warm/spec serving) the ledger must record
    ZERO compile events — ``compile_warm_phase_count == 0`` in
    ``--against``; a warm tick that silently recompiles is exactly the
    tail-latency bug this section exists to catch. (3) Cold-process
    children (wedge-contained) share one throwaway persistent-cache dir:
    the first populates it (ledger classifies misses), the second is
    served from it — ``compile_cache_hit_rate`` is the second child's
    ledger-classified hit rate.
    """
    from distilp_tpu.gateway.loadgen import run_loadgen

    n_fleets = int(_env_num("DPERF_COMPILE_FLEETS", 10))
    n_workers = int(_env_num("DPERF_COMPILE_WORKERS", 2))
    events = int(_env_num("DPERF_COMPILE_EVENTS", 40))
    repeats = max(1, int(_env_num("DPERF_COMPILE_REPEATS", 2)))

    def arm(led_on: bool) -> dict:
        return run_loadgen(
            model,
            n_fleets=n_fleets,
            n_workers=n_workers,
            events_per_fleet=events,
            fleet_size=int(_env_num("DPERF_GATEWAY_M", 3)),
            seed=0,
            k_candidates=[8, 10],
            mip_gap=MIP_GAP,
            compile_ledger=led_on,
        )

    runs = {"off": [], "on": []}
    for _ in range(repeats):
        # ON first: the first arm of the whole section pays the process's
        # cold compiles, and they must land in a LEDGERED arm's warmup so
        # cold_compiles is the real count, not zero-by-jit-cache.
        runs["on"].append(arm(True))
        runs["off"].append(arm(False))
    med_off = statistics.median(r["events_per_sec"] for r in runs["off"])
    med_on = statistics.median(r["events_per_sec"] for r in runs["on"])
    overhead = (med_off - med_on) / med_off * 100.0 if med_off > 0 else 0.0
    warm_total = sum(
        r["compile"]["warm_phase_compiles"] for r in runs["on"]
    )
    unregistered = sorted(
        {e for r in runs["on"] for e in r["compile"]["unregistered"]}
    )
    out: dict = {
        "compile": {
            "fleets": n_fleets,
            "workers": n_workers,
            "events_per_fleet": events,
            "repeats": repeats,
            "events_per_sec_off": [r["events_per_sec"] for r in runs["off"]],
            "events_per_sec_on": [r["events_per_sec"] for r in runs["on"]],
            "cold_compiles_first_arm": runs["on"][0]["compile"][
                "cold_compiles"
            ],
            "warm_phase_compiles_per_arm": [
                r["compile"]["warm_phase_compiles"] for r in runs["on"]
            ],
            "warm_phase_entries": sorted(
                {e for r in runs["on"] for e in r["compile"]["warm_entries"]}
            ),
            "unregistered_entries": unregistered,
        },
        "compile_cold_count": runs["on"][0]["compile"]["cold_compiles"],
        # THE gate: steady-state warm/spec serving never compiles.
        "compile_warm_phase_count": warm_total,
        "compile_overhead_pct": round(max(0.0, overhead), 2),
        "compile_overhead_pct_raw": round(overhead, 2),
    }

    # -- persistent-cache hit rate, fresh processes ------------------------
    import tempfile

    with tempfile.TemporaryDirectory(prefix="distilp-ledger-") as cache_dir:
        env = dict(os.environ)
        env["DISTILP_COMPILE_CACHE"] = cache_dir
        cold_children = {}
        for key in ("populate", "cached"):
            rc, stdout, stderr = run_contained(
                [sys.executable, "-c", _COMPILE_COLD_SRC],
                timeout_s=max(120.0, _env_num("DPERF_COLD_TIMEOUT", 300)),
                env=env,
                cwd=str(REPO),
            )
            line = next(
                (
                    ln for ln in stdout.splitlines()
                    if ln.startswith("DPERF_COMPILE ")
                ),
                None,
            )
            if rc != 0 or line is None:
                out["compile"]["cold_process_error"] = (
                    f"{key} child rc={rc}: {stderr.strip()[-300:]}"
                )
                return out
            cold_children[key] = json.loads(line[len("DPERF_COMPILE "):])
        out["compile"]["cold_process"] = cold_children
        out["compile_cache_hit_rate"] = cold_children["cached"]["hit_rate"]
    return out


def _memory_bench(model) -> dict:
    """memory section: ledger overhead, the zero-leak warm gate, and the
    analytic-model calibration.

    (1) The 10-fleet loadgen arm re-runs ledger-ON vs ledger-OFF,
    interleaved (ON FIRST so the once-per-entry AOT analyses land in a
    ledgered arm's warmup): ``memory_overhead_pct`` is the events/sec
    cost of dispatch counting + throttled watermark sampling, gated
    <= 5% absolute like the other obs ceilings. (2) The headline gate:
    a dedicated scheduler per LP engine runs >= 100 steady-state warm
    drift ticks with the ledger live — live-array bytes must show ZERO
    net growth (``mem_leak_bytes_<engine>``, absolute in ``--against``;
    a warm tick that pins arrays is tomorrow's OOM). (3) Calibration:
    ``halda_solve`` at two M sizes per engine, each under a FRESH ledger
    so ``solver._solve_packed`` re-analyzes at that size — the measured
    XLA temp bytes over the ops/memmodel analytic proxy is the
    calibration ratio. The proxy models the dominant working-set term,
    so the ratio is a constant-factor > 1 that must sit inside a sanity
    band AND be STABLE across M (ratio_large/ratio_small near 1): a
    proxy that scales wrongly with M would steer fleet_scale's skip
    decisions (and ROADMAP item 3's per-shard sizing) off a cliff.
    Measured this box: ipm ratio ~7-8, pdhg ~58-68, scaling 0.85-0.88.
    """
    from distilp_tpu.gateway.loadgen import run_loadgen
    from distilp_tpu.obs import memory as obs_memory
    from distilp_tpu.ops import memmodel
    from distilp_tpu.sched import Scheduler
    from distilp_tpu.sched.sim import generate_trace
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.utils import make_synthetic_fleet

    n_fleets = int(_env_num("DPERF_MEM_FLEETS", 10))
    n_workers = int(_env_num("DPERF_MEM_WORKERS", 2))
    events = int(_env_num("DPERF_MEM_EVENTS", 40))
    repeats = max(1, int(_env_num("DPERF_MEM_REPEATS", 2)))
    leak_ticks = max(100, int(_env_num("DPERF_MEM_LEAK_TICKS", 110)))
    cal_ms = [
        int(x)
        for x in os.environ.get("DPERF_MEM_MS", "16,48").split(",")
        if x.strip()
    ][:2]

    # -- (1) overhead, interleaved ----------------------------------------
    def arm(mem_on: bool) -> dict:
        return run_loadgen(
            model,
            n_fleets=n_fleets,
            n_workers=n_workers,
            events_per_fleet=events,
            fleet_size=int(_env_num("DPERF_GATEWAY_M", 3)),
            seed=0,
            k_candidates=[8, 10],
            mip_gap=MIP_GAP,
            memory_ledger=mem_on,
        )

    runs = {"off": [], "on": []}
    for _ in range(repeats):
        runs["on"].append(arm(True))
        runs["off"].append(arm(False))
    med_off = statistics.median(r["events_per_sec"] for r in runs["off"])
    med_on = statistics.median(r["events_per_sec"] for r in runs["on"])
    overhead = (med_off - med_on) / med_off * 100.0 if med_off > 0 else 0.0
    arm_leaks = [
        (r["mem"]["leak"] or {}).get("growth_bytes") for r in runs["on"]
    ]
    out: dict = {
        "memory": {
            "fleets": n_fleets,
            "workers": n_workers,
            "events_per_fleet": events,
            "repeats": repeats,
            "events_per_sec_off": [r["events_per_sec"] for r in runs["off"]],
            "events_per_sec_on": [r["events_per_sec"] for r in runs["on"]],
            "loadgen_leak_bytes_per_arm": arm_leaks,
            "entries_analyzed_first_arm": runs["on"][0]["mem"][
                "entries_analyzed"
            ],
            "watermarks_first_arm": runs["on"][0]["mem"]["watermarks"],
        },
        "memory_overhead_pct": round(max(0.0, overhead), 2),
        "memory_overhead_pct_raw": round(overhead, 2),
    }

    # -- (2) the zero-leak warm gate, per engine ---------------------------
    leak_max = None
    for engine in ("ipm", "pdhg"):
        fleet = make_synthetic_fleet(4, seed=11)
        trace = generate_trace(
            "drift", leak_ticks + 5, seed=5, base_fleet=fleet
        )
        led = obs_memory.enable(obs_memory.MemoryLedger())
        try:
            sched = Scheduler(
                fleet, model, mip_gap=MIP_GAP, kv_bits="4bit",
                backend="jax", k_candidates=[8, 10], lp_backend=engine,
                speculative=True,
            )
            for ev in trace[:5]:  # cold + warm layouts + scenario batch
                sched.handle(ev)
            led.mark_warm()
            for ev in trace[5:]:
                sched.handle(ev)
            led.sample(force=True)
            leak = led.leak_report()
            sched.close()
        finally:
            obs_memory.disable()
        growth = leak["growth_bytes"] if leak else None
        out[f"mem_leak_bytes_{engine}"] = growth
        out["memory"][f"leak_{engine}"] = leak
        if growth is not None:
            leak_max = growth if leak_max is None else max(leak_max, growth)
    # THE gate: steady-state warm serving pins nothing (both engines).
    out["memory_leak_bytes"] = leak_max

    # -- (3) analytic-model calibration ------------------------------------
    cal: dict = {"entry": "solver._solve_packed", "sizes": {}}
    ratios: dict = {}
    ok = True
    for M in cal_ms:
        row: dict = {}
        for engine in ("ipm", "pdhg"):
            led = obs_memory.enable(obs_memory.MemoryLedger())
            try:
                halda_solve(
                    make_synthetic_fleet(M, seed=123), model,
                    mip_gap=MIP_GAP, kv_bits="4bit", backend="jax",
                    lp_backend=engine,
                )
                rec = led.analyses.get("solver._solve_packed") or {}
                mem = rec.get("memory") or {}
                temp = mem.get("temp_bytes")
            finally:
                obs_memory.disable()
            proxy = memmodel.peak_bytes(M, engine)
            ratio = round(temp / proxy, 3) if temp else None
            row[engine] = {
                "measured_temp_bytes": temp,
                "analytic_proxy_bytes": proxy,
                "ratio": ratio,
                "flops": rec.get("flops"),
            }
            ratios.setdefault(engine, []).append(ratio)
        cal["sizes"][str(M)] = row
    for engine, rs in ratios.items():
        rs = [r for r in rs if r is not None]
        if len(rs) < 2:
            # A backend that reports no memory stats cannot calibrate —
            # record the absence, do not fabricate a verdict.
            ok = None if ok is True else ok
            continue
        out[f"mem_calibration_ratio_{engine}"] = rs[-1]
        scaling = round(rs[-1] / rs[0], 3) if rs[0] else None
        cal[f"scaling_{engine}"] = scaling
        # Sanity band: the proxy is the dominant-term model, so measured
        # temp must sit ABOVE it but within two orders; and the ratio
        # must be stable across M (the proxy's scaling law is the part
        # fleet_scale's skip decision actually leans on).
        if not (1.0 <= rs[-1] <= 100.0) or scaling is None or not (
            0.25 <= scaling <= 4.0
        ):
            ok = False
    cal["ms"] = cal_ms
    out["memory"]["calibration"] = cal
    out["mem_calibration_ok"] = ok
    return out


_COLD_PROCESS_SRC = r"""
import json, time
t0 = time.perf_counter()
from distilp_tpu.common import load_model_profile
from distilp_tpu.solver import halda_solve
from distilp_tpu.utils import make_synthetic_fleet

model = load_model_profile("tests/profiles/llama_3_70b/online/model_profile.json")
devs = make_synthetic_fleet(16, seed=123)
res = halda_solve(devs, model, mip_gap=1e-3, kv_bits="4bit", backend="jax")
print("DPERF_COLD", json.dumps(
    {"ms": (time.perf_counter() - t0) * 1e3, "certified": res.certified}
))
"""


def _cold_process_bench() -> dict:
    """cold_process_* section: the restart cost of the serving stack.

    A "real-time re-placement" service restarts (deploys, crashes, host
    churn), and a fresh process pays import + jit-compile + first solve
    before it can serve. Two FRESH subprocesses each solve the 16-device
    north star cold, sharing one throwaway ``DISTILP_COMPILE_CACHE``
    directory: the first populates the persistent compilation cache (its
    time = today's restart cost), the second restarts against it (the
    restart cost the env-gated cache buys). Timed inside the child from
    first import to solved result — interpreter startup is not the
    solver's bill. Wedge-contained like every other subprocess probe.
    """
    import tempfile

    out: dict = {}
    with tempfile.TemporaryDirectory(prefix="distilp-cache-") as cache_dir:
        env = dict(os.environ)
        env["DISTILP_COMPILE_CACHE"] = cache_dir
        for key in ("cold_process_ms", "cold_process_cached_ms"):
            rc, stdout, stderr = run_contained(
                [sys.executable, "-c", _COLD_PROCESS_SRC],
                timeout_s=max(120.0, _env_num("DPERF_COLD_TIMEOUT", 300)),
                env=env,
                cwd=str(REPO),
            )
            line = next(
                (
                    ln for ln in stdout.splitlines()
                    if ln.startswith("DPERF_COLD ")
                ),
                None,
            )
            if rc != 0 or line is None:
                out["cold_process_error"] = (
                    f"{key} child rc={rc}: {stderr.strip()[-300:]}"
                )
                return out
            got = json.loads(line[len("DPERF_COLD "):])
            if not got.get("certified"):
                out["cold_process_error"] = f"{key} child solved uncertified"
                return out
            out[key] = round(got["ms"], 1)
    if out.get("cold_process_cached_ms"):
        out["cold_process_cache_speedup"] = round(
            out["cold_process_ms"] / out["cold_process_cached_ms"], 2
        )
    return out


# Fleet-scale engine comparison. One wedge-contained child per (M, engine):
# a fresh process is the only honest peak-memory meter (ru_maxrss), and an
# engine that cannot fit or finish must cost a timeout, not the bench. The
# child stretches the 70B profile's typical-layer scalars to L=2M layers —
# HALDA places every device (w_i >= 1), so a fleet-scale instance needs a
# model at least as deep as the fleet; 2M keeps two k candidates feasible
# so the sweep still searches. Engines get the SAME instance, gap and
# first-order budget (recorded in the section), so the per-M solve_ms pair
# is a like-for-like engine comparison, not a knob comparison.
_FLEET_SCALE_SRC = r"""
import json, resource, sys, time
M = int(sys.argv[1]); engine = sys.argv[2]
gap = float(sys.argv[3]); pdhg_iters = int(sys.argv[4])
shards = int(sys.argv[5])
dtype = None if sys.argv[6] == "none" else sys.argv[6]
do_conv = len(sys.argv) > 7 and sys.argv[7] == "conv"
if shards > 1:
    # Before ANY backend touch: a CPU host exposes one device otherwise
    # and the row mesh cannot form (utils.shardcompat, same as the CLI).
    from distilp_tpu.utils import shardcompat
    shardcompat.force_host_devices(shards)
from distilp_tpu.common import load_model_profile
from distilp_tpu.solver import halda_solve
from distilp_tpu.utils import make_synthetic_fleet, stretch_model_for_fleet

base = load_model_profile(
    "tests/profiles/llama_3_70b/online/model_profile.json"
)
model = stretch_model_for_fleet(base, M)
devs = make_synthetic_fleet(M, seed=123)
kw = {"pdhg_iters": pdhg_iters} if engine == "pdhg" else {}
if shards > 1:
    kw["mesh_shards"] = shards
if dtype is not None:
    kw["pdhg_dtype"] = dtype
led = None
if shards > 1:
    # Sharded arms run under the memory ledger so the per-shard analytic
    # prediction (ops/memmodel.pdhg_shard_peak_bytes) is checked against
    # XLA's measured temp bytes for THIS executable — the PR 15
    # calibration contract extended to the mesh. The ledger's per-entry
    # analysis costs <5% (bench memory section gate), accepted here
    # rather than paying a second fleet-scale solve.
    from distilp_tpu.obs import memory as obs_memory
    led = obs_memory.enable(obs_memory.MemoryLedger())
tm = {}
t0 = time.perf_counter()
res = halda_solve(
    devs, model, mip_gap=gap, kv_bits="4bit", backend="jax",
    lp_backend=engine, timings=tm, **kw,
)
wall = (time.perf_counter() - t0) * 1e3
payload = {
    "engine": tm.get("lp_backend"), "k": res.k,
    "obj": round(res.obj_value, 6), "certified": bool(res.certified),
    "gap": res.gap, "wall_ms": round(wall, 1),
    "solve_ms": round(tm.get("solve_ms", 0.0), 1),
    "lp_iters": tm.get("ipm_iters_executed"),
    "bnb_rounds": tm.get("bnb_rounds"),
    "peak_rss_mb": round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e3, 1
    ),
}
if shards > 1:
    payload["mesh_shards"] = tm.get("mesh_shards")
    payload["pdhg_dtype"] = dtype
if led is not None:
    from distilp_tpu.obs import memory as obs_memory
    from distilp_tpu.ops import memmodel
    rec = led.analyses.get("solver._solve_packed") or {}
    mem = rec.get("memory") or {}
    payload["shard_temp_bytes_measured"] = mem.get("temp_bytes")
    payload["shard_temp_bytes_predicted"] = memmodel.pdhg_shard_peak_bytes(
        M, shards, memmodel.dtype_bytes_of(dtype)
    )
    obs_memory.disable()
if do_conv:
    # ONE designated arm (the parent picks the smallest pdhg M) runs a
    # SECOND solve with solver-interior telemetry on: the fleet-scale
    # restart cadence / iters-to-certify trail is what ROADMAP item 3
    # tunes against. It is a separate solve on purpose — the timed solve
    # above stays untraced so the --against-gated solve_ms keys keep
    # measuring the solver, never the telemetry.
    from distilp_tpu.obs.convergence import build_search_trace
    conv = {}
    tm2 = {}
    halda_solve(
        devs, model, mip_gap=gap, kv_bits="4bit", backend="jax",
        lp_backend=engine, timings=tm2, convergence=conv, **kw,
    )
    t = build_search_trace(conv)
    payload["conv"] = {
        "rounds": len(t.rounds), "restarts": t.restarts,
        "rounds_to_certify": t.rounds_to_certify,
        "iters_to_certify": t.iters_to_certify, "final_gap": t.final_gap,
        "traced_solve_ms": round(tm2.get("solve_ms", 0.0), 1),
    }
print("DPERF_FLEET", json.dumps(payload))
"""


def _fleet_scale_bench() -> dict:
    """fleet_scale section: both LP engines on synthetic M-device fleets.

    For each M in DPERF_FLEET_MS (default 512,1024,2048,4096) solve the
    same stretched-70B instance under PDHG and under the IPM, reporting
    per-engine solve_ms / certified / measured peak RSS plus the analytic
    working-set proxies (the IPM's beam-batched (m, m) f32 normal
    matrices vs PDHG's ONE shared (m, n) operator — the structural reason
    the first-order engine exists). The IPM arm is skipped outright when
    its proxy exceeds DPERF_FLEET_IPM_MEM_GB (default 8, an accelerator
    HBM-class budget: this host's RAM would let the IPM limp into sizes no
    deployment target fits). `fleet_scale_crossover_m` is the smallest M
    where the PDHG arm certified and the IPM arm lost (slower, timed out,
    uncertified, or memory-infeasible) — the measured engine-selection
    threshold `auto`'s build-time PDHG_AUTO_M approximates. Every arm is
    bounded by DPERF_FLEET_TIMEOUT seconds and the whole section by
    DPERF_FLEET_BUDGET; a bound that fires is recorded as an honest
    timeout/skip, never silence. The mip_gap here is 0.05 — the
    fleet-scale placement tolerance (DPERF_FLEET_GAP; measured root-LP
    bound quality on this family is gap 0.000-0.012 at 1000-2000
    first-order iterations for M=512-2048, so 5% certifies in one B&B
    round with real margin, which is what keeps the big arms inside a
    bench-shaped time box) — and the first-order budget is pinned
    (DPERF_FLEET_ITERS, default 1000: a PDHG iteration streams the whole
    (m, n) operator twice, so wall scales ~M² and the measured per-M walls
    on this box are ~110s/560s/2630s at M=512/1024/2048, each certifying
    at gap 0.0 in ONE root round — 1000 is what fits M=2048 inside
    DPERF_FLEET_TIMEOUT with the certificate intact) and recorded, so
    captures compare like for like.

    PR 18 adds the sharded arms (DPERF_FLEET_SHARD_ARMS, "M:shards:dtype"
    triples; default a 512:4:f32 parity arm + the 8192:4:f32 ceiling arm,
    16384:4:f32 behind DPERF_FLEET_SHARD_SLOW=1): each runs on a forced
    host mesh with f32 iterates + the f64 certificate, can extend
    `fleet_scale_certified_m_max`, and reports memmodel's per-shard
    predicted bytes against ledger-measured XLA temp bytes
    (`fleet_shard_calibration_ok`, gated absolutely by --against). All
    first-order arms draw on DPERF_FLEET_SHARD_BUDGET so an IPM arm can
    no longer starve them.
    """
    ms_list = [
        int(x)
        for x in os.environ.get(
            "DPERF_FLEET_MS", "512,1024,2048,4096"
        ).split(",")
        if x.strip()
    ]
    gap = _env_num("DPERF_FLEET_GAP", 0.05)
    pdhg_iters = int(_env_num("DPERF_FLEET_ITERS", 1000))
    per_timeout = max(120.0, _env_num("DPERF_FLEET_TIMEOUT", 3600))
    budget_s = max(per_timeout, _env_num("DPERF_FLEET_BUDGET", 4200))
    mem_cap_gb = _env_num("DPERF_FLEET_IPM_MEM_GB", 8.0)
    # The per-(M, engine) peak formulas moved to ops/memmodel.py (PR 15):
    # ONE copy shared with the bench memory section's calibration gate and
    # the `solver memory` report; fleet_scale behavior unchanged (pinned
    # by the memmodel parity test in tests/test_memory.py).
    from distilp_tpu.ops import memmodel

    def _run_arm(
        M: int, engine: str, timeout_s: float, conv: bool = False,
        shards: int = 1, dtype: Optional[str] = None,
    ) -> dict:
        argv = [
            sys.executable, "-c", _FLEET_SCALE_SRC,
            str(M), engine, str(gap), str(pdhg_iters),
            str(shards), dtype or "none",
        ]
        if conv:
            argv.append("conv")
        rc, stdout, stderr = run_contained(
            argv,
            timeout_s=timeout_s,
            env=dict(os.environ),
            cwd=str(REPO),
        )
        line = next(
            (
                ln for ln in stdout.splitlines()
                if ln.startswith("DPERF_FLEET ")
            ),
            None,
        )
        if rc is None:
            return {"status": f"timeout (>{timeout_s:.0f}s)"}
        if rc != 0 or line is None:
            return {"status": f"failed rc={rc}: {stderr.strip()[-200:]}"}
        got = json.loads(line[len("DPERF_FLEET "):])
        got["status"] = "ok"
        return got

    # First-order arms (pdhg + sharded) draw on their OWN budget: before
    # PR 18 a slow IPM arm at small M could exhaust DPERF_FLEET_BUDGET and
    # starve the large-M PDHG arms — the section's actual headline. IPM
    # arms keep charging DPERF_FLEET_BUDGET alone, so the section's total
    # is bounded by the sum of the two knobs and neither side can starve
    # the other.
    shard_budget_s = max(
        per_timeout, _env_num("DPERF_FLEET_SHARD_BUDGET", 4200)
    )
    sizes: dict = {}
    spent = 0.0  # IPM-side / total-section spend (DPERF_FLEET_BUDGET)
    spent_fo = 0.0  # first-order arms (DPERF_FLEET_SHARD_BUDGET)
    crossover = None
    certified_max = None
    ipm_lost = False  # first IPM loss settles every larger M
    out: dict = {}
    for M in ms_list:
        # Dense HALDA standard form (ops/memmodel.py): m = 6M+3 rows,
        # n_cols ~ 3M. The proxies are the per-iteration working sets the
        # engines cannot avoid — analytic, and calibrated against XLA's
        # measured temp bytes by the bench `memory` section.
        ipm_gb = memmodel.peak_gb(M, "ipm")
        pdhg_gb = memmodel.peak_gb(M, "pdhg")
        row: dict = {
            "ipm_mem_proxy_gb": round(ipm_gb, 2),
            "pdhg_mem_proxy_gb": round(pdhg_gb, 3),
        }

        if spent_fo >= shard_budget_s:
            row["pdhg"] = {
                "status": "skipped (DPERF_FLEET_SHARD_BUDGET exhausted)"
            }
        else:
            t0 = time.perf_counter()
            # The smallest pdhg arm is the designated convergence arm: its
            # child runs a SECOND, traced solve for the conv block (the
            # timed/gated solve stays untraced — see _FLEET_SCALE_SRC), so
            # it gets twice the single-solve timeout.
            conv_arm = M == min(ms_list)
            row["pdhg"] = _run_arm(
                M, "pdhg",
                min(
                    per_timeout * (2 if conv_arm else 1),
                    max(120.0, shard_budget_s - spent_fo),
                ),
                conv=conv_arm,
            )
            spent_fo += time.perf_counter() - t0
        pd = row["pdhg"]
        pd_ok = pd.get("status") == "ok" and pd.get("certified")

        # IPM arm. Three cheap exits before burning a timeout on it: the
        # batched normal matrices exceed the accelerator-class memory cap;
        # a smaller M already settled the crossover (scaling only gets
        # worse for a factorizing engine — rerunning a loss at every M
        # would double the section's cost for no information); or the
        # budget is gone. When PDHG finished, the IPM arm only needs
        # 1.5x PDHG's wall clock to prove itself: if it is still running
        # past that, it has lost the comparison by definition — which is
        # an answer, not a measurement failure.
        infeasible = memmodel.ipm_memory_infeasible(M, mem_cap_gb)
        if infeasible is not None:
            row["ipm"] = {"status": infeasible}
        elif ipm_lost:
            row["ipm"] = {
                "status": "skipped (crossover settled at smaller M)"
            }
        elif spent >= budget_s:
            row["ipm"] = {"status": "skipped (DPERF_FLEET_BUDGET exhausted)"}
        else:
            arm_timeout = min(per_timeout, max(120.0, budget_s - spent))
            # The 1.5x clamp only applies when PDHG actually CERTIFIED:
            # an ok-but-uncertified PDHG run proves nothing, so the IPM
            # keeps its full timeout to try for the certificate itself.
            if pd_ok:
                arm_timeout = min(
                    arm_timeout, max(120.0, 1.5 * pd["wall_ms"] / 1e3)
                )
            t0 = time.perf_counter()
            row["ipm"] = _run_arm(M, "ipm", arm_timeout)
            spent += time.perf_counter() - t0
            if row["ipm"].get("status", "").startswith("timeout"):
                row["ipm"]["status"] += " — lost to pdhg" if pd_ok else ""
        sizes[str(M)] = row

        ip = row["ipm"]
        if pd_ok:
            certified_max = M
            # A budget-exhausted skip is a bench artifact, not a
            # measurement — only an arm that RAN (ok / timeout / crash)
            # or is memory-infeasible by the analytic proxy may settle
            # the crossover; a skipped arm leaves it open.
            ipm_measured = not ip.get("status", "").startswith("skipped")
            ipm_won = (
                ip.get("status") == "ok"
                and ip.get("certified")
                and ip["solve_ms"] <= pd["solve_ms"]
            )
            if ipm_measured and not ipm_won:
                ipm_lost = True
                if crossover is None:
                    crossover = M

    # -- sharded arms: (M, shards, dtype) triples on a forced host mesh —
    # the "move the ceiling" half of the section. Defaults: a small parity
    # arm (sharded-vs-unsharded solve_ms at M=512 is directly comparable
    # against the unsharded row above) and the M=8192 f32-iterate arm that
    # extends fleet_scale_certified_m_max past the unsharded 4096.
    # M=16384 exists for capable boxes behind DPERF_FLEET_SHARD_SLOW=1
    # (the pytest twin is tests/test_meshlp.py's @pytest.mark.slow arm).
    # Each arm's child reports memmodel's per-shard predicted bytes next
    # to the ledger-measured XLA temp bytes; the measured/predicted ratio
    # must sit in the PR 15 calibration band (above the dominant-term
    # model, within two orders) for fleet_shard_calibration_ok to hold —
    # `--against` fails on False, same contract as mem_calibration_ok.
    arm_spec = os.environ.get(
        "DPERF_FLEET_SHARD_ARMS", "512:4:f32,8192:4:f32"
    )
    if os.environ.get("DPERF_FLEET_SHARD_SLOW", ""):
        arm_spec += ",16384:4:f32"
    sharded: dict = {}
    shard_ratios: list = []
    for spec in [s.strip() for s in arm_spec.split(",") if s.strip()]:
        m_s, s_s, dt = (spec.split(":") + ["f32"])[:3]
        M, S = int(m_s), int(s_s)
        key = f"{M}x{S}:{dt}"
        if spent_fo >= shard_budget_s:
            sharded[key] = {
                "status": "skipped (DPERF_FLEET_SHARD_BUDGET exhausted)"
            }
            continue
        t0 = time.perf_counter()
        arm = _run_arm(
            M, "pdhg",
            min(per_timeout, max(120.0, shard_budget_s - spent_fo)),
            shards=S, dtype=dt,
        )
        spent_fo += time.perf_counter() - t0
        if arm.get("status") == "ok":
            meas = arm.get("shard_temp_bytes_measured")
            pred = arm.get("shard_temp_bytes_predicted")
            arm["shard_calibration_ratio"] = (
                round(meas / pred, 3) if meas and pred else None
            )
            if arm["shard_calibration_ratio"] is not None:
                shard_ratios.append(arm["shard_calibration_ratio"])
            if arm.get("certified"):
                certified_max = max(certified_max or 0, M)
        sharded[key] = arm
    out["fleet_scale"] = {
        "gap": gap,
        "pdhg_iters": pdhg_iters,
        "model": "llama_3_70b scalars stretched to L=2M",
        "sizes": sizes,
        "sharded": sharded,
        "shard_budget_s": shard_budget_s,
    }
    out["fleet_scale_crossover_m"] = crossover
    out["fleet_scale_certified_m_max"] = certified_max
    # Band verdict mirrors mem_calibration_ok: None (no measurement) is
    # not a failure — only a measured ratio OUTSIDE the band is.
    out["fleet_shard_calibration_ok"] = (
        None if not shard_ratios
        else all(1.0 <= r <= 100.0 for r in shard_ratios)
    )
    for M in (512, 2048):
        e = sizes.get(str(M), {}).get("pdhg", {})
        if e.get("status") == "ok" and e.get("certified"):
            out[f"fleet_scale_pdhg_{M}_solve_ms"] = e["solve_ms"]
    for key, arm in sharded.items():
        if arm.get("status") == "ok" and arm.get("certified"):
            M = key.split("x")[0]
            out[f"fleet_scale_sharded_{M}_solve_ms"] = arm["solve_ms"]
    return out


def _moe_warm_tick(rng):
    """(median ms, result, breakdown) of certified warm ticks on the
    DeepSeek-V3 E=256 / 32-device flagship. The breakdown carries the same
    keys as the dense headline (build/pack/upload/solve medians +
    static_hit) so a regression in the MoE tick is attributable, not just
    visible."""
    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver.streaming import StreamingReplanner
    from distilp_tpu.utils import make_synthetic_fleet

    split = profile_model(
        str(REPO / "tests" / "configs" / "deepseek_v3.json"),
        batch_sizes=[1],
        sequence_length=128,
    )
    model = split.to_model_profile()
    # Expert residency is hard-capped: the fleet must physically hold the
    # E=256 expert slices (~1.6 GB each), so give every pool 32 GB.
    devs = make_synthetic_fleet(MOE_DEVICES, seed=11, pool_bytes=int(32e9))
    planner = StreamingReplanner(mip_gap=MIP_GAP, kv_bits="8bit", backend="jax")
    planner.step(devs, model)  # cold solve + compile
    planner.step(devs, model)  # compile the warm layout
    times = []
    acc: dict = {}
    result = planner.last
    for _ in range(REPEATS):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        tm: dict = {}
        t0 = time.perf_counter()
        result = planner.step(devs, model, timings=tm)
        times.append((time.perf_counter() - t0) * 1e3)
        for k, v in tm.items():
            acc.setdefault(k, []).append(v)
    assert result.certified, f"MoE warm tick not certified (gap={result.gap})"
    assert sum(result.y) == model.n_routed_experts
    breakdown = {k: round(statistics.median(v), 3) for k, v in acc.items()}
    _add_per_round_iters(breakdown)

    # Pipelined MoE: one tick in flight, margin bounds decided at dispatch
    # and the anchor refreshed at collect — on a per-operation-billed
    # tunnel this is the E=256 streaming throughput path (host prep +
    # upload overlap the previous solve's execution + result transfer).
    n_pipe = 2 * REPEATS
    uncert = 0
    t0 = time.perf_counter()
    planner.submit(devs, model)
    for _ in range(n_pipe):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        planner.submit(devs, model)
        if not planner.collect().certified:
            uncert += 1
    if not planner.collect().certified:
        uncert += 1
    pipe_s = time.perf_counter() - t0
    breakdown["pipelined_placements_per_sec"] = round((n_pipe + 1) / pipe_s, 1)
    if uncert:
        breakdown["pipelined_uncertified_ticks"] = uncert
    return statistics.median(times), result, breakdown


def _main_guarded() -> int:
    """Last-resort containment: the driver must ALWAYS get one JSON line."""
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--against",
        default=None,
        metavar="BENCH_rNN.json",
        help="compare this run's payload against a previous capture "
        "(driver wrapper or raw payload JSON), print per-metric deltas, "
        "and exit nonzero on a >20%% regression of value or warm_tick_ms "
        "(`make bench-compare`)",
    )
    parser.add_argument(
        "--history",
        default=None,
        metavar="BENCH_HISTORY.jsonl",
        help="append this run's headline keys as one committed-format "
        "JSONL line (`make bench` passes BENCH_HISTORY.jsonl; trend-check "
        "with `solver slo --history`)",
    )
    args = parser.parse_args()
    try:
        return main(against=args.against, history=args.history)
    except BaseException as e:  # noqa: BLE001 - the line matters more
        print(
            json.dumps(
                {
                    "metric": "halda_sweep_16dev_llama70b_wallclock",
                    "value": None,
                    "unit": "ms",
                    "platform": _PLATFORM,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        return 1


if __name__ == "__main__":
    raise SystemExit(_main_guarded())

#!/usr/bin/env python3
"""Headline benchmark: 16-device Llama-3-70B HALDA sweep wall-clock.

Workload (BASELINE.md north star): assign 80 layers across a 16-device
heterogeneous fleet, full k-candidate sweep, mip_gap<=1e-3. The JAX backend
solves the whole sweep as batched accelerator work; the baseline is the
equivalent scipy/HiGHS branch-and-cut sweep measured in-process (the same
engine the reference uses, see BASELINE.md).

Prints ONE JSON line:
    {"metric": ..., "value": <cold jax ms>, "unit": "ms", "vs_baseline":
     <speedup>, "warm_tick_ms": <warm-start streaming re-solve ms>,
     "placements_per_sec": <1000 / warm_tick_ms>}

The extra keys report the streaming north star (BASELINE.json
"placements/sec over k-sweep"): each tick perturbs the fleet's measured
t_comm and re-solves warm-started from the previous placement.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

REPEATS = 10
MIP_GAP = 1e-3
M_DEVICES = 16


def main() -> int:
    import numpy as np

    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.streaming import StreamingReplanner
    from distilp_tpu.utils import make_synthetic_fleet

    model = load_model_profile(
        REPO / "tests" / "profiles" / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(M_DEVICES, seed=123)

    # Baseline: the scipy/HiGHS branch-and-cut sweep (reference engine).
    t0 = time.perf_counter()
    ref = halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="cpu")
    cpu_ms = (time.perf_counter() - t0) * 1e3

    # JAX backend: warm up (compile), then best-of-N wall clock.
    got = halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
    assert abs(got.obj_value - ref.obj_value) <= 2 * MIP_GAP * abs(ref.obj_value) + 1e-9, (
        f"backend disagreement: jax={got.obj_value} cpu={ref.obj_value}"
    )
    assert got.certified, f"north-star solve not certified (gap={got.gap})"

    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
        times.append((time.perf_counter() - t0) * 1e3)
    jax_ms = min(times)

    # Streaming re-placement: warm-started ticks under drifting t_comm.
    planner = StreamingReplanner(mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
    planner.step(devs, model)
    rng = np.random.default_rng(7)
    warm_times = []
    for _ in range(REPEATS):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
        t0 = time.perf_counter()
        planner.step(devs, model)
        warm_times.append((time.perf_counter() - t0) * 1e3)
    warm_ms = min(warm_times)

    print(
        json.dumps(
            {
                "metric": "halda_sweep_16dev_llama70b_wallclock",
                "value": round(jax_ms, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_ms / jax_ms, 3),
                "warm_tick_ms": round(warm_ms, 3),
                "placements_per_sec": round(1000.0 / warm_ms, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python3
"""Headline benchmark: 16-device Llama-3-70B HALDA sweep wall-clock.

Workload (BASELINE.md north star): assign 80 layers across a 16-device
heterogeneous fleet, full k-candidate sweep, mip_gap<=1e-3. The JAX backend
solves the whole sweep as batched accelerator work; the baseline is the
equivalent scipy/HiGHS branch-and-cut sweep measured in-process (the same
engine the reference uses, see BASELINE.md).

Prints ONE JSON line:
    {"metric": ..., "value": <jax ms>, "unit": "ms", "vs_baseline": <speedup>}
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

REPEATS = 10
MIP_GAP = 1e-3
M_DEVICES = 16


def main() -> int:
    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.utils import make_synthetic_fleet

    model = load_model_profile(
        REPO / "tests" / "profiles" / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(M_DEVICES, seed=123)

    # Baseline: the scipy/HiGHS branch-and-cut sweep (reference engine).
    t0 = time.perf_counter()
    ref = halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="cpu")
    cpu_ms = (time.perf_counter() - t0) * 1e3

    # JAX backend: warm up (compile), then best-of-N wall clock.
    got = halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
    assert abs(got.obj_value - ref.obj_value) <= 2 * MIP_GAP * abs(ref.obj_value) + 1e-9, (
        f"backend disagreement: jax={got.obj_value} cpu={ref.obj_value}"
    )

    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        halda_solve(devs, model, mip_gap=MIP_GAP, kv_bits="4bit", backend="jax")
        times.append((time.perf_counter() - t0) * 1e3)
    jax_ms = min(times)

    print(
        json.dumps(
            {
                "metric": "halda_sweep_16dev_llama70b_wallclock",
                "value": round(jax_ms, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_ms / jax_ms, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

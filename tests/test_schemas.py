"""Schema round-trip and loader tests over the golden conformance fixtures."""

import json
from pathlib import Path

import pytest

from distilp_tpu.common import (
    DeviceProfile,
    ModelProfile,
    ModelProfileSplit,
    kv_bits_to_factor,
    load_from_profile_folder,
    load_model_profile,
)

FIXTURE_FOLDERS = [
    "hermes_70b",
    "llama_3_70b/4bit",
    "llama_3_70b/online",
    "qwen3_32b/bf16",
]


@pytest.mark.parametrize("folder", FIXTURE_FOLDERS)
def test_fixture_folder_loads(profiles_dir: Path, folder: str):
    devices, model = load_from_profile_folder(profiles_dir / folder)
    assert devices, "expected at least one device"
    assert devices[0].is_head
    assert model.L > 0
    assert model.b_layer > 0
    assert "b_1" in model.f_q
    for dev in devices:
        assert dev.T_cpu > 0
        assert dev.scpu, "CPU throughput table must be populated"
        # All seven quant levels present in measured fixtures
        for q in ("Q4_K", "Q5_K", "Q6_K", "Q8_0", "F16", "BF16", "F32"):
            assert q in dev.scpu


def test_split_to_scalar_uses_layer_1_decode(profiles_dir: Path):
    path = profiles_dir / "hermes_70b" / "model_profile.json"
    raw = json.loads(path.read_text())
    split = ModelProfileSplit.model_validate(raw)
    model = split.to_model_profile()
    assert model.b_layer == split.b[1]
    assert model.b_in == split.b_i[1]
    assert model.b_out == split.b_o[1]
    for batch_key, values in split.f_q["decode"].items():
        assert model.f_q[batch_key] == values[1]
    assert model.f_out == split.f_out["decode"]
    # Loader auto-detects the Split format
    assert load_model_profile(path).b_layer == model.b_layer


def test_device_profile_json_round_trip(profiles_dir: Path):
    # Prefer the pristine reference fixture so the field-preservation check
    # runs against the original wire contract, not our own normalized output.
    ref = Path("/root/reference/test/profiles/llama_3_70b/online/m1.json")
    path = ref if ref.exists() else profiles_dir / "llama_3_70b" / "online" / "m1.json"
    raw = json.loads(path.read_text())
    dev = DeviceProfile.model_validate(raw)
    dumped = dev.model_dump(mode="json")
    assert DeviceProfile.model_validate(dumped) == dev
    # No fields lost relative to the on-disk contract
    assert set(raw) <= set(dumped)
    assert dumped["t_comm"] == raw["t_comm"]
    assert dumped["scpu"] == raw["scpu"]


def test_model_profile_round_trip(profiles_dir: Path):
    path = profiles_dir / "qwen3_32b" / "bf16" / "model_profile.json"
    raw = json.loads(path.read_text())
    split = ModelProfileSplit.model_validate(raw)
    dumped = split.model_dump(mode="json")
    assert ModelProfileSplit.model_validate(dumped) == split


def test_gpu_table_preference():
    dev = DeviceProfile(
        has_metal=True,
        has_cuda=True,
        sgpu_metal={"F16": {"b_1": 2.0}},
        sgpu_cuda={"F16": {"b_1": 1.0}},
        T_metal=5.0,
        T_cuda=3.0,
        d_avail_metal=1,
        d_avail_cuda=1,
    )
    assert dev.gpu_table() == {"F16": {"b_1": 2.0}}
    assert dev.gpu_T() == 5.0
    assert dev.has_gpu_backend()
    cpu_only = DeviceProfile()
    assert cpu_only.gpu_table() is None
    assert not cpu_only.has_gpu_backend()


def test_kv_bits_factor():
    assert kv_bits_to_factor("4bit") == 0.5
    assert kv_bits_to_factor("8bit") == 1.0
    assert kv_bits_to_factor("fp16") == 2.0
    assert kv_bits_to_factor("BF16") == 2.0
    with pytest.raises(ValueError):
        kv_bits_to_factor("2bit")


def test_scalar_model_profile_loads(tmp_path: Path):
    scalar = ModelProfile(L=8, b_layer=100, f_q={"b_1": 1.0}, f_out={"b_1": 2.0})
    p = tmp_path / "model_profile.json"
    p.write_text(scalar.model_dump_json())
    loaded = load_model_profile(p)
    assert loaded.L == 8
    assert loaded.b_layer == 100

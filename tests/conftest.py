"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding code
compiles and executes without TPU hardware. Set before any jax import.
"""

import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

PROFILES = REPO_ROOT / "tests" / "profiles"

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def profiles_dir() -> Path:
    return PROFILES

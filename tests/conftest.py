"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding code
compiles and executes without TPU hardware. Set before any jax import.
"""

import os
import sys
from pathlib import Path

# Tests always run on a virtual 8-device CPU mesh. On this image a TPU-tunnel
# PJRT plugin ("axon") is injected into every interpreter via a PYTHONPATH
# sitecustomize, and when the tunnel is down its backend init wedges the whole
# process — so unregister it before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = Path(__file__).resolve().parents[1]

# The suite is XLA-compile dominated (every solver shape is a multi-second
# trace on the 2-core CI box) and the tier-1 gate runs it under a hard wall
# clock. Persist compiled executables across pytest processes so repeat runs
# pay dispatch, not compilation. Subprocess tests (CLI, smoke daemon) inherit
# the same cache through the environment. setdefault: an explicit cache dir
# in the environment (or pointing at a tmpfs) wins.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", str(REPO_ROOT / ".cache" / "jax")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
sys.path.insert(0, str(REPO_ROOT))

from distilp_tpu.axon_guard import force_cpu_platform  # noqa: E402

force_cpu_platform()

PROFILES = REPO_ROOT / "tests" / "profiles"

import pytest  # noqa: E402


def jax_shard_map_available() -> bool:
    """Capability detect for the profiler's collective microbenchmarks.

    ``profiler.topology`` times its interconnect collectives through
    ``utils.shardcompat.shard_map``, which resolves ``jax.shard_map`` on
    new releases and ``jax.experimental.shard_map.shard_map`` on this
    image's jax 0.4.37 (mapping the ``check_vma`` knob to the old
    ``check_rep`` spelling). Tests that need the collectives skip on this
    CAPABILITY check, not a version pin, so a jax with neither spelling
    still skips cleanly while a real regression in a capable environment
    fails loudly.
    """
    from distilp_tpu.utils.shardcompat import have_shard_map

    return have_shard_map()


SHARD_MAP_SKIP_REASON = (
    "env defect: this jax has neither `jax.shard_map` nor "
    "`jax.experimental.shard_map.shard_map` (see utils/shardcompat.py), "
    "so the profiler's interconnect collectives (profiler/topology.py) "
    "cannot run here; capability-detected skip, lifts on a fixed "
    "environment"
)


@pytest.fixture(scope="session")
def profiles_dir() -> Path:
    return PROFILES

"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding code
compiles and executes without TPU hardware. Set before any jax import.
"""

import os
import sys
from pathlib import Path

# Tests always run on a virtual 8-device CPU mesh. On this image a TPU-tunnel
# PJRT plugin ("axon") is injected into every interpreter via a PYTHONPATH
# sitecustomize, and when the tunnel is down its backend init wedges the whole
# process — so unregister it before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from distilp_tpu.axon_guard import force_cpu_platform  # noqa: E402

force_cpu_platform()

PROFILES = REPO_ROOT / "tests" / "profiles"

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def profiles_dir() -> Path:
    return PROFILES

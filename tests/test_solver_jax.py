"""JAX backend parity: golden fixtures + synthetic fleets vs the CPU oracle."""

import numpy as np
import pytest

pytest.importorskip("jax")

from distilp_tpu.common import load_from_profile_folder, load_model_profile  # noqa: E402
from distilp_tpu.solver import halda_solve  # noqa: E402
from distilp_tpu.utils import make_synthetic_fleet  # noqa: E402

GOLDEN = [
    ("hermes_70b", 40, 29.643569),
    ("llama_3_70b/4bit", 8, 12.834690),
    ("llama_3_70b/online", 2, 1.934942),
    ("qwen3_32b/bf16", 16, 12.072837),
]


@pytest.mark.parametrize("folder,k_star,obj", GOLDEN)
def test_jax_backend_matches_golden(profiles_dir, folder, k_star, obj):
    devs, model = load_from_profile_folder(profiles_dir / folder)
    result = halda_solve(devs, model, mip_gap=1e-4, kv_bits="4bit", backend="jax")
    assert result.k == k_star
    assert result.obj_value == pytest.approx(obj, rel=2e-4)
    assert sum(result.w) * result.k == model.L
    for wi, ni in zip(result.w, result.n):
        assert 0 <= ni <= wi


@pytest.mark.parametrize("M", [4, 8])
def test_jax_matches_cpu_on_synthetic_fleet(profiles_dir, M):
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(M, seed=M)
    gap = 1e-3
    ref = halda_solve(devs, model, mip_gap=gap, kv_bits="4bit", backend="cpu")
    got = halda_solve(devs, model, mip_gap=gap, kv_bits="4bit", backend="jax")
    # Both backends certify the same relative gap, so the objectives can
    # differ by at most twice that.
    assert got.obj_value == pytest.approx(ref.obj_value, rel=2 * gap)
    assert sum(got.w) * got.k == model.L
    assert all(0 <= n <= w for w, n in zip(got.w, got.n))


def test_jax_backend_infeasible(profiles_dir):
    devs = make_synthetic_fleet(6, seed=1)
    _, model = load_from_profile_folder(profiles_dir / "hermes_70b")
    # k=20 -> W=4 < 6 devices: structurally infeasible; only candidate.
    with pytest.raises(RuntimeError, match="No feasible"):
        halda_solve(devs, model, k_candidates=[20], kv_bits="4bit", backend="jax")

"""JAX backend parity: golden fixtures + synthetic fleets vs the CPU oracle."""

import pytest

pytest.importorskip("jax")

from distilp_tpu.common import load_from_profile_folder, load_model_profile  # noqa: E402
from distilp_tpu.solver import halda_solve  # noqa: E402
from distilp_tpu.utils import make_synthetic_fleet  # noqa: E402

GOLDEN = [
    ("hermes_70b", 40, 29.643569),
    ("llama_3_70b/4bit", 8, 12.834690),
    ("llama_3_70b/online", 2, 1.934942),
    ("qwen3_32b/bf16", 16, 12.072837),
]


@pytest.mark.parametrize("folder,k_star,obj", GOLDEN)
def test_jax_backend_matches_golden(profiles_dir, folder, k_star, obj):
    devs, model = load_from_profile_folder(profiles_dir / folder)
    result = halda_solve(devs, model, mip_gap=1e-4, kv_bits="4bit", backend="jax")
    assert result.k == k_star
    assert result.obj_value == pytest.approx(obj, rel=2e-4)
    assert sum(result.w) * result.k == model.L
    for wi, ni in zip(result.w, result.n):
        assert 0 <= ni <= wi


@pytest.mark.parametrize("M", [4, 8, 16, 32])
def test_jax_matches_cpu_on_synthetic_fleet(profiles_dir, M):
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    # seed=123 at M=16 IS the north-star bench instance (bench.py) — the
    # backend agreement asserted there is pinned here as a committed test.
    # M=32 doubles the reference's largest synthetic scaling point
    # (BASELINE.md) and pins the fixed-shape assembly at 7*32+1 variables.
    devs = make_synthetic_fleet(M, seed=M if M != 16 else 123)
    gap = 1e-3
    ref = halda_solve(devs, model, mip_gap=gap, kv_bits="4bit", backend="cpu")
    got = halda_solve(devs, model, mip_gap=gap, kv_bits="4bit", backend="jax")
    # Both backends certify the same relative gap, so the objectives can
    # differ by at most twice that.
    assert got.obj_value == pytest.approx(ref.obj_value, rel=2 * gap)
    assert got.certified and got.gap is not None and got.gap <= gap
    assert sum(got.w) * got.k == model.L
    assert all(0 <= n <= w for w, n in zip(got.w, got.n))


def test_max_rounds_converts_warning_into_certificate(profiles_dir):
    """The certify-or-warn escape hatch the public API advertises: a solve
    truncated at one B&B round warns and returns certified=False with the
    achieved gap; the default round budget certifies the same instance."""
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(16, seed=123)
    with pytest.warns(RuntimeWarning, match="certificate NOT met"):
        short = halda_solve(
            devs, model, mip_gap=1e-4, kv_bits="4bit", backend="jax", max_rounds=1
        )
    assert not short.certified
    assert short.gap is not None and short.gap > 1e-4

    full = halda_solve(
        devs, model, mip_gap=1e-4, kv_bits="4bit", backend="jax", max_rounds=48
    )
    assert full.certified and full.gap <= 1e-4
    # The truncated incumbent is still a valid (if possibly worse) placement.
    assert sum(short.w) * short.k == model.L


def test_per_k_reporting_entries_have_no_assignment(profiles_dir):
    """Non-winning k's in the DEFAULT sweep output carry only a best-found
    objective: w/n are None and certified is False, so no caller can mistake
    them for solved placements. The reference's certified-per-k contract
    (/root/reference/src/distilp/solver/halda_p_solver.py:392-412) is the
    opt-in ``halda_solve_per_k`` / ``per_k_optima=True`` mode (pinned by
    test_per_k_optima_match_cpu_oracle)."""
    from distilp_tpu.common import kv_bits_to_factor
    from distilp_tpu.solver.assemble import assemble
    from distilp_tpu.solver.backend_jax import solve_sweep_jax
    from distilp_tpu.solver.coeffs import assign_sets, build_coeffs, valid_factors_of_L

    devs, model = load_from_profile_folder(profiles_dir / "hermes_70b")
    coeffs = build_coeffs(devs, model, kv_bits_to_factor("4bit"), assign_sets(devs))
    arrays = assemble(coeffs)
    kWs = [(k, model.L // k) for k in valid_factors_of_L(model.L)]
    results, best = solve_sweep_jax(arrays, kWs, mip_gap=1e-4, coeffs=coeffs)

    assert best is not None and best.certified
    assert best.w is not None and sum(best.w) * best.k == model.L
    losers = [r for r in results if r is not None and r.k != best.k]
    assert losers, "sweep should report non-winning k entries"
    for r in losers:
        assert r.w is None and r.n is None
        assert not r.certified
        assert r.obj_value >= best.obj_value - 1e-9


def test_jax_backend_infeasible(profiles_dir):
    devs = make_synthetic_fleet(6, seed=1)
    _, model = load_from_profile_folder(profiles_dir / "hermes_70b")
    # k=20 -> W=4 < 6 devices: structurally infeasible; only candidate.
    with pytest.raises(RuntimeError, match="No feasible"):
        halda_solve(devs, model, k_candidates=[20], kv_bits="4bit", backend="jax")


def test_qwen3_4b_4dev_full_sweep_both_backends():
    """BASELINE.json config 2: Qwen3-4B over 4 heterogeneous devices, FULL
    k-candidate sweep — analytic profile in, certified placement out, both
    backends agreeing. (The other four baseline configs are covered by the
    golden-fixture tests, the Mixtral/DeepSeek MoE tests, and bench.py.)"""
    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.utils import make_synthetic_fleet

    model = profile_model(
        "tests/configs/qwen3_4b_8bit.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    assert model.L == 36
    devs = make_synthetic_fleet(4, seed=5)

    gap = 1e-3
    ref = halda_solve(devs, model, kv_bits="8bit", mip_gap=gap, backend="cpu")
    got = halda_solve(devs, model, kv_bits="8bit", mip_gap=gap, backend="jax")
    assert got.certified
    assert abs(got.obj_value - ref.obj_value) <= 2 * gap * abs(ref.obj_value) + 1e-9
    assert sum(got.w) * got.k == model.L
    # Full sweep: the winning k is a proper factor of L=36.
    assert got.k in (1, 2, 3, 4, 6, 9, 12, 18)


def test_timings_breakdown_populated(profiles_dir):
    """halda_solve(timings=...) must report the pack/upload/solve wall-clock
    split the bench publishes."""
    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.utils import make_synthetic_fleet

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(4, seed=3)
    tm = {}
    result = halda_solve(
        devs, model, kv_bits="4bit", mip_gap=1e-3, backend="jax", timings=tm
    )
    assert result.certified
    assert set(tm) == {
        "build_ms", "pack_ms", "upload_ms", "solve_ms", "static_hit",
        "ipm_iters_executed", "bnb_rounds", "lp_backend", "mesh_shards",
    }
    # The LP engine echo: 'auto' on a 4-device fleet resolves to the IPM.
    assert tm.pop("lp_backend") == "ipm"
    # The mesh echo: no --mesh-shards request resolves to the 1-shard
    # (plain single-device) engine.
    assert tm.pop("mesh_shards") == 1
    assert all(v >= 0 for v in tm.values())
    assert tm["build_ms"] > 0
    assert tm["solve_ms"] > 0
    assert tm["static_hit"] in (0.0, 1.0)
    # The device program's execution counters: a certified solve ran at
    # least one round and spent at least one IPM iteration on it.
    assert tm["bnb_rounds"] >= 1
    assert tm["ipm_iters_executed"] >= 1


def test_static_cache_survives_t_comm_drift(profiles_dir):
    """The drift-invariant half of the packed instance must stay cached
    on-device across streaming t_comm drift — that cache hit is what makes
    warm ticks upload a few KB instead of the whole instance. A changed
    fleet shape must miss (correctness over reuse)."""
    import numpy as np

    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.backend_jax import clear_static_cache
    from distilp_tpu.utils import make_synthetic_fleet

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(4, seed=3)
    clear_static_cache()

    tm = {}
    cold = halda_solve(
        devs, model, kv_bits="4bit", mip_gap=1e-3, backend="jax", timings=tm
    )
    assert cold.certified
    assert tm["static_hit"] == 0.0  # first contact uploads

    rng = np.random.default_rng(5)
    for _ in range(3):
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.9, 1.1)))
        tm = {}
        drifted = halda_solve(
            devs, model, kv_bits="4bit", mip_gap=1e-3, backend="jax",
            timings=tm, warm=cold,
        )
        assert drifted.certified
        assert tm["static_hit"] == 1.0, "t_comm drift must not evict the static blob"

    # Different fleet shape: the cached blob must NOT be reused.
    other = make_synthetic_fleet(5, seed=9)
    tm = {}
    res = halda_solve(
        other, model, kv_bits="4bit", mip_gap=1e-3, backend="jax", timings=tm
    )
    assert res.certified
    assert tm["static_hit"] == 0.0


def test_static_cache_survives_drift_moe(profiles_dir):
    """Same drift-invariance on the MoE family: t_comm drift moves g_raw
    (the all-to-all term) and the busy constants, all of which ship in the
    dynamic blob — the per-k A family is rebuilt in-trace from the cached
    base, so the static blob must keep hitting."""
    import numpy as np

    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.backend_jax import clear_static_cache
    from distilp_tpu.utils import make_synthetic_fleet

    model = profile_model(
        str(profiles_dir.parent / "configs" / "mixtral_8x7b.json"),
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    clear_static_cache()

    tm = {}
    cold = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=1e-3, backend="jax", timings=tm
    )
    assert cold.certified
    assert tm["static_hit"] == 0.0

    rng = np.random.default_rng(13)
    for d in devs:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.9, 1.1)))
    tm = {}
    drifted = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=1e-3, backend="jax",
        timings=tm, warm=cold,
    )
    assert drifted.certified
    assert tm["static_hit"] == 1.0, "MoE t_comm drift must not evict the static blob"


def test_batch_size_pricing_opt_in(profiles_dir):
    """Opt-in batch pricing: batch_size=N prices dense compute at the b_N
    columns of both the model FLOPs and device throughput tables. The
    default stays b_1 (reference parity; golden-objective tests pin it);
    a requested column the model profile lacks is a clear error, never a
    silent zero-compute price."""
    import pytest

    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.utils import make_synthetic_fleet

    model = profile_model(
        str(profiles_dir.parent / "configs" / "qwen3_14b_8bit.json"),
        batch_sizes=[1, 2],
        sequence_length=128,
    ).to_model_profile()
    assert "b_2" in model.f_q
    devs = make_synthetic_fleet(3, seed=21)

    ref1 = halda_solve(devs, model, kv_bits="8bit", mip_gap=1e-3, backend="cpu")
    ref2 = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=1e-3, backend="cpu", batch_size=2
    )
    got2 = halda_solve(
        devs, model, kv_bits="8bit", mip_gap=1e-3, backend="jax", batch_size=2
    )
    # Backends agree on the SAME batch-2-priced instance.
    tol = 2e-3 * abs(ref2.obj_value) + 1e-9
    assert abs(got2.obj_value - ref2.obj_value) <= tol
    # Batch-2 FLOPs are ~2x batch-1 while throughput grows only ~2%, so the
    # compute-priced objective must move (strictly larger here).
    assert ref2.obj_value > ref1.obj_value

    # A column the model was never profiled at is an explicit error.
    with pytest.raises(ValueError, match="b_4"):
        halda_solve(devs, model, kv_bits="8bit", backend="cpu", batch_size=4)

    # f_out is validated too (a partial hand-edited profile must not price
    # the head's output layer at a silent 0.0).
    partial = model.model_copy(deep=True)
    partial.f_out = {"b_1": partial.f_out["b_1"]}
    with pytest.raises(ValueError, match="f_out"):
        halda_solve(devs, partial, kv_bits="8bit", backend="cpu", batch_size=2)


def test_batch_size_rejected_for_moe(profiles_dir):
    """Batch pricing is dense-only: the MoE expert busy model is per-token
    batch-1, so a batch-N MoE solve must raise instead of silently mixing
    batches in one objective — and solve_load_aware (MoE-only) likewise."""
    import pytest

    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.routing import solve_load_aware
    from distilp_tpu.utils import make_synthetic_fleet

    model = profile_model(
        str(profiles_dir.parent / "configs" / "mixtral_8x7b.json"),
        batch_sizes=[1, 2],
        sequence_length=128,
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    with pytest.raises(ValueError, match="dense-only"):
        halda_solve(devs, model, kv_bits="8bit", backend="cpu", batch_size=2)
    with pytest.raises(ValueError, match="dense-only"):
        solve_load_aware(
            devs, model, expert_loads=None, backend="cpu", batch_size=2
        )
    # The dense slice of a MoE profile may still be priced at batch N.
    res = halda_solve(
        devs, model, kv_bits="8bit", backend="cpu", moe=False, batch_size=2
    )
    assert res.obj_value is not None


def test_scenario_batched_solves_match_individual(profiles_dir):
    """S what-if drifts of one fleet solved in ONE dispatch must each match
    their individually solved counterpart within the certification band,
    and a scenario outside the profile-drift class (a device speed change,
    which moves the static half) must be rejected."""
    import numpy as np
    import pytest

    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.api import halda_solve_scenarios
    from distilp_tpu.utils import make_synthetic_fleet

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    rng = np.random.default_rng(31)
    gap = 1e-3

    scenarios = []
    for _ in range(4):
        devs = make_synthetic_fleet(5, seed=31)  # same fleet...
        for d in devs:  # ...under scenario-specific t_comm drift
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.5, 2.0)))
        scenarios.append(devs)

    tm = {}
    batched = halda_solve_scenarios(
        scenarios, model, kv_bits="4bit", mip_gap=gap, timings=tm
    )
    assert len(batched) == 4
    assert tm["scenarios"] == 4.0
    for devs, res in zip(scenarios, batched):
        assert res.certified
        solo = halda_solve(
            devs, model, kv_bits="4bit", mip_gap=gap, backend="jax"
        )
        tol = 2 * gap * abs(solo.obj_value) + 1e-9
        assert abs(res.obj_value - solo.obj_value) <= tol
        assert sum(res.w) * res.k == model.L

    # Drift outside the profile class: scale a device's CPU table (changes
    # alpha -> the A matrix -> the static half). The shared-static fast
    # path can't take it, but the multi-instance batch layout fallback
    # packs each scenario's own static half and still serves the batch in
    # one dispatch — each lane matching its individual solve.
    bad = [d.model_copy(deep=True) for d in scenarios[0]]
    for q in bad[0].scpu:
        bad[0].scpu[q] = {col: v * 2.0 for col, v in bad[0].scpu[q].items()}
    tm2 = {}
    hetero = halda_solve_scenarios(
        [scenarios[0], bad], model, kv_bits="4bit", mip_gap=gap, timings=tm2
    )
    assert tm2.get("scenario_fallback") == 1.0
    assert len(hetero) == 2
    for devs, res in zip([scenarios[0], bad], hetero):
        assert res.certified
        solo = halda_solve(
            devs, model, kv_bits="4bit", mip_gap=gap, backend="jax"
        )
        tol = 2 * gap * abs(solo.obj_value) + 1e-9
        assert abs(res.obj_value - solo.obj_value) <= tol

    # Scenarios that don't even share a shape family (different fleet
    # SIZE) stay rejected: no batch layout can carry them in one dispatch.
    with pytest.raises(ValueError, match="shape family"):
        halda_solve_scenarios(
            [scenarios[0], make_synthetic_fleet(6, seed=31)],
            model, kv_bits="4bit", mip_gap=gap,
        )


def test_scenario_batched_moe_load_factors(profiles_dir):
    """MoE scenario batching: alternative expert-load regimes of one fleet
    (load_factors_list) ride the dynamic blob, so they batch into one
    dispatch too — each certified and matching its individual solve."""
    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.api import halda_solve_scenarios
    from distilp_tpu.utils import make_synthetic_fleet

    model = profile_model(
        str(profiles_dir.parent / "configs" / "mixtral_8x7b.json"),
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()
    gap = 1e-3
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    regimes = [
        None,  # uniform
        [1.5, 0.8, 1.0, 0.9],  # hot device 0
        [0.7, 0.7, 1.6, 1.2],  # load shifted to the slow half
    ]
    batched = halda_solve_scenarios(
        [devs, devs, devs], model, kv_bits="8bit", mip_gap=gap,
        load_factors_list=regimes,
    )
    assert len(batched) == 3
    for factors, res in zip(regimes, batched):
        assert res.certified
        assert sum(res.y) == model.n_routed_experts
        solo = halda_solve(
            devs, model, kv_bits="8bit", mip_gap=gap, backend="jax",
            load_factors=factors,
        )
        tol = 2 * gap * abs(solo.obj_value) + 1e-9
        assert abs(res.obj_value - solo.obj_value) <= tol


def test_scenario_batched_warm_seeds(profiles_dir):
    """Scenario batching with per-scenario warm seeds: the has_warm layout
    engages only when EVERY scenario carries a hint (all-or-none, since
    the vmapped jit layout is shared) and each warm result still matches
    its cold counterpart within the certification band."""
    import numpy as np

    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver.api import halda_solve_scenarios
    from distilp_tpu.utils import make_synthetic_fleet

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    rng = np.random.default_rng(43)
    gap = 1e-3
    scenarios = []
    for _ in range(3):
        devs = make_synthetic_fleet(4, seed=43)
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.6, 1.7)))
        scenarios.append(devs)

    cold = halda_solve_scenarios(scenarios, model, kv_bits="4bit", mip_gap=gap)
    # Re-solve the same scenarios warm-seeded by their own cold results.
    warm = halda_solve_scenarios(
        scenarios, model, kv_bits="4bit", mip_gap=gap, warms=cold
    )
    for c, w in zip(cold, warm):
        assert w.certified
        tol = 2 * gap * abs(c.obj_value) + 1e-9
        assert abs(w.obj_value - c.obj_value) <= tol

    # Mixed warms (one None) degrade the whole batch to cold — still
    # correct, same objectives.
    mixed = halda_solve_scenarios(
        scenarios, model, kv_bits="4bit", mip_gap=gap,
        warms=[cold[0], None, cold[2]],
    )
    for c, m in zip(cold, mixed):
        assert m.certified
        tol = 2 * gap * abs(c.obj_value) + 1e-9
        assert abs(m.obj_value - c.obj_value) <= tol


def test_per_k_optima_match_cpu_oracle(profiles_dir):
    """halda_solve_per_k must return a CERTIFIED optimum with a full
    assignment for EVERY feasible k — the reference's per-k-MILP output
    contract — each matching the HiGHS oracle's fixed-k solve within the
    certification band."""
    from distilp_tpu.common import kv_bits_to_factor, load_from_profile_folder
    from distilp_tpu.solver.api import halda_solve_per_k
    from distilp_tpu.solver.assemble import assemble
    from distilp_tpu.solver.backend_cpu import solve_fixed_k_cpu
    from distilp_tpu.solver.coeffs import assign_sets, build_coeffs

    devs, model = load_from_profile_folder(profiles_dir / "hermes_70b")
    gap = 1e-4
    per_k = halda_solve_per_k(devs, model, mip_gap=gap, kv_bits="4bit")
    assert len(per_k) >= 8  # every feasible k came back with an assignment

    coeffs = build_coeffs(
        devs, model, kv_bits_to_factor("4bit"), assign_sets(devs)
    )
    arrays = assemble(coeffs)
    for r in per_k:
        assert r.certified and r.gap is not None and r.gap <= gap
        assert sum(r.w) * r.k == model.L
        assert all(0 <= n <= w for w, n in zip(r.w, r.n))
        oracle = solve_fixed_k_cpu(arrays, r.k, model.L // r.k, mip_gap=gap)
        tol = 2 * gap * abs(oracle.obj_value) + 1e-9
        assert abs(r.obj_value - oracle.obj_value) <= tol, (
            f"k={r.k}: per-k {r.obj_value} vs oracle {oracle.obj_value}"
        )


def test_per_k_optima_multi_device(profiles_dir):
    """Per-k mode on a heterogeneous fleet: losing k's must NOT be pruned
    by the global winner — each closes its own certificate."""
    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver.api import halda_solve_per_k

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(5, seed=11)
    gap = 1e-3
    per_k = halda_solve_per_k(devs, model, mip_gap=gap, kv_bits="4bit")
    assert len(per_k) >= 2
    objs = [r.obj_value for r in per_k]
    best = halda_solve(devs, model, mip_gap=gap, kv_bits="4bit", backend="jax")
    assert min(objs) <= best.obj_value + 2 * gap * abs(best.obj_value)
    for r in per_k:
        assert r.certified
        assert sum(r.w) * r.k == model.L


def test_per_k_truncated_budget_never_fabricates_certificates(profiles_dir):
    """A per-k sweep cut off at one round must not claim certificates for
    k's that never closed (or never explored) their own gap — it warns and
    marks them certified=False; an unexplored k reports gap=None."""
    from distilp_tpu.common import load_from_profile_folder
    from distilp_tpu.solver.api import halda_solve_per_k

    devs, model = load_from_profile_folder(profiles_dir / "hermes_70b")
    with pytest.warns(RuntimeWarning):
        per_k = halda_solve_per_k(
            devs, model, mip_gap=1e-9, kv_bits="4bit", max_rounds=1
        )
    assert any(not r.certified for r in per_k)
    for r in per_k:
        if not r.certified:
            assert r.gap is None or r.gap > 1e-9


def test_scenario_batched_moe_warm_with_duals(profiles_dir):
    """MoE scenario batching seeded by previous results: the persisted
    Lagrangian duals ride the dynamic blobs (has_duals engages only when
    every scenario carries a usable set) and each warm re-batch stays
    certified, matching its cold counterpart — the vmapped warm+duals
    layout compiles and prices correctly."""
    import numpy as np

    from distilp_tpu.profiler.api import profile_model
    from distilp_tpu.solver.api import halda_solve_scenarios
    from distilp_tpu.utils import make_synthetic_fleet

    model = profile_model(
        str(profiles_dir.parent / "configs" / "mixtral_8x7b.json"),
        batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()
    gap = 1e-3
    rng = np.random.default_rng(71)
    scenarios = []
    for _ in range(3):
        devs = make_synthetic_fleet(4, seed=71, pool_bytes=int(64e9))
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.8, 1.3)))
        scenarios.append(devs)

    cold = halda_solve_scenarios(scenarios, model, kv_bits="8bit", mip_gap=gap)
    assert all(r.certified and r.duals is not None for r in cold)
    warm = halda_solve_scenarios(
        scenarios, model, kv_bits="8bit", mip_gap=gap, warms=cold
    )
    for c, w in zip(cold, warm):
        assert w.certified
        assert sum(w.y) == model.n_routed_experts
        tol = 2 * gap * abs(c.obj_value) + 1e-9
        assert abs(w.obj_value - c.obj_value) <= tol


def test_per_k_cpu_backend_matches_jax(profiles_dir):
    """halda_solve_per_k(backend='cpu'): the HiGHS loop must return the
    same k set with matching objectives as the one-dispatch JAX sweep
    (VERDICT r5 item 7 — --per-k without a JAX install)."""
    from distilp_tpu.common import load_model_profile
    from distilp_tpu.solver.api import halda_solve_per_k

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(4, seed=11)
    gap = 1e-3
    ks = [4, 8, 10]
    via_jax = halda_solve_per_k(
        devs, model, k_candidates=ks, mip_gap=gap, kv_bits="4bit"
    )
    via_cpu = halda_solve_per_k(
        devs, model, k_candidates=ks, mip_gap=gap, kv_bits="4bit",
        backend="cpu",
    )
    assert [r.k for r in via_cpu] == [r.k for r in via_jax]
    for c, j in zip(via_cpu, via_jax):
        assert c.certified  # HiGHS optima are exact
        assert sum(c.w) * c.k == model.L
        tol = 2 * gap * abs(c.obj_value) + 1e-9
        assert abs(j.obj_value - c.obj_value) <= tol, (
            f"k={c.k}: cpu {c.obj_value} vs jax {j.obj_value}"
        )
    with pytest.raises(ValueError, match="backend"):
        halda_solve_per_k(devs, model, k_candidates=ks, backend="nope")


def test_halda_solve_escalates_uncertified_dense_defaults(profiles_dir, monkeypatch):
    """The in-solver certification ladder (VERDICT r5 item 4): a dense
    solve that misses its certificate at the class-default budgets retries
    once at the MoE-class budget before returning. Starving the DENSE
    defaults (frontier beam 1, 2 IPM iterations — well past the documented
    beam-4/6-iters edges) makes the first attempt miss deterministically;
    plain halda_solve must come back certified anyway, reporting the
    escalation, while explicit caller budgets stay honest (no silent
    override of an owner's trade-off)."""
    import numpy as np

    import distilp_tpu.solver.backend_jax as bj
    from distilp_tpu.common import load_model_profile

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(6, seed=11)
    rng = np.random.default_rng(11)
    for d in devs:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.3, 3.0)))

    orig = bj.default_search_params
    monkeypatch.setattr(
        bj,
        "default_search_params",
        lambda moe, n_k: (max(10, n_k), 1, 2) if not moe else orig(moe, n_k),
    )
    gap = 1e-3
    tm: dict = {}
    got = halda_solve(
        devs, model, mip_gap=gap, kv_bits="4bit", backend="jax", timings=tm
    )
    assert got.certified
    assert tm.get("escalated") == 1
    ref = halda_solve(devs, model, mip_gap=gap, kv_bits="4bit", backend="cpu")
    tol = 2 * gap * abs(ref.obj_value) + 1e-9
    assert abs(got.obj_value - ref.obj_value) <= tol

    # Explicit budgets: the caller owns the trade-off — no escalation,
    # honest certificate either way.
    tm2: dict = {}
    explicit = halda_solve(
        devs, model, mip_gap=gap, kv_bits="4bit", backend="jax",
        node_cap=10, beam=1, ipm_iters=2, timings=tm2,
    )
    assert tm2.get("escalated") is None
    if not explicit.certified:
        assert explicit.gap is None or explicit.gap > gap  # honest miss


def test_fuzz_dense_defaults_always_certify(profiles_dir):
    """No dense fuzz instance may return uncertified through plain
    halda_solve at default budgets — the documented budget edges are now
    backstopped by the in-solver escalation ladder, so the honest-but-
    uncertified window at defaults is closed."""
    import numpy as np

    from distilp_tpu.common import load_model_profile

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    for seed in (11, 23, 37):
        rng = np.random.default_rng(seed)
        M = int(rng.choice([3, 5, 8]))
        devs = make_synthetic_fleet(M, seed=seed)
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.3, 3.0)))
            d.s_disk = max(1e6, d.s_disk * float(rng.uniform(0.3, 3.0)))
            d.d_avail_ram = max(
                int(1e9), int(d.d_avail_ram * rng.uniform(0.5, 2.0))
            )
        got = halda_solve(
            devs, model, mip_gap=1e-3, kv_bits="4bit", backend="jax"
        )
        assert got.certified, f"seed {seed} (M={M}) uncertified at defaults"
        assert sum(got.w) * got.k == model.L


def test_compile_cache_env_gate(tmp_path):
    """DISTILP_COMPILE_CACHE (VERDICT r5 item 3) must point JAX's
    persistent compilation cache at the directory — checked in a fresh
    subprocess because the config must land at first backend import, and
    this process has long since imported jax."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["DISTILP_COMPILE_CACHE"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    src = (
        "import distilp_tpu.solver.backend_jax, jax; "
        "print('CACHE', jax.config.jax_compilation_cache_dir)"
    )
    out = subprocess.run(
        [sys.executable, "-c", src],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
    )
    assert out.returncode == 0, out.stderr[-500:]
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("CACHE ")]
    assert line and line[0].split(" ", 1)[1] == str(tmp_path)

"""Solver-interior convergence telemetry: the in-jit LP trace, the B&B
round log, the obs.convergence reports, and the `solver diagnose` CLI.

The two load-bearing contracts pinned here:

1. **Byte-identical off-path.** With tracing off, the kernels and the
   packed sweep produce bit-for-bit the same outputs as with tracing on
   (trace buffers excluded) — telemetry reads the iteration, it never
   steers it.
2. **Exact accounting.** The per-round LP iteration counts sum to the
   `ipm_iters_executed` header counter, the per-round gap trajectory is
   monotone non-increasing, and each element's last live trace row agrees
   with its `iters_run`.

Integration tests reuse the llama-70B profile + M=4 synthetic fleet and
the [8, 10] k-grid other modules compile, so post-compile solves are fast.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from distilp_tpu.obs.convergence import (
    build_search_trace,
    search_trace_from_jsonl,
    search_trace_to_jsonl,
)

GAP = 1e-3
KS = [8, 10]  # proper factors of L=80


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.common import load_model_profile

    return load_model_profile(
        "tests/profiles/llama_3_70b/online/model_profile.json"
    )


@pytest.fixture(scope="module")
def fleet():
    from distilp_tpu.utils import make_synthetic_fleet

    return make_synthetic_fleet(4, seed=11)


def tiny_batch(B=3, m=5, n=9):
    """A small feasible boxed-LP batch (shared A, b at the box midpoint)."""
    import jax.numpy as jnp

    from distilp_tpu.ops.ipm import LPBatch

    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.normal(size=(m, n)), jnp.float32)
    c = jnp.asarray(rng.normal(size=(B, n)), jnp.float32)
    l = jnp.zeros((B, n), jnp.float32)
    u = jnp.full((B, n), 2.0, jnp.float32)
    b = jnp.einsum("mn,bn->bm", A, jnp.ones((B, n), jnp.float32))
    return LPBatch(A=A, b=b, c=c, l=l, u=u)


# -- kernel-level contracts -------------------------------------------------


@pytest.mark.parametrize("engine", ["ipm", "pdhg"])
def test_trace_off_on_bit_identical(engine):
    """The traced solve's result fields equal the untraced solve's bit for
    bit — the trace rides the carry, it never feeds back."""
    from distilp_tpu.ops.ipm import ipm_solve_batch
    from distilp_tpu.ops.pdhg import pdhg_solve_batch

    batch = tiny_batch()
    if engine == "ipm":
        r0 = ipm_solve_batch(batch, iters=20)
        r1 = ipm_solve_batch(batch, iters=20, trace=True)
    else:
        r0 = pdhg_solve_batch(batch, iters=200)
        r1 = pdhg_solve_batch(batch, iters=200, trace=True)
    assert r0.trace_buf is None
    assert r1.trace_buf is not None
    for f in r0._fields:
        if f == "trace_buf":
            continue
        assert np.array_equal(
            np.asarray(getattr(r0, f)), np.asarray(getattr(r1, f))
        ), f"{engine}: field {f} diverged under tracing"


@pytest.mark.parametrize("engine", ["ipm", "pdhg"])
def test_trace_rows_account_for_iters(engine):
    """Per-element: live rows carry monotone cumulative iteration counts,
    the last live row equals iters_run, and rows are finite."""
    from distilp_tpu.ops.ipm import TRACE_COLS, ipm_solve_batch
    from distilp_tpu.ops.pdhg import pdhg_solve_batch

    batch = tiny_batch()
    if engine == "ipm":
        res = ipm_solve_batch(batch, iters=20, trace=True)
    else:
        res = pdhg_solve_batch(batch, iters=200, trace=True)
    tb = np.asarray(res.trace_buf)
    iters_run = np.asarray(res.iters_run)
    assert tb.shape[0] == len(iters_run) and tb.shape[2] == TRACE_COLS
    for e in range(tb.shape[0]):
        live = tb[e][tb[e][:, 5] > 0.5]
        assert len(live) >= 1
        assert np.all(np.diff(live[:, 0]) > 0)  # iters strictly increase
        assert live[-1, 0] == iters_run[e]
        assert np.all(np.isfinite(live))
        # restarts are cumulative: non-decreasing, and zero for the IPM.
        assert np.all(np.diff(live[:, 4]) >= 0)
        if engine == "ipm":
            assert np.all(live[:, 4] == 0)


def test_pdhg_skip_element_has_no_live_rows():
    import jax.numpy as jnp

    from distilp_tpu.ops.pdhg import pdhg_solve_batch

    batch = tiny_batch()
    skip = jnp.asarray([True, False, False])
    res = pdhg_solve_batch(batch, iters=64, skip=skip, trace=True)
    tb = np.asarray(res.trace_buf)
    assert not np.any(tb[0][:, 5] > 0.5)  # skipped element never live
    assert np.any(tb[1][:, 5] > 0.5)


# -- sweep-level contracts --------------------------------------------------


@pytest.mark.parametrize("engine", ["ipm", "pdhg"])
def test_sweep_convergence_report(model, fleet, engine):
    """halda_solve(convergence=...) yields a SearchTrace whose per-round
    LP iteration counts sum EXACTLY to the executed-iteration counter and
    whose gap trajectory is monotone non-increasing; the digest rides the
    timings dict."""
    from distilp_tpu.solver import halda_solve

    tm: dict = {}
    conv: dict = {}
    res = halda_solve(
        fleet, model, k_candidates=KS, mip_gap=GAP, kv_bits="4bit",
        backend="jax", lp_backend=engine, timings=tm, convergence=conv,
    )
    trace = build_search_trace(conv)
    assert trace.lp_backend == engine
    assert trace.rounds, "no rounds recorded"
    assert sum(r.lp_iters for r in trace.rounds) == trace.lp_iters_executed
    assert trace.lp_iters_executed == int(round(tm["ipm_iters_executed"]))
    gaps = [r.gap for r in trace.rounds if r.gap is not None]
    assert all(a >= b - 1e-12 for a, b in zip(gaps, gaps[1:])), gaps
    if res.certified:
        assert trace.certified
        assert trace.final_gap is not None and trace.final_gap <= GAP + 1e-12
        assert trace.rounds_to_certify is not None
        assert trace.iters_to_certify is not None
    # digest landed in timings for the span/flight plumbing
    assert tm["conv_rounds"] == len(trace.rounds)
    assert tm["conv_lp_iters"] == trace.lp_iters_executed
    assert tm["conv_certified"] == trace.certified
    # root traces cover the k grid and the PDHG engine reports restarts
    assert [t.k for t in trace.root_traces] == KS
    if engine == "pdhg":
        assert trace.restarts > 0


def test_untraced_solve_identical_to_traced(model, fleet):
    """The byte-identical contract one level up: solving with and without
    the convergence dict gives the same placement, objective, certificate
    and device-side work counters."""
    from distilp_tpu.solver import halda_solve

    tm0: dict = {}
    r0 = halda_solve(
        fleet, model, k_candidates=KS, mip_gap=GAP, kv_bits="4bit",
        backend="jax", timings=tm0,
    )
    tm1: dict = {}
    r1 = halda_solve(
        fleet, model, k_candidates=KS, mip_gap=GAP, kv_bits="4bit",
        backend="jax", timings=tm1, convergence={},
    )
    assert (r0.k, r0.w, r0.n, r0.obj_value, r0.certified) == (
        r1.k, r1.w, r1.n, r1.obj_value, r1.certified
    )
    assert tm0["ipm_iters_executed"] == tm1["ipm_iters_executed"]
    assert tm0["bnb_rounds"] == tm1["bnb_rounds"]


def test_streaming_diagnostics_flag(model, fleet):
    from distilp_tpu.solver.streaming import StreamingReplanner

    planner = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax", diagnostics=True
    )
    tm: dict = {}
    planner.step(list(fleet), model, k_candidates=KS, timings=tm)
    assert planner.last_convergence.get("round_log")
    assert "conv_rounds" in tm
    trace = build_search_trace(planner.last_convergence)
    assert trace.rounds
    # a warm tick refreshes the report
    planner.step(list(fleet), model, k_candidates=KS, timings=tm)
    assert build_search_trace(planner.last_convergence).rounds


def test_pipelined_diagnostics_refresh(model, fleet):
    """submit()/collect() ticks refresh last_convergence too — a stale
    sync-tick report must never be read as the pipelined tick's."""
    from distilp_tpu.solver.streaming import StreamingReplanner

    planner = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax", diagnostics=True
    )
    planner.step(list(fleet), model, k_candidates=KS)
    first = planner.last_convergence
    assert first.get("round_log")
    planner.submit(list(fleet), model, k_candidates=KS)
    res = planner.collect()
    assert res is not None
    assert planner.last_convergence is not first
    trace = build_search_trace(planner.last_convergence)
    assert trace.rounds
    assert sum(r.lp_iters for r in trace.rounds) == trace.lp_iters_executed


# -- report layer -----------------------------------------------------------


def test_jsonl_roundtrip(model, fleet):
    from distilp_tpu.solver import halda_solve

    conv: dict = {}
    halda_solve(
        fleet, model, k_candidates=KS, mip_gap=GAP, kv_bits="4bit",
        backend="jax", convergence=conv,
    )
    trace = build_search_trace(conv)
    back = search_trace_from_jsonl(search_trace_to_jsonl(trace))
    assert back == trace
    assert back.digest() == trace.digest()
    assert "round" in trace.render_text()


def test_digest_keys_match_registry(model, fleet):
    """Every digest field is enumerated in CONV_DIGEST_KEYS (the one list
    the scheduler's span/flight plumbing filters by), and a certified
    solve emits the full set — a key added to digest() but not the
    registry would silently vanish from spans and flight records."""
    from distilp_tpu.obs.convergence import CONV_DIGEST_KEYS
    from distilp_tpu.solver import halda_solve

    conv: dict = {}
    halda_solve(
        fleet, model, k_candidates=KS, mip_gap=GAP, kv_bits="4bit",
        backend="jax", convergence=conv,
    )
    digest = build_search_trace(conv).digest()
    assert set(digest) <= set(CONV_DIGEST_KEYS)
    assert set(digest) == set(CONV_DIGEST_KEYS)  # certified: every field


def test_jsonl_rejects_malformed():
    with pytest.raises(ValueError):
        search_trace_from_jsonl('{"type": "round", "round": 0}\n')
    with pytest.raises(ValueError):
        search_trace_from_jsonl('{"type": "mystery"}\n')


def test_build_search_trace_handles_sentinels():
    """±inf sentinels (no incumbent / exhausted bound) decode to honest
    None/0.0 facts, never to NaN-laden reports."""
    conv = {
        "lp_backend": "ipm",
        "mip_gap": 1e-3,
        "ks": [4],
        "incumbent": float("inf"),
        "best_bound": float("-inf"),
        "ipm_iters_executed": 8.0,
        "bnb_rounds": 1.0,
        "round_log": [[0, 1.0, 2.0, float("inf"), float("-inf"), 8.0]],
        "root_trace": [[[8.0, 1e-5, 1e-6, 1e-7, 0.0, 1.0]]],
    }
    tr = build_search_trace(conv)
    assert tr.incumbent is None and tr.best_bound is None
    assert not tr.certified and tr.final_gap is None
    assert tr.rounds[0].gap is None
    # exhausted (+inf) bound = gap closed
    conv["best_bound"] = float("inf")
    conv["incumbent"] = 5.0
    assert build_search_trace(conv).final_gap == 0.0


# -- the diagnose CLI -------------------------------------------------------


def test_diagnose_cli_roundtrip(tmp_path, capsys):
    from distilp_tpu.cli.solver_cli import diagnose_main

    out = tmp_path / "diag.jsonl"
    rc = diagnose_main(
        [
            "--profile", "tests/profiles/llama_3_70b/online",
            "--synthetic-fleet", "4", "--fleet-seed", "11",
            "--k-candidates", "8,10", "--mip-gap", str(GAP),
            "--json", "--out", str(out),
        ]
    )
    assert rc == 0
    payload = json.loads(capsys.readouterr().out.strip())
    assert payload["rounds"]
    assert payload["digest"]["conv_rounds"] == len(payload["rounds"])
    assert sum(r["lp_iters"] for r in payload["rounds"]) == payload[
        "lp_iters_executed"
    ]
    # --load renders the export without a solve (or a backend)
    rc = diagnose_main(["--load", str(out)])
    assert rc == 0
    assert "search:" in capsys.readouterr().out
    # and the export round-trips through the report layer
    trace = search_trace_from_jsonl(out.read_text())
    assert trace.rounds and trace.lp_iters_executed == payload[
        "lp_iters_executed"
    ]


def test_diagnose_cli_rejects_bad_input(tmp_path, capsys):
    from distilp_tpu.cli.solver_cli import diagnose_main

    assert diagnose_main([]) == 2  # no --profile, no --load
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json\n")
    assert diagnose_main(["--load", str(bad)]) == 2
    capsys.readouterr()

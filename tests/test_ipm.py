"""IPM kernel tests: random boxed LPs vs scipy linprog, bound validity."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from distilp_tpu.ops import LPBatch, ipm_solve_batch  # noqa: E402


def _random_feasible_batch(rng, m, n, B, fix_frac=0.2):
    from scipy.optimize import linprog

    A = rng.normal(size=(m, n))
    bs, cs, ls, us, refs = [], [], [], [], []
    for _ in range(B):
        l = rng.uniform(-2, 0, n)
        u = l + rng.uniform(0.5, 3, n)
        fix = rng.random(n) < fix_frac
        u = np.where(fix, l, u)
        x_feas = l + rng.uniform(0, 1, n) * (u - l)
        b = A @ x_feas
        c = rng.normal(size=n)
        r = linprog(c, A_eq=A, b_eq=b, bounds=np.stack([l, u], 1), method="highs")
        assert r.status == 0
        refs.append(r.fun)
        bs.append(b)
        cs.append(c)
        ls.append(l)
        us.append(u)
    batch = LPBatch(
        jnp.array(A), jnp.array(bs), jnp.array(cs), jnp.array(ls), jnp.array(us)
    )
    return batch, np.array(refs)


def test_ipm_matches_scipy_on_random_lps():
    rng = np.random.default_rng(42)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=16)
    res = ipm_solve_batch(batch, iters=50)
    assert np.all(np.array(res.converged))
    np.testing.assert_allclose(np.array(res.obj), refs, rtol=1e-8, atol=1e-8)
    # The Lagrangian bound must be a valid lower bound on the true optimum.
    assert np.all(np.array(res.bound) <= refs + 1e-8)
    # ...and tight at convergence.
    np.testing.assert_allclose(np.array(res.bound), refs, rtol=1e-6, atol=1e-6)


def test_ipm_no_nan_with_extra_iterations():
    """Iterating far past convergence must not corrupt the frozen solution."""
    rng = np.random.default_rng(7)
    batch, refs = _random_feasible_batch(rng, m=6, n=14, B=4, fix_frac=0.0)
    res = ipm_solve_batch(batch, iters=200)
    assert np.all(np.isfinite(np.array(res.obj)))
    assert np.all(np.isfinite(np.array(res.bound)))
    np.testing.assert_allclose(np.array(res.obj), refs, rtol=1e-8, atol=1e-8)


def test_ipm_all_columns_fixed():
    """A fully-fixed box (every branch variable pinned) must not blow up."""
    rng = np.random.default_rng(3)
    n, m = 8, 3
    A = rng.normal(size=(m, n))
    l = rng.uniform(0, 1, size=(1, n))
    u = l.copy()  # everything fixed
    b = (A @ l[0])[None, :]
    c = rng.normal(size=(1, n))
    res = ipm_solve_batch(
        LPBatch(jnp.array(A), jnp.array(b), jnp.array(c), jnp.array(l), jnp.array(u)),
        iters=20,
    )
    assert np.isfinite(float(res.obj[0]))
    assert float(res.obj[0]) == pytest.approx(float(c[0] @ l[0]))


def test_ipm_infeasible_bound_grows():
    """On an infeasible LP the Lagrangian bound should exceed any feasible-
    looking value, so branch-and-bound prunes the node."""
    A = jnp.array([[1.0, 1.0]])
    b = jnp.array([[10.0]])  # x1 + x2 = 10 but boxes cap at 2
    c = jnp.array([[1.0, 1.0]])
    l = jnp.zeros((1, 2))
    u = jnp.full((1, 2), 1.0)
    res = ipm_solve_batch(LPBatch(A, b, c, l, u), iters=60)
    # Any feasible point would cost <= 2; the bound must blow past that.
    assert float(res.bound[0]) > 2.0

"""IPM kernel tests: random boxed LPs vs scipy linprog, bound validity."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from distilp_tpu.ops import IPMWarmState, LPBatch, ipm_solve_batch  # noqa: E402


def _random_feasible_batch(rng, m, n, B, fix_frac=0.2):
    from scipy.optimize import linprog

    A = rng.normal(size=(m, n))
    bs, cs, ls, us, refs = [], [], [], [], []
    for _ in range(B):
        l = rng.uniform(-2, 0, n)
        u = l + rng.uniform(0.5, 3, n)
        fix = rng.random(n) < fix_frac
        u = np.where(fix, l, u)
        x_feas = l + rng.uniform(0, 1, n) * (u - l)
        b = A @ x_feas
        c = rng.normal(size=n)
        r = linprog(c, A_eq=A, b_eq=b, bounds=np.stack([l, u], 1), method="highs")
        assert r.status == 0
        refs.append(r.fun)
        bs.append(b)
        cs.append(c)
        ls.append(l)
        us.append(u)
    batch = LPBatch(
        jnp.array(A), jnp.array(bs), jnp.array(cs), jnp.array(ls), jnp.array(us)
    )
    return batch, np.array(refs)


def test_ipm_matches_scipy_on_random_lps():
    rng = np.random.default_rng(42)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=16)
    res = ipm_solve_batch(batch, iters=50)
    assert np.all(np.array(res.converged))
    np.testing.assert_allclose(np.array(res.obj), refs, rtol=1e-8, atol=1e-8)
    # The Lagrangian bound must be a valid lower bound on the true optimum.
    assert np.all(np.array(res.bound) <= refs + 1e-8)
    # ...and tight at convergence.
    np.testing.assert_allclose(np.array(res.bound), refs, rtol=1e-6, atol=1e-6)


def test_ipm_no_nan_with_extra_iterations():
    """Iterating far past convergence must not corrupt the frozen solution."""
    rng = np.random.default_rng(7)
    batch, refs = _random_feasible_batch(rng, m=6, n=14, B=4, fix_frac=0.0)
    res = ipm_solve_batch(batch, iters=200)
    assert np.all(np.isfinite(np.array(res.obj)))
    assert np.all(np.isfinite(np.array(res.bound)))
    np.testing.assert_allclose(np.array(res.obj), refs, rtol=1e-8, atol=1e-8)


def test_ipm_all_columns_fixed():
    """A fully-fixed box (every branch variable pinned) must not blow up."""
    rng = np.random.default_rng(3)
    n, m = 8, 3
    A = rng.normal(size=(m, n))
    l = rng.uniform(0, 1, size=(1, n))
    u = l.copy()  # everything fixed
    b = (A @ l[0])[None, :]
    c = rng.normal(size=(1, n))
    res = ipm_solve_batch(
        LPBatch(jnp.array(A), jnp.array(b), jnp.array(c), jnp.array(l), jnp.array(u)),
        iters=20,
    )
    assert np.isfinite(float(res.obj[0]))
    assert float(res.obj[0]) == pytest.approx(float(c[0] @ l[0]))


def _warm_from(res, B):
    return IPMWarmState(
        v=res.v, y=res.y_dual, z=res.z_dual, f=res.f_dual,
        ok=jnp.ones(B, bool),
    )


def test_ipm_warm_start_matches_cold_and_early_exits():
    """(a) A warm-started solve must reach the cold solve's certified
    objective/bound, and do so in strictly fewer iterations (the whole
    point of carrying iterates across B&B nodes and streaming ticks)."""
    rng = np.random.default_rng(11)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=12)
    cold = ipm_solve_batch(batch, iters=50)
    assert np.all(np.array(cold.converged))
    warm = ipm_solve_batch(batch, iters=50, warm=_warm_from(cold, 12))
    assert np.all(np.array(warm.converged))
    np.testing.assert_allclose(
        np.array(warm.obj), np.array(cold.obj), rtol=1e-6, atol=1e-8
    )
    # Bound validity is independent of the start point.
    assert np.all(np.array(warm.bound) <= refs + 1e-8)
    assert np.array(warm.iters_run).max() < np.array(cold.iters_run).max()


def test_ipm_early_exit_stops_before_budget():
    """The chunked while_loop must stop once the batch converges instead of
    scanning out the fixed budget (iters_run is the executed count)."""
    rng = np.random.default_rng(5)
    batch, _ = _random_feasible_batch(rng, m=8, n=20, B=6)
    res = ipm_solve_batch(batch, iters=200)
    assert np.all(np.array(res.converged))
    assert np.array(res.iters_run).max() < 40  # nowhere near 200


def test_ipm_truncated_budget_bound_stays_sound():
    """(b) An early-exited / truncated solve must still return a rigorous
    float64 lower bound (bound <= true optimum) — branch-and-bound prunes
    on it, so this is the soundness half of the warm-start contract."""
    rng = np.random.default_rng(21)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=12)
    for iters in (2, 3, 5, 8):
        res = ipm_solve_batch(batch, iters=iters, chunk=2)
        b = np.array(res.bound)
        assert np.all(np.isfinite(b) | np.isneginf(b))
        assert np.all(b <= refs + 1e-8), f"unsound bound at iters={iters}"


def test_ipm_garbage_warm_state_degrades_to_cold():
    """(c) NaN/inf warm components must fall back to the cold start, and
    finite-but-absurd warm points must still converge to the cold result —
    a stale streaming iterate can cost iterations, never correctness."""
    rng = np.random.default_rng(33)
    B = 8
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=B)
    cold = ipm_solve_batch(batch, iters=60)

    bad = IPMWarmState(
        v=jnp.full_like(cold.v, jnp.nan),
        y=jnp.full_like(cold.y_dual, jnp.inf),
        z=cold.z_dual,
        f=cold.f_dual,
        ok=jnp.ones(B, bool),
    )
    res = ipm_solve_batch(batch, iters=60, warm=bad)
    np.testing.assert_allclose(
        np.array(res.obj), np.array(cold.obj), rtol=1e-7, atol=1e-8
    )

    absurd = IPMWarmState(
        v=1e6 * jnp.ones_like(cold.v),
        y=-1e5 * jnp.ones_like(cold.y_dual),
        z=1e9 * jnp.ones_like(cold.z_dual),
        f=1e-12 * jnp.ones_like(cold.f_dual),
        ok=jnp.ones(B, bool),
    )
    res2 = ipm_solve_batch(batch, iters=60, warm=absurd)
    assert np.all(np.array(res2.converged))
    np.testing.assert_allclose(
        np.array(res2.obj), np.array(cold.obj), rtol=1e-6, atol=1e-7
    )
    assert np.all(np.array(res2.bound) <= refs + 1e-8)

    # ok=False must behave exactly like no warm state at all.
    off = IPMWarmState(
        v=absurd.v, y=absurd.y, z=absurd.z, f=absurd.f,
        ok=jnp.zeros(B, bool),
    )
    res3 = ipm_solve_batch(batch, iters=60, warm=off)
    np.testing.assert_allclose(
        np.array(res3.obj), np.array(cold.obj), rtol=1e-9, atol=1e-10
    )


def test_ipm_skip_mask_freezes_elements():
    """Skipped elements execute zero iterations and never gate the batch
    early exit (inactive frontier rows ride this)."""
    rng = np.random.default_rng(44)
    B = 6
    batch, _ = _random_feasible_batch(rng, m=8, n=18, B=B)
    sk = jnp.zeros(B, bool).at[2].set(True)
    res = ipm_solve_batch(batch, iters=50, skip=sk)
    runs = np.array(res.iters_run)
    assert runs[2] == 0
    live = np.delete(np.arange(B), 2)
    assert np.all(runs[live] > 0)
    assert np.all(np.array(res.converged)[live])


def test_ipm_infeasible_bound_grows():
    """On an infeasible LP the Lagrangian bound should exceed any feasible-
    looking value, so branch-and-bound prunes the node."""
    A = jnp.array([[1.0, 1.0]])
    b = jnp.array([[10.0]])  # x1 + x2 = 10 but boxes cap at 2
    c = jnp.array([[1.0, 1.0]])
    l = jnp.zeros((1, 2))
    u = jnp.full((1, 2), 1.0)
    res = ipm_solve_batch(LPBatch(A, b, c, l, u), iters=60)
    # Any feasible point would cost <= 2; the bound must blow past that.
    assert float(res.bound[0]) > 2.0

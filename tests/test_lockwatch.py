"""Unit tests for the runtime lock sanitizer (distilp_tpu/utils/lockwatch).

The sanitizer is the dynamic half of dlint's DLP032: `make_lock` hands out
plain threading primitives in production and instrumented wrappers under
DLP_LOCKWATCH=1, recording per-thread acquisition order into a process-wide
observed graph that `python -m tools.dlint --check-lockwatch` validates
against the static one. These tests pin the wrapper mechanics; the
end-to-end static/observed comparison is `make smoke-lockwatch` and the
check_lockwatch tests in test_dlint.py.
"""

from __future__ import annotations

import json
import threading

import pytest

from distilp_tpu.utils import lockwatch


@pytest.fixture()
def watching(monkeypatch):
    """Sanitizer on, graph clean before AND after (the observed graph is
    process-global; leaking edges between tests would corrupt verdicts)."""
    monkeypatch.setenv("DLP_LOCKWATCH", "1")
    lockwatch.reset()
    yield
    lockwatch.reset()


def test_disabled_factory_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv("DLP_LOCKWATCH", raising=False)
    assert not lockwatch.enabled()
    lock = lockwatch.make_lock("t.plain")
    assert type(lock) is type(threading.Lock())
    cv = lockwatch.make_lock("t.cv", kind="condition")
    assert isinstance(cv, threading.Condition)
    # RLock's concrete type varies by implementation; behaviorally it must
    # be reentrant.
    rl = lockwatch.make_lock("t.rl", kind="rlock")
    with rl:
        with rl:
            pass


def test_nesting_records_acquisition_order_edges(watching):
    a = lockwatch.make_lock("t.a")
    b = lockwatch.make_lock("t.b")
    assert isinstance(a, lockwatch.WatchedLock)
    with a:
        with b:
            pass
    rep = lockwatch.report()
    assert rep["enabled"]
    assert {"t.a", "t.b"} <= set(rep["locks"])
    assert [(e["from"], e["to"]) for e in rep["edges"]] == [("t.a", "t.b")]
    assert rep["witnesses"] == []


def test_opposite_order_produces_cycle_witness(watching, monkeypatch, tmp_path):
    # Witness dumps go through the flight recorder; point them at a temp
    # dir so the test leaves no droppings.
    monkeypatch.setenv("DLP_LOCKWATCH_DIR", str(tmp_path))
    a = lockwatch.make_lock("t.a")
    b = lockwatch.make_lock("t.b")
    with a:
        with b:
            pass
    with b:
        with a:  # closes t.a -> t.b -> t.a
            pass
    rep = lockwatch.report()
    assert len(rep["witnesses"]) == 1
    w = rep["witnesses"][0]
    assert w["kind"] == "lock-order-cycle"
    assert w["edge"] == ["t.b", "t.a"]
    assert w["cycle"] == ["t.b", "t.a", "t.b"]
    assert w["held"] == ["t.b"]


def test_same_name_reacquire_records_no_self_edge(watching):
    # Names are type-granular: two instances sharing one name must not
    # manufacture a name -> name self-edge (the static graph has none).
    a1 = lockwatch.make_lock("t.same")
    a2 = lockwatch.make_lock("t.same")
    with a1:
        with a2:
            pass
    assert lockwatch.report()["edges"] == []


def test_condition_wait_releases_its_own_held_entry(watching):
    # During cv.wait the lock is RELEASED: a nested acquisition by the
    # wait's wakeup path must not look like cv -> other ordering. The
    # held stack must also survive the pop/re-push (timeout path).
    cv = lockwatch.make_lock("t.cv", kind="condition")
    other = lockwatch.make_lock("t.other")
    with cv:
        cv.wait(timeout=0.01)
        with other:
            pass
    rep = lockwatch.report()
    assert ("t.cv", "t.other") in {
        (e["from"], e["to"]) for e in rep["edges"]
    }
    assert rep["witnesses"] == []
    # Stack is clean: a fresh acquisition records no residual edges.
    lockwatch.reset()
    with other:
        pass
    assert lockwatch.report()["edges"] == []


def test_wait_for_predicate_round_trips_the_held_stack(watching):
    cv = lockwatch.make_lock("t.cv", kind="condition")
    hits = []
    with cv:
        cv.wait_for(lambda: hits.append(1) or True, timeout=0.01)
    assert hits
    lockwatch.reset()
    a = lockwatch.make_lock("t.a")
    with a:
        pass
    assert lockwatch.report()["edges"] == []


def test_cross_thread_orders_share_one_observed_graph(watching):
    a = lockwatch.make_lock("t.a")
    b = lockwatch.make_lock("t.b")

    def forward():
        with a:
            with b:
                pass

    t = threading.Thread(target=forward)
    t.start()
    t.join()
    with b:
        with a:  # the ABBA half, from the main thread
            pass
    rep = lockwatch.report()
    edges = {(e["from"], e["to"]) for e in rep["edges"]}
    assert edges == {("t.a", "t.b"), ("t.b", "t.a")}
    assert len(rep["witnesses"]) == 1


def test_reset_clears_graph_and_report_is_json_serializable(watching):
    a = lockwatch.make_lock("t.a")
    with a:
        pass
    assert lockwatch.report()["locks"]
    json.dumps(lockwatch.report())  # must survive DLP_LOCKWATCH_OUT
    lockwatch.reset()
    rep = lockwatch.report()
    assert rep["locks"] == [] and rep["edges"] == [] and rep["witnesses"] == []


def test_exit_report_written_only_when_out_and_enabled(
    watching, monkeypatch, tmp_path
):
    out = tmp_path / "lw.json"
    monkeypatch.setenv("DLP_LOCKWATCH_OUT", str(out))
    a = lockwatch.make_lock("t.a")
    b = lockwatch.make_lock("t.b")
    with a:
        with b:
            pass
    lockwatch._write_report_at_exit()
    rep = json.loads(out.read_text())
    assert [(e["from"], e["to"]) for e in rep["edges"]] == [("t.a", "t.b")]
    # Disabled (or OUT unset): never writes.
    out.unlink()
    monkeypatch.delenv("DLP_LOCKWATCH")
    lockwatch._write_report_at_exit()
    assert not out.exists()

"""XLA compile ledger (obs/compile_ledger.py): classification, storms,
persistence, and the serving-path integration.

Two tiers, following the repo's test economics:

- **Unit tier** (no solver): the ledger in wrap-the-jit fallback mode —
  plain python callables stand in for jitted entry points, so cause
  classification (cold / static-arg-flip / shape-bucket-change /
  recompile / cache-hit), storm detection, thread filtering and the
  byte-stable JSONL round trip are all pinned without compiling anything.
- **Solver tier**: real schedulers on the JAX CPU backend (test_sched's
  small-L recipe so jit compiles amortize across the module) pin the
  tick attribution (counters + span attrs + flight records) and THE
  invariant this module exists to guard: after warmup, steady-state
  warm/spec/spec_near serving records ZERO compile events — on both LP
  engines.

Every test that enables a ledger disables it in a finally: the ledger is
process-global, and a leaked one would mint ``compiles`` counters into
other tests' byte-identical serving pins.
"""

from __future__ import annotations

import threading

import pytest

from distilp_tpu.obs import compile_ledger as cl
from distilp_tpu.obs.compile_ledger import (
    CompileLedger,
    InstrumentedJit,
    instrument,
    ledger_from_jsonl,
    ledger_to_jsonl,
    render_report,
)

GAP = 1e-3
KS = [4, 8]


class _Arr:
    """Shape-carrying stand-in for an array (no numpy needed)."""

    def __init__(self, *shape, dtype="float32"):
        self.shape = tuple(shape)
        self.dtype = dtype


@pytest.fixture()
def ledger():
    led = CompileLedger(storm_threshold=3, storm_window_s=60.0)
    led.fallback = True  # wrap-the-jit mode: nothing real compiles
    cl.enable(led)
    try:
        yield led
    finally:
        cl.disable()


# -- unit tier: wrapper + classification ------------------------------------


def test_wrapper_is_passthrough_with_no_ledger():
    assert cl.current() is None
    calls = []
    fn = instrument("tests.passthrough", lambda x: calls.append(x) or x)
    assert isinstance(fn, InstrumentedJit)
    assert fn(7) == 7 and calls == [7]
    # Registered at import/instrument time regardless of enablement.
    assert "tests.passthrough" in cl.registered_entry_points()


def test_fallback_classifies_cold_flip_and_shape(ledger):
    fn = instrument(
        "tests.kernel", lambda batch, n=1: batch, static_argnames=("n",)
    )
    a, b = _Arr(2, 3), _Arr(4, 5)
    fn(a, n=1)  # first signature ever -> cold
    fn(a, n=2)  # same shapes, new static -> static_arg_flip
    fn(b, n=2)  # same static, new shapes -> shape_bucket_change
    fn(a, n=1)  # seen signature -> NO new event in fallback mode
    causes = [e["cause"] for e in ledger.events_since(0)]
    assert causes == ["cold", "static_arg_flip", "shape_bucket_change"]
    assert ledger.dispatches["tests.kernel"] == 4
    assert ledger.counters()["compiles"] == 3
    ev = ledger.events_since(0)[1]
    assert "n=2" in ev["static"]
    assert "float32[2, 3]" in ev["shapes"]


def test_shape_signature_flattens_containers(ledger):
    fn = instrument("tests.tree", lambda data: data)
    fn({"b": _Arr(2), "a": (_Arr(3), None, 5.0)})
    sig = ledger.events_since(0)[0]["shapes"]
    # dict keys sorted, nested tuple flattened, non-arrays skipped.
    assert sig == "float32[3];float32[2]"


def test_recompile_cause_and_storm_alarm():
    led = CompileLedger(storm_threshold=3, storm_window_s=60.0)
    for i in range(4):
        ev = led.note_compile("tests.hot", "n=1", "f32[2]", ms=10.0)
    events = list(led.events)
    assert [e["cause"] for e in events] == [
        "cold", "recompile", "recompile", "recompile"
    ]
    # Storm flags from the threshold on; the storm COUNTER is the
    # transition (one alarm per storm, however long it lasts), and the
    # transition event alone carries storm_start — what the scheduler's
    # recompile_storms counter tallies, so metric and ledger agree.
    assert [bool(e.get("storm")) for e in events] == [
        False, False, True, True
    ]
    assert [bool(e.get("storm_start")) for e in events] == [
        False, False, True, False
    ]
    assert led.storms == 1
    assert ev["storm"] is True
    # A different entry under threshold stays unflagged.
    led.note_compile("tests.cool", "n=1", "f32[2]", ms=1.0)
    assert "storm" not in list(led.events)[-1]


def test_cache_hit_cause_and_hit_rate():
    led = CompileLedger()
    led.note_compile("tests.k", "n=1", "s", ms=100.0, cache="miss")
    led.note_compile("tests.k", "n=2", "s", ms=20.0, cache="hit")
    assert [e["cause"] for e in led.events] == ["cold", "cache_hit"]
    assert led.cache_hit_rate() == pytest.approx(0.5)
    assert led.counters()["compile_cache_hits"] == 1
    # No persistent cache engaged at all -> None, not 0.0.
    assert CompileLedger().cache_hit_rate() is None


def test_unregistered_attribution(ledger):
    # A compile landing with no entry context (inline jit, dependency
    # compile) is counted under the sentinel bucket — the dynamic view
    # of what DLP020 guards statically.
    ledger._compile_from_listener(50.0, cache=None)
    ev = ledger.events_since(0)[-1]
    assert ev["entry"] == "(unregistered)"
    assert ledger.counters()["unattributed_compiles"] == 1
    assert "NO" in render_report(ledger.dump())


def test_events_since_token_and_thread_filter(ledger):
    fn = instrument("tests.threads", lambda x: x)
    fn(_Arr(1))
    tok = ledger.seq()
    other: list = []
    t = threading.Thread(target=lambda: other.append(fn(_Arr(2))))
    t.start()
    t.join()
    fn(_Arr(3))
    all_since = ledger.events_since(tok)
    assert len(all_since) == 2
    mine = ledger.events_since(tok, threads={threading.get_ident()})
    assert len(mine) == 1 and "float32[3]" in mine[0]["shapes"]


def test_jsonl_round_trip_byte_stable_and_report_deterministic(ledger):
    fn = instrument(
        "tests.dump", lambda x, n=0: x, static_argnames=("n",)
    )
    fn(_Arr(2), n=1)
    fn(_Arr(2), n=2)
    text = ledger.to_jsonl()
    dump = ledger_from_jsonl(text)
    assert ledger_to_jsonl(dump) == text  # byte-stable round trip
    # Rendering a dump is a pure function: same dump, same bytes —
    # and it carries the table, causes, and offender sections.
    r1, r2 = render_report(dump), render_report(ledger_from_jsonl(text))
    assert r1 == r2
    assert "tests.dump" in r1 and "static_arg_flip" in r1
    assert "top recompile offenders" in r1


def test_from_jsonl_rejects_bad_dumps():
    with pytest.raises(ValueError, match="empty"):
        ledger_from_jsonl("")
    with pytest.raises(ValueError, match="header"):
        ledger_from_jsonl('{"not": "a header"}')
    with pytest.raises(ValueError, match="version"):
        ledger_from_jsonl('{"compile_ledger": 99}')


def test_enable_reuses_and_disable_detaches():
    led = cl.enable()
    try:
        assert cl.current() is led
        led2 = CompileLedger()
        assert cl.enable(led2) is led2 and cl.current() is led2
    finally:
        assert cl.disable() is led2
        assert cl.current() is None


# -- solver tier: serving-path attribution ----------------------------------


@pytest.fixture(scope="module")
def model():
    from distilp_tpu.profiler.api import profile_model

    return profile_model(
        "tests/configs/llama31_8b_4bit.json", batch_sizes=[1],
        sequence_length=128,
    ).to_model_profile()


@pytest.fixture()
def fleet():
    from distilp_tpu.utils import make_synthetic_fleet

    return make_synthetic_fleet(4, seed=11)


def make_scheduler(fleet, model, **kw):
    from distilp_tpu.sched import Scheduler

    kw.setdefault("mip_gap", GAP)
    kw.setdefault("kv_bits", "4bit")
    kw.setdefault("backend", "jax")
    kw.setdefault("k_candidates", KS)
    return Scheduler(fleet, model, **kw)


def test_no_ledger_means_no_compile_counters(fleet, model):
    from distilp_tpu.sched import LoadTick

    assert cl.current() is None
    sched = make_scheduler(fleet, model)
    sched.handle(LoadTick(t_comm_jitter={fleet[1].name: 1.1}))
    assert "compiles" not in sched.metrics.counters
    assert "compile_ms" not in sched.metrics.hists
    sched.close()


def test_tick_attribution_counters_span_flight(fleet, model):
    from distilp_tpu.obs.flight import FlightRecorder
    from distilp_tpu.obs.trace import Tracer
    from distilp_tpu.sched import LoadTick

    led = cl.enable()
    try:
        tracer = Tracer(capacity=256)
        flight = FlightRecorder()
        sched = make_scheduler(fleet, model, tracer=tracer, flight=flight)
        sched.handle(LoadTick(t_comm_jitter={fleet[1].name: 1.1}))
        sched.handle(LoadTick(t_comm_jitter={fleet[1].name: 1.1}))
        c = sched.metrics.counters
        recs = flight.snapshot("default")
        if c.get("compiles", 0):
            # Cold layouts not yet jit-cached by earlier tests in this
            # process: the tick(s) that paid say so, with causes.
            paid = [r for r in recs if "compile" in r]
            assert paid, "compiles counted but no flight record carries them"
            assert sum(r["compile"]["count"] for r in paid) == c["compiles"]
            assert all(r["compile"]["entries"] for r in paid)
            spans = [
                s for s in tracer.spans()
                if s["name"] == "sched.tick" and "compiles" in s["attrs"]
            ]
            assert (
                sum(s["attrs"]["compiles"] for s in spans) == c["compiles"]
            )
            assert sched.metrics.hists["compile_ms"].count == len(paid)
        else:
            # Everything was already compiled process-wide; then no tick
            # may claim otherwise.
            assert not any("compile" in r for r in recs)
            assert "compile_ms" not in sched.metrics.hists
        # Timeline sample always carries the ledger series while enabled.
        sample = sched.timeline_sample()
        assert sample["c.compiles"] == float(led.counters()["compiles"])
        assert "compile_ms" in sample
        sched.close()
    finally:
        cl.disable()


@pytest.mark.parametrize("lp_backend", ["ipm", "pdhg"])
def test_warm_serving_never_recompiles(fleet, model, lp_backend):
    """THE zero-recompile regression pin: after gateway-style warmup, a
    drift / spec-hit / spec_near tick sequence records ZERO compile
    events in the ledger — warm serving never silently recompiles. Until
    now this invariant was assumed (warmup conventions in every bench);
    this is the test that fails when a new static arg, a shape-unstable
    layout, or an inline jit sneaks onto the hot path."""
    from distilp_tpu.sched import LoadTick

    names = [d.name for d in fleet]
    led = cl.enable()
    try:
        sched = make_scheduler(
            fleet, model, speculative=True, lp_backend=lp_backend
        )
        up = LoadTick(t_comm_jitter={names[1]: 1.4, names[2]: 1.4})
        down = LoadTick(
            t_comm_jitter={names[1]: 1 / 1.4, names[2]: 1 / 1.4}
        )
        # Warmup: the cold layout, the warm layout, the speculative
        # scenario batch, and both oscillation states' bank entries all
        # compile/populate here.
        sched.handle(up)
        sched.handle(down)
        sched.handle(up)
        token = led.seq()
        # Steady state: plain drift (warm), oscillation (spec hits), and
        # a pressure tick served from the bank's near-match.
        v_warm = sched.handle(down)
        v_spec = sched.handle(up)
        v_near = sched.handle(
            LoadTick(t_comm_jitter={names[1]: 1.12}), pressure=True
        )
        assert v_warm.mode in ("warm", "spec")
        assert v_spec.mode == "spec"
        assert v_near.mode == "spec_near"
        stray = led.events_since(token)
        assert stray == [], (
            f"warm serving paid {len(stray)} compile(s) under "
            f"{lp_backend}: "
            + "; ".join(
                f"{e['entry']}[{e['cause']}] static=[{e['static']}]"
                for e in stray
            )
        )
        sched.close()
    finally:
        cl.disable()

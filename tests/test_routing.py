"""Load-weighted expert routing (solver.routing).

Counts alone cannot see skewed expert popularity; these tests pin that the
LPT mapper sends hot experts to fast devices, that the realized load
factors re-price the MILP consistently on BOTH backends, and that the
streaming loop carries the fixed point across ticks.
"""

from __future__ import annotations

import numpy as np
import pytest

from distilp_tpu.profiler.api import profile_model
from distilp_tpu.solver import halda_solve
from distilp_tpu.solver.routing import (
    expert_makespan,
    map_experts,
    normalize_loads,
    solve_load_aware,
)
from distilp_tpu.utils import make_synthetic_fleet

GAP = 1e-3


@pytest.fixture(scope="module")
def mixtral():
    split = profile_model(
        "tests/configs/mixtral_8x7b.json", batch_sizes=[1], sequence_length=128
    )
    return split.to_model_profile()


def test_normalize_loads():
    assert np.allclose(normalize_loads(None, 4), 1.0)
    q = normalize_loads([4.0, 2.0, 1.0, 1.0], 4)
    assert q.sum() == pytest.approx(4.0)
    assert q[0] == pytest.approx(2.0)
    with pytest.raises(ValueError):
        normalize_loads([1.0, 2.0], 4)  # wrong length
    with pytest.raises(ValueError):
        normalize_loads([1.0, -1.0, 1.0, 1.0], 4)  # negative


def test_map_experts_hot_to_fast():
    # Device 0 is 3x faster per y-unit (smaller g). One very hot expert.
    loads = normalize_loads([6.0, 1.0, 0.5, 0.5], 4)
    m = map_experts([2, 2], [1.0, 3.0], loads)
    # Every device got exactly its y_i experts.
    assert sorted(len(ids) for ids in m.expert_of_device) == [2, 2]
    # The hottest expert (id 0) is hosted by the fast device.
    assert 0 in m.expert_of_device[0]
    # The fast device serves more than its uniform share of the load.
    assert m.load_share[0] > 0.5
    assert m.factors[0] > 1.0 > m.factors[1]
    assert np.isclose(m.load_share.sum(), 1.0)
    # Makespan is priced at served load, not counts.
    ms = expert_makespan([1.0, 3.0], m)
    served = m.load_share * 4
    assert ms == pytest.approx(max(1.0 * served[0], 3.0 * served[1]))


def test_map_experts_rejects_count_mismatch():
    with pytest.raises(ValueError):
        map_experts([1, 1], [1.0, 1.0], normalize_loads(None, 4))


def test_solve_load_aware_beats_contiguous_mapping(mixtral):
    """Skewed popularity: the routed mapping's makespan must beat the naive
    contiguous (id-order) assignment, and hot experts must land on the
    accelerator devices."""
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    E = mixtral.n_routed_experts
    # Two hot experts carry half the routed load.
    raw = [4.0, 4.0] + [1.0] * (E - 2)
    result, mapping, realized = solve_load_aware(
        devs, mixtral, expert_loads=raw, iters=2,
        kv_bits="8bit", mip_gap=GAP, backend="jax",
    )
    assert result.certified
    assert sum(result.y) == E
    assert np.isfinite(realized)  # realized end-to-end objective is real
    loads = normalize_loads(raw, E)

    # Naive contiguous mapping of the same counts.
    from distilp_tpu.solver.moe import build_moe_arrays

    g = build_moe_arrays(devs, mixtral).g_raw
    naive_share = np.zeros(len(devs))
    e = 0
    for i, yi in enumerate(result.y):
        naive_share[i] = loads[e : e + yi].sum() / E
        e += yi
    naive_ms = float(np.max(g * naive_share * E))
    assert expert_makespan(g, mapping) <= naive_ms + 1e-12

    # The hot experts sit on devices whose per-unit busy is below average.
    host_of = {}
    for i, ids in enumerate(mapping.expert_of_device):
        for eid in ids:
            host_of[eid] = i
    hot_hosts = {host_of[0], host_of[1]}
    assert all(g[i] <= np.mean(g) for i in hot_hosts)


def test_load_aware_backends_match(mixtral):
    """Both backends must agree on the SAME load-factor-weighted instance."""
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    factors = [1.4, 0.8, 1.1, 0.7]
    ref = halda_solve(
        devs, mixtral, kv_bits="8bit", mip_gap=GAP, backend="cpu",
        load_factors=factors,
    )
    got = halda_solve(
        devs, mixtral, kv_bits="8bit", mip_gap=GAP, backend="jax",
        load_factors=factors,
    )
    tol = 2 * GAP * abs(ref.obj_value) + 1e-9
    assert abs(got.obj_value - ref.obj_value) <= tol


def test_streaming_carries_load_fixed_point(mixtral):
    """A streaming tick with expert_loads on the profile maps experts and
    feeds the realized factors into the NEXT tick's pricing."""
    from distilp_tpu.solver import StreamingReplanner

    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    E = mixtral.n_routed_experts
    model = mixtral.model_copy(
        update={"expert_loads": [5.0, 3.0] + [1.0] * (E - 2)}
    )
    planner = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")

    first = planner.step(devs, model)
    assert first.certified
    assert planner.last_mapping is not None
    assert planner._load_factors is not None
    assert not np.allclose(planner._load_factors, 1.0)

    second = planner.step(devs, model)  # warm + factor-priced
    assert second.certified
    assert planner.last_mapping is not None
    assert sum(len(ids) for ids in planner.last_mapping.expert_of_device) == E

    # Dropping the loads reverts to the uniform path.
    third = planner.step(devs, mixtral)
    assert third.certified and planner.last_mapping is None


def test_realized_objective_prices_fixed_assignment(mixtral):
    """realized_objective must price the iterate's OWN (k,w,n,y) at the
    mapping's factors — matching the solver's objective when the factors
    are the ones the instance was solved with."""
    from distilp_tpu.solver.routing import realized_objective

    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    E = mixtral.n_routed_experts
    loads = normalize_loads([4.0, 4.0] + [1.0] * (E - 2), E)
    from distilp_tpu.solver.moe import build_moe_arrays

    g = build_moe_arrays(devs, mixtral).g_raw

    # Solve an instance at specific factors, map, and re-price.
    result = halda_solve(
        devs, mixtral, kv_bits="8bit", mip_gap=GAP, backend="jax", moe=True
    )
    mapping = map_experts(result.y, g, loads)
    val = realized_objective(devs, mixtral, result, mapping, kv_bits="8bit")
    assert np.isfinite(val)
    # With uniform factors (all-1 mapping of uniform loads), the realized
    # objective equals the solver's own certified objective.
    uni = map_experts(result.y, g, normalize_loads(None, E))
    assert np.allclose(uni.factors, 1.0)
    val_uni = realized_objective(devs, mixtral, result, uni, kv_bits="8bit")
    assert val_uni == pytest.approx(result.obj_value, rel=1e-6)


def test_solve_load_aware_falls_back_cold_when_warm_uncertified(monkeypatch, mixtral):
    """A warm iterate whose stale-dual bound misses the certificate must be
    replaced by a cold re-solve, never carried uncertified."""
    import warnings


    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    E = mixtral.n_routed_experts

    calls = []

    def make_spy(real):
        def spy(*args, **kwargs):
            result = real(*args, **kwargs)
            warm = kwargs.get("warm") is not None
            calls.append(warm)
            if warm:
                result = result.model_copy(update={"certified": False})
            return result
        return spy

    # solve_load_aware resolves halda_solve lazily via `from .api import
    # halda_solve`, so patching the api module attribute intercepts it.
    import distilp_tpu.solver.api as api_mod

    monkeypatch.setattr(api_mod, "halda_solve", make_spy(api_mod.halda_solve))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        result, mapping, realized = solve_load_aware(
            devs, mixtral, expert_loads=[5.0] + [1.0] * (E - 1), iters=2,
            kv_bits="8bit", mip_gap=GAP, backend="jax",
        )
    # Pattern: cold, warm (forced uncertified), cold fallback.
    assert calls == [False, True, False]
    assert result.certified


def test_solve_load_aware_rejects_managed_kwargs(mixtral):
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    with pytest.raises(TypeError, match="manages"):
        solve_load_aware(devs, mixtral, expert_loads=None, moe=True)


def test_fixed_point_iters_study(mixtral):
    """Characterize the fixed-point depth: best-of-N selection over the
    realized end-to-end objective must be monotone non-worsening in N, and
    the study pins WHERE the improvement lands so the ``iters=2`` default
    is a measured choice, not a guess (one re-pricing captures the skew;
    see solve_load_aware's docstring note)."""
    devs = make_synthetic_fleet(4, seed=7, pool_bytes=int(64e9))
    E = mixtral.n_routed_experts
    raw = [4.0, 4.0] + [1.0] * (E - 2)  # two hot experts, half the load
    realized_at = {}
    for iters in (1, 2, 3):
        result, mapping, realized = solve_load_aware(
            devs, mixtral, expert_loads=raw, iters=iters,
            kv_bits="8bit", mip_gap=GAP, backend="jax",
        )
        assert result.certified
        assert np.isfinite(realized)
        realized_at[iters] = realized
    # The iterate sequence is deterministic, so best-of-N can only improve.
    assert realized_at[2] <= realized_at[1] + 1e-12
    assert realized_at[3] <= realized_at[2] + 1e-12
    # The default (iters=2) must capture the bulk of whatever the deeper
    # fixed point finds: iterate 3 may polish, but not by more than the
    # solve's own certification tolerance band.
    tol = 2 * GAP * abs(realized_at[2])
    assert realized_at[2] - realized_at[3] <= tol, (
        f"iters=3 improved the realized objective by "
        f"{realized_at[2] - realized_at[3]:.6g} (> {tol:.3g}); "
        f"the iters=2 default is leaving real objective on the table"
    )

"""A picklable-by-spec scheduler stub for process-worker tests.

The child process resolves ``tests.procstub:make_scheduler`` (a
'module:callable' factory spec — the only factory form that crosses a
process boundary) and hosts ``StubScheduler`` instances: no jax, no
solver, just the scheduler surface the gateway's closures touch, with
every return value a plain picklable dict. The warm-resume audit
counters mirror the real scheduler's restore contract closely enough
for the migration reconciliation tests to pin warm_resumes/cold_resumes
through a live move.
"""

from __future__ import annotations

import os
import time

from distilp_tpu.sched.metrics import SchedulerMetrics


class StubScheduler:
    def __init__(self, devices, model):
        self.devices = list(devices)
        self.model = model
        self.metrics = SchedulerMetrics()
        self.health = "healthy"
        self.spec_k = 4
        self.events = 0
        self._restore_pending = False
        # Chaos knobs (ISSUE 20 crash-taxonomy tests), set over the RPC
        # setattr surface and inert by default. Neither rides the dump
        # blob: a respawned child comes back with both disarmed, exactly
        # like a real scheduler loses its injected faults on restart.
        self.exit_on_dump = 0  # die (os._exit) on the Nth dump_state call
        self.solve_sleep_s = 0.0  # stretch handle() so a kill lands mid-solve
        self.dumps = 0

    # -- ticks -------------------------------------------------------------

    def handle(self, event, pressure: bool = False):
        if self.solve_sleep_s:
            time.sleep(self.solve_sleep_s)
        if self._restore_pending:
            self._restore_pending = False
            self.metrics.inc("warm_resumes")
        self.events += 1
        self.metrics.inc("events_total")
        return {
            "seq": self.events,
            "pressure": bool(pressure),
            "kind": getattr(event, "kind", str(event)),
        }

    def handle_coalesced(self, events, pressure: bool = False):
        out = None
        for ev in events:
            out = self.handle(ev, pressure=pressure)
        return out

    def latest(self):
        return {"seq": self.events} if self.events else None

    # -- snapshot chain ----------------------------------------------------

    def dump_state(self) -> dict:
        self.dumps += 1
        if self.exit_on_dump and self.dumps >= self.exit_on_dump:
            # Child suicide mid-RPC: the parent's recv sees EOF and
            # raises WorkerCrashed — the migration-abort / torn-dump
            # corner the fold-on-abort tests pin.
            os._exit(43)
        return {
            "version": 1,
            "devices": list(self.devices),
            "model": self.model,
            "events": self.events,
            "spec_k": self.spec_k,
        }

    def load_state(self, state: dict) -> None:
        self.events = state["events"]
        self.spec_k = state.get("spec_k", self.spec_k)
        self._restore_pending = True
        self.metrics.inc("state_restored")

    # -- reads -------------------------------------------------------------

    def health_snapshot(self) -> dict:
        return {"state": self.health, "breaker_open": False}

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def close(self) -> None:
        self.health = "closed"


def make_scheduler(devices, model) -> StubScheduler:
    return StubScheduler(devices, model)

"""Device profiler tests: JAX microbenchmarks -> DeviceProfile.

Runs on the CPU backend with tiny benchmark sizes (DPERF_* env knobs, the
same knob mechanism the reference exposes for its disk bench,
reference profiler/device.py:271-389). The integration test chains
profile-device -> profile-model -> save -> load -> solve, mirroring the
reference's workflow test (test/test_integration.py:66-116).
"""

import json
import os
from pathlib import Path

import pytest

from distilp_tpu.common import ALL_QUANT_LEVELS, DeviceProfile

CONFIGS = Path(__file__).resolve().parent / "configs"

FAST_KNOBS = {
    "DPERF_GEMM_WARMUP": "1",
    "DPERF_GEMM_ITERS": "2",
    "DPERF_MEM_MB": "8",
    "DPERF_HBM_MB": "8",
    "DPERF_XFER_MB": "4",
    "DPERF_DISK_FILE_MB": "4",
    "DPERF_DISK_CHUNK_MB": "1",
}


@pytest.fixture(scope="module")
def device_profile():
    old = {k: os.environ.get(k) for k in FAST_KNOBS}
    os.environ.update(FAST_KNOBS)
    try:
        from distilp_tpu.profiler import profile_device

        yield profile_device(CONFIGS / "llama31_8b_4bit.json", max_batch_exp=1)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_device_profile_validity(device_profile):
    # Mirrors reference test_integration.py:119-137.
    prof = device_profile
    assert prof.os_type in ("linux", "android", "mac_metal", "mac_no_metal")
    assert set(prof.scpu.keys()) == set(ALL_QUANT_LEVELS)
    assert prof.scpu["F32"]["b_1"] > 0
    # Quant synthesis factors (reference profiler/device.py:641-653).
    f32 = prof.scpu["F32"]["b_1"]
    assert prof.scpu["Q4_K"]["b_1"] == pytest.approx(f32 * 0.25)
    assert prof.scpu["Q8_0"]["b_1"] == pytest.approx(f32 * 0.5)
    assert prof.T_cpu > 0
    assert prof.t_kvcpy_cpu > 0
    assert prof.d_avail_ram > 0
    assert prof.s_disk > 0
    # On the virtual 8-device mesh t_comm is *measured* (ICI all-reduce
    # latency) — an upgrade over the reference's hard-coded 0
    # (reference profiler/device.py:719).
    assert prof.t_comm >= 0.0


def test_device_profile_json_roundtrip(device_profile, tmp_path):
    path = tmp_path / "device.json"
    path.write_text(device_profile.model_dump_json())
    loaded = DeviceProfile.model_validate_json(path.read_text())
    assert loaded == device_profile


def test_device_info_schema_roundtrip():
    from distilp_tpu.profiler import DeviceInfo

    di = DeviceInfo()
    di.cpu.benchmarks.f32.b_1 = 1e9
    di.gpu.name = "tpu"
    blob = di.model_dump_json()
    back = DeviceInfo.model_validate_json(blob)
    assert back.gpu.name == "tpu"
    assert back.cpu.benchmarks.f32.b_1 == 1e9


def test_interconnect_measurement_virtual_mesh():
    # The 8-device virtual CPU mesh (conftest) stands in for an ICI mesh.
    from distilp_tpu.profiler.topology import measure_interconnect

    info = measure_interconnect(latency_iters=3, bandwidth_mb=1)
    assert info.num_devices == 8
    assert info.ici_allreduce_latency_s > 0
    assert info.ici_bandwidth > 0


def test_estimate_t_comm_positive_on_mesh():
    from distilp_tpu.profiler.topology import estimate_t_comm

    t = estimate_t_comm(payload_bytes=1024)
    assert t > 0


def test_profile_and_solve_workflow(device_profile, tmp_path):
    # Mirrors reference test_integration.py:66-116: profile -> save ->
    # load-from-folder -> solve, with the same device duplicated into a
    # 2-device cluster.
    from distilp_tpu.profiler import profile_model
    from distilp_tpu.common import load_from_profile_folder
    from distilp_tpu.solver import halda_solve

    model_split = profile_model(
        CONFIGS / "llama31_8b_4bit.json", batch_sizes=[1], sequence_length=128
    )

    folder = tmp_path / "cluster"
    folder.mkdir()
    (folder / "model_profile.json").write_text(model_split.model_dump_json())
    head = device_profile.model_copy(deep=True)
    head.is_head = True
    second = device_profile.model_copy(deep=True)
    second.is_head = False
    second.name = "m2"
    (folder / "m1.json").write_text(head.model_dump_json())
    (folder / "m2.json").write_text(second.model_dump_json())

    devices, model = load_from_profile_folder(folder)
    assert len(devices) == 2
    assert devices[0].is_head

    result = halda_solve(devices, model, kv_bits="4bit", backend="cpu")
    assert sum(result.w) * result.k == model.L
    # Note: obj_value can be negative on a high-RAM host — kappa subtracts
    # the RAM headroom over s_disk (reference dense_common.py:211-230), and
    # the golden fixtures only stay positive because their devices have tiny
    # RAM. Finiteness + feasibility is the invariant.
    import math

    assert math.isfinite(result.obj_value)

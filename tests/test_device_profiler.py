"""Device profiler tests: JAX microbenchmarks -> DeviceProfile.

Runs on the CPU backend with tiny benchmark sizes (DPERF_* env knobs, the
same knob mechanism the reference exposes for its disk bench,
reference profiler/device.py:271-389). The integration test chains
profile-device -> profile-model -> save -> load -> solve, mirroring the
reference's workflow test (test/test_integration.py:66-116).
"""

import os
from pathlib import Path

import pytest

from conftest import SHARD_MAP_SKIP_REASON, jax_shard_map_available
from distilp_tpu.common import ALL_QUANT_LEVELS, DeviceProfile

CONFIGS = Path(__file__).resolve().parent / "configs"

# profile_device and every interconnect test below drive the collective
# microbenchmarks through jax.shard_map; see SHARD_MAP_SKIP_REASON.
requires_shard_map = pytest.mark.skipif(
    not jax_shard_map_available(), reason=SHARD_MAP_SKIP_REASON
)

FAST_KNOBS = {
    "DPERF_GEMM_WARMUP": "1",
    "DPERF_GEMM_ITERS": "2",
    "DPERF_MEM_MB": "8",
    "DPERF_HBM_MB": "8",
    "DPERF_XFER_MB": "4",
    "DPERF_DISK_FILE_MB": "4",
    "DPERF_DISK_CHUNK_MB": "1",
}


@pytest.fixture(scope="module")
def device_profile():
    if not jax_shard_map_available():
        # The fixture itself runs profile_device (whose t_comm measurement
        # is the shard_map collectives), so its dependents skip here with
        # the same env-defect reason instead of ERRORing at setup.
        pytest.skip(SHARD_MAP_SKIP_REASON)
    old = {k: os.environ.get(k) for k in FAST_KNOBS}
    os.environ.update(FAST_KNOBS)
    try:
        from distilp_tpu.profiler import profile_device

        yield profile_device(CONFIGS / "llama31_8b_4bit.json", max_batch_exp=1)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_device_profile_validity(device_profile):
    # Mirrors reference test_integration.py:119-137.
    prof = device_profile
    assert prof.os_type in ("linux", "android", "mac_metal", "mac_no_metal")
    assert set(prof.scpu.keys()) == set(ALL_QUANT_LEVELS)
    assert prof.scpu["F32"]["b_1"] > 0
    # Quant synthesis factors (reference profiler/device.py:641-653).
    f32 = prof.scpu["F32"]["b_1"]
    assert prof.scpu["Q4_K"]["b_1"] == pytest.approx(f32 * 0.25)
    assert prof.scpu["Q8_0"]["b_1"] == pytest.approx(f32 * 0.5)
    assert prof.T_cpu > 0
    assert prof.t_kvcpy_cpu > 0
    assert prof.d_avail_ram > 0
    assert prof.s_disk > 0
    # On the virtual 8-device mesh t_comm is *measured* (ICI all-reduce
    # latency) — an upgrade over the reference's hard-coded 0
    # (reference profiler/device.py:719).
    assert prof.t_comm >= 0.0


def test_device_profile_json_roundtrip(device_profile, tmp_path):
    path = tmp_path / "device.json"
    path.write_text(device_profile.model_dump_json())
    loaded = DeviceProfile.model_validate_json(path.read_text())
    assert loaded == device_profile


def test_device_info_schema_roundtrip():
    from distilp_tpu.profiler import DeviceInfo

    di = DeviceInfo()
    di.cpu.benchmarks.f32.b_1 = 1e9
    di.gpu.name = "tpu"
    blob = di.model_dump_json()
    back = DeviceInfo.model_validate_json(blob)
    assert back.gpu.name == "tpu"
    assert back.cpu.benchmarks.f32.b_1 == 1e9


@requires_shard_map
def test_interconnect_measurement_virtual_mesh():
    # The 8-device virtual CPU mesh (conftest) stands in for an ICI mesh.
    from distilp_tpu.profiler.topology import measure_interconnect

    info = measure_interconnect(latency_iters=3, bandwidth_mb=1)
    assert info.num_devices == 8
    assert info.ici_allreduce_latency_s > 0
    assert info.ici_bandwidth > 0
    # Provenance (VERDICT r5 item 8): numbers timed over host-platform
    # virtual devices must say so — they time the host's memory system,
    # not any real link — and the field must survive a JSON round trip so
    # saved captures cannot launder virtual numbers into measured ones.
    assert info.provenance == "virtual"
    from distilp_tpu.profiler.datatypes import InterconnectInfo

    back = InterconnectInfo.model_validate_json(info.model_dump_json())
    assert back.provenance == "virtual"
    assert InterconnectInfo().provenance == "unmeasured"


@requires_shard_map
def test_estimate_t_comm_positive_on_mesh():
    from distilp_tpu.profiler.topology import estimate_t_comm

    t = estimate_t_comm(payload_bytes=1024)
    assert t > 0


def test_profile_and_solve_workflow(device_profile, tmp_path):
    # Mirrors reference test_integration.py:66-116: profile -> save ->
    # load-from-folder -> solve, with the same device duplicated into a
    # 2-device cluster.
    from distilp_tpu.profiler import profile_model
    from distilp_tpu.common import load_from_profile_folder
    from distilp_tpu.solver import halda_solve

    model_split = profile_model(
        CONFIGS / "llama31_8b_4bit.json", batch_sizes=[1], sequence_length=128
    )

    folder = tmp_path / "cluster"
    folder.mkdir()
    (folder / "model_profile.json").write_text(model_split.model_dump_json())
    head = device_profile.model_copy(deep=True)
    head.is_head = True
    second = device_profile.model_copy(deep=True)
    second.is_head = False
    second.name = "m2"
    (folder / "m1.json").write_text(head.model_dump_json())
    (folder / "m2.json").write_text(second.model_dump_json())

    devices, model = load_from_profile_folder(folder)
    assert len(devices) == 2
    assert devices[0].is_head

    result = halda_solve(devices, model, kv_bits="4bit", backend="cpu")
    assert sum(result.w) * result.k == model.L
    # Note: obj_value can be negative on a high-RAM host — kappa subtracts
    # the RAM headroom over s_disk (reference dense_common.py:211-230), and
    # the golden fixtures only stay positive because their devices have tiny
    # RAM. Finiteness + feasibility is the invariant.
    import math

    assert math.isfinite(result.obj_value)


@requires_shard_map
def test_interconnect_dcn_split_virtual_mesh():
    """Forcing the 8-device virtual mesh into two fake slices must measure a
    separate cross-slice (DCN) latency/bandwidth pair alongside the
    intra-slice (ICI) one."""
    from distilp_tpu.profiler.topology import measure_interconnect

    info = measure_interconnect(
        latency_iters=3, bandwidth_mb=1, slice_of=lambda d: d.id % 2
    )
    assert info.num_slices == 2
    assert info.ici_allreduce_latency_s > 0 and info.ici_bandwidth > 0
    assert info.dcn_latency_s > 0 and info.dcn_bandwidth > 0


@requires_shard_map
def test_cross_slice_pricing_steers_placement():
    """End-to-end profiler->solver loop (the reference never closes it: its
    t_comm is a hand-edited scalar): MEASURED ICI/DCN numbers from a fake
    2-slice virtual mesh price a 2-device fleet's t_comm via
    ``estimate_t_comm`` — the within-slice device on the ICI link, the
    cross-boundary device on the DCN link — and the solver must (a) pay
    strictly more for the fleet whose hop crosses the slice boundary on the
    slower link, and (b) shift layers OFF the boundary device once the
    measured link difference exceeds two per-layer compute costs (exchange
    argument: with k=2 and otherwise-identical devices, moving one layer
    off the busier device strictly lowers the cycle max)."""
    import copy

    from distilp_tpu.common import load_model_profile
    from distilp_tpu.profiler.topology import estimate_t_comm, measure_interconnect
    from distilp_tpu.solver import halda_solve
    from distilp_tpu.solver.coeffs import build_coeffs
    from distilp_tpu.utils import make_synthetic_fleet

    info = measure_interconnect(
        latency_iters=3, bandwidth_mb=1, slice_of=lambda d: d.id % 2
    )
    assert info.num_slices == 2
    info_ici = info.model_copy(update={"num_slices": 1})

    model = load_model_profile(
        Path(__file__).resolve().parent
        / "profiles" / "llama_3_70b" / "online" / "model_profile.json"
    )
    base = make_synthetic_fleet(1, seed=5)[0]

    def fleet(t_boundary: float, t_within: float):
        d0 = copy.deepcopy(base)
        d1 = copy.deepcopy(base)
        d0.name, d1.name = "within-slice", "cross-boundary"
        d0.is_head, d1.is_head = True, False
        d0.t_comm, d1.t_comm = t_within, t_boundary
        # Thread the link terms exactly as profiler.device does (same
        # link-selection rule), so the profile records WHICH link priced it.
        d0.comm_latency = info.ici_allreduce_latency_s
        d0.comm_bandwidth = info.ici_bandwidth
        d1.comm_latency = info.dcn_latency_s
        d1.comm_bandwidth = info.dcn_bandwidth
        return [d0, d1]

    # Worst-case marginal cost of moving one layer between these devices:
    # compute (a, b_gpu) plus the slack/VRAM penalty staircases a layer can
    # cross on the receiving device. A link delta of twice this FORCES a
    # shift (exchange argument: at equal w the busier side exceeds the
    # other by >= 2x the worst exchange cost, so moving one layer strictly
    # lowers the k=2 cycle max whatever penalties it triggers).
    c = build_coeffs(fleet(0.0, 0.0), model, kv_factor=0.5)
    alpha = float(
        (abs(c.a) + abs(c.b_gpu) + c.pen_m1 + c.pen_vram).max()
    )

    # Find a payload where the measured DCN-vs-ICI delta forces the shift:
    # delta(X) = (lat_d - lat_i) + X * (1/bw_d - 1/bw_i), monotone in X.
    lat_i, bw_i = info.ici_allreduce_latency_s, info.ici_bandwidth
    lat_d, bw_d = info.dcn_latency_s, info.dcn_bandwidth
    assert bw_i > 0 and bw_d > 0
    slope = 1.0 / bw_d - 1.0 / bw_i
    need = 2.0 * alpha
    if abs(lat_d - lat_i) >= need:
        payload = 1
    elif abs(slope) > 1e-18:
        # Aim past the target on the side the slope grows toward.
        payload = int(abs((need * (1 if slope > 0 else -1) - (lat_d - lat_i)) / slope)) + 1
    else:
        pytest.skip("virtual mesh measured identical ICI and DCN links")
    t_within = estimate_t_comm(payload, info_ici)
    t_cross = estimate_t_comm(payload, info)
    delta = t_cross - t_within
    if abs(delta) < need:
        pytest.skip(f"measured link delta {delta:.3g}s below 2*alpha {need:.3g}s")

    # Price both devices on the faster effective link, then move the
    # boundary device onto the slower one. k=2 pins the cycle term.
    t_fast, t_slow = min(t_within, t_cross), max(t_within, t_cross)
    uniform = halda_solve(
        fleet(t_fast, t_fast), model, k_candidates=[2], kv_bits="4bit",
        mip_gap=1e-4, backend="cpu",
    )
    split = halda_solve(
        fleet(t_slow, t_fast), model, k_candidates=[2], kv_bits="4bit",
        mip_gap=1e-4, backend="cpu",
    )
    # (a) the boundary hop costs real objective, not just bookkeeping...
    assert split.obj_value > uniform.obj_value
    # (b) ...and the measured delta moved the placement: layers shift off
    # the device paying the slower link.
    assert split.w[1] < uniform.w[1]
    assert sum(split.w) * split.k == model.L


def test_estimate_t_comm_reproduces_fixture_order_of_magnitude():
    """The reference's only multi-device fixture carries a HAND-measured
    t_comm of 0.06355 s (test/profiles/llama_3_70b/online/m1.json, a
    home-network fleet). The latency+payload/bandwidth model with plausible
    home-network link terms (~50 ms RTT collective, ~1 Gb/s) must land in
    the same order of magnitude — the number the reference asks operators
    to hand-edit is *derivable*."""
    from distilp_tpu.profiler.datatypes import InterconnectInfo
    from distilp_tpu.profiler.topology import estimate_t_comm

    info = InterconnectInfo(
        num_devices=2,
        ici_allreduce_latency_s=0.05,
        ici_bandwidth=125e6,
    )
    payload = 8192 * 2  # one token's hidden state, bf16, llama-70b width
    t = estimate_t_comm(payload, info=info)
    assert 0.02 < t < 0.2  # fixture: 0.06355

    # Multi-slice meshes price over the slower DCN link.
    info2 = InterconnectInfo(
        num_devices=16,
        num_slices=2,
        ici_allreduce_latency_s=1e-5,
        ici_bandwidth=4.5e10,
        dcn_latency_s=1e-3,
        dcn_bandwidth=3e9,
    )
    assert estimate_t_comm(payload, info=info2) > estimate_t_comm(
        payload, info=InterconnectInfo(
            num_devices=16, ici_allreduce_latency_s=1e-5, ici_bandwidth=4.5e10
        )
    )


def test_bench_subnoise_is_invalid_not_clamped():
    """A kernel indistinguishable from the dispatch round-trip must come back
    NaN with Stat.valid=False — not clamped to 1e-9 s (which used to turn
    RTT noise into absurd throughput table entries)."""
    import math

    import jax.numpy as jnp

    from distilp_tpu.profiler.device import bench

    sink = {}
    x = jnp.ones((4,), jnp.float32)
    # Huge fake baseline: net time is guaranteed negative -> sub-noise.
    t = bench(lambda: x, warmup=1, iters=4, baseline=10.0, label="probe", sink=sink)
    assert math.isnan(t)
    st = sink["probe"]
    assert not st.valid
    assert st.samples == 4
    assert st.baseline == 10.0
    assert st.min <= st.p50 <= st.p95 <= st.p99 <= st.max

    # A real measurement stays valid and positive.
    t2 = bench(lambda: x, warmup=1, iters=4, label="ok", sink=sink)
    assert t2 > 0 and sink["ok"].valid


def test_gemm_flops_subnoise_returns_no_table_sentinel():
    """_gemm_flops must report 0.0 (the solver's "no table" sentinel) for a
    sub-noise measurement, never an absurd positive throughput."""
    from distilp_tpu.profiler.device import _gemm_flops

    sink = {}
    flops = _gemm_flops(
        "cpu", 1, 8, 8, 8, "uint32", warmup=0, iters=2, baseline=10.0,
        label="gemm.cpu.u32.b_1", sink=sink,
    )
    assert flops == 0.0
    assert not sink["gemm.cpu.u32.b_1"].valid


def test_hbm_provenance_recorded(monkeypatch):
    """accel_get_memory_info must record where the capacity figure came from."""
    from distilp_tpu.profiler import device as dev_mod
    from distilp_tpu.profiler.datatypes import DeviceInfo

    class FakeDev:
        device_kind = "TPU v5 lite"

        def memory_stats(self):
            return {}

    class FakeJax:
        @staticmethod
        def default_backend():
            return "tpu"

        @staticmethod
        def devices():
            return [FakeDev()]

        @staticmethod
        def local_device_count():
            return 1

    import sys as _sys

    monkeypatch.setitem(_sys.modules, "jax", FakeJax())
    # Static-table path.
    di = DeviceInfo()
    dev_mod.accel_get_memory_info(di)
    assert di.gpu.memory.capacity_source == "table:TPU v5 lite"
    assert di.gpu.memory.total == 16 * 2**30

    # Env-override path wins over the table.
    monkeypatch.setenv("DPERF_HBM_BYTES", str(123 * 2**20))
    di2 = DeviceInfo()
    dev_mod.accel_get_memory_info(di2)
    assert di2.gpu.memory.capacity_source == "env:DPERF_HBM_BYTES"
    assert di2.gpu.memory.total == 123 * 2**20

    # Unlisted kind with no override: capacity 0, provenance 'unknown'.
    monkeypatch.delenv("DPERF_HBM_BYTES")
    FakeDev.device_kind = "Mystery Accelerator"
    di3 = DeviceInfo()
    dev_mod.accel_get_memory_info(di3)
    assert di3.gpu.memory.capacity_source == "unknown"
    assert di3.gpu.memory.total == 0


class TestTpuV5eGoldenArtifacts:
    """Regression pins for the measured-on-hardware TPU device fixtures
    (tests/profiles/tpu_v5e/ — the analogue of the reference's measured
    device fixtures, e.g. test/profiles/llama_3_70b/online/m1.json).
    Skipped until the artifacts are captured on a live chip; once present
    they keep the profiler's hardware path honest: a regression that zeroes
    a GEMM table or drops capacity provenance fails here, not in the field.
    """

    FIXDIR = Path(__file__).resolve().parent / "profiles" / "tpu_v5e"

    @pytest.fixture(autouse=True)
    def _need_artifacts(self):
        if not (
            (self.FIXDIR / "tpu_v5e.json").exists()
            and (self.FIXDIR / "tpu_v5e_raw.json").exists()
        ):
            pytest.skip("no measured TPU artifacts committed yet")

    def test_device_profile_loads_and_solves(self):
        import json

        from distilp_tpu.common import DeviceProfile, load_model_profile
        from distilp_tpu.solver import halda_solve

        prof = DeviceProfile.model_validate(
            json.loads((self.FIXDIR / "tpu_v5e.json").read_text())
        )
        # Measured tables must be populated with real (positive) throughput
        # — an all-zero column means the measurement silently died.
        assert prof.scpu, "empty CPU throughput table"
        for q, cols in prof.scpu.items():
            assert any(v > 0 for v in cols.values()), (q, cols)
        assert prof.T_cpu > 0
        assert prof.d_avail_ram > 0
        # The profile must be solver-usable as-is.
        model = load_model_profile(
            Path(__file__).resolve().parent
            / "profiles" / "llama_3_70b" / "online" / "model_profile.json"
        )
        prof.is_head = True
        r = halda_solve([prof], model, kv_bits="4bit", mip_gap=1e-3,
                        backend="cpu")
        assert sum(r.w) * r.k == model.L

    def test_raw_deviceinfo_carries_measurement_evidence(self):
        import json

        from distilp_tpu.profiler.datatypes import DeviceInfo

        raw = DeviceInfo.model_validate(
            json.loads((self.FIXDIR / "tpu_v5e_raw.json").read_text())
        )
        # Capacity provenance recorded (memory_stats / HBM-kind / env);
        # DeviceInfo.gpu is non-Optional, so a capture without accelerator
        # evidence fails here rather than passing by omission.
        assert raw.gpu.memory.capacity_source != ""
        # Timing spreads present AND carrying real measurements — all-default
        # Stat objects (p50=0.0) would mean persistence dropped the evidence.
        assert raw.stats, "no Stat spreads persisted"
        assert any(st.p50 > 0 for st in raw.stats.values()), (
            "every persisted Stat is all-defaults"
        )

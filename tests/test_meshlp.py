"""Sharded + mixed-precision PDHG tests (ops/meshlp.py, ROADMAP item 3).

The fleet-scale contract: the row-sharded mesh kernel must be *invisible*
above the ops layer — same ``LPBatch`` in, same fully-replicated
``IPMResult`` out, same warm-state fields in full-array coordinates, same
rigorous f64 Lagrangian certificate — so ``mesh_shards`` is a pure
capacity knob: it changes which devices hold which operator rows and
nothing else. These tests pin that on the forced host mesh the whole
suite runs under (conftest sets ``--xla_force_host_platform_device_count=8``
before any jax import), plus the mixed-precision soundness half: f32
iterates are an optimization that can cost an f64 re-solve, never a wrong
certificate.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from test_pdhg import GOLDEN, _random_feasible_batch  # noqa: E402

from distilp_tpu.common import load_from_profile_folder, load_model_profile  # noqa: E402
from distilp_tpu.ops import (  # noqa: E402
    LPBatch,
    pdhg_solve_batch,
    pdhg_solve_batch_mp,
    pdhg_solve_batch_sharded,
)
from distilp_tpu.ops import memmodel  # noqa: E402
from distilp_tpu.ops.meshlp import pad_rows_to  # noqa: E402
from distilp_tpu.ops.pdhg import PDHGWarmState  # noqa: E402
from distilp_tpu.solver import halda_solve  # noqa: E402
from distilp_tpu.solver.streaming import StreamingReplanner  # noqa: E402
from distilp_tpu.utils import make_synthetic_fleet  # noqa: E402

GAP = 1e-3
SHARDS = 4

# The mesh tests need >= SHARDS local devices; conftest forces 8 virtual
# CPU devices, so this only skips when run outside the suite's env.
requires_mesh = pytest.mark.skipif(
    len(jax.devices()) < SHARDS,
    reason=f"needs >= {SHARDS} local devices "
    "(run under --xla_force_host_platform_device_count)",
)


# --------------------------------------------------------------------------
# Kernel level: sharded vs unsharded parity, padding, warm interchange.


@requires_mesh
def test_sharded_matches_unsharded_kernel():
    """4-shard solve == unsharded solve on random feasible LPs, with the
    row padding exercised (m=10 is not a multiple of 4): objectives,
    f64 bounds and the gathered dual agree to collective-reduction noise,
    and the bound stays a valid lower bound."""
    rng = np.random.default_rng(42)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=6)
    assert pad_rows_to(10, SHARDS) == 12  # padding is really in play
    ref = pdhg_solve_batch(batch, iters=20000, tol=1e-8)
    res = pdhg_solve_batch_sharded(
        batch, tol=1e-8, mesh_shards=SHARDS, iters=20000
    )
    assert np.all(np.array(res.converged))
    np.testing.assert_allclose(
        np.array(res.obj), np.array(ref.obj), rtol=1e-9, atol=1e-9
    )
    np.testing.assert_allclose(
        np.array(res.bound), np.array(ref.bound), rtol=1e-9, atol=1e-9
    )
    # y_dual is gathered back to full coordinates and sliced to m=10.
    assert res.y_dual.shape == ref.y_dual.shape
    np.testing.assert_allclose(
        np.array(res.y_dual), np.array(ref.y_dual), rtol=1e-7, atol=1e-9
    )
    assert np.all(np.array(res.bound) <= refs + 1e-6)


@requires_mesh
def test_shards1_matches_unsharded_to_ulp():
    """mesh_shards=1 runs the identity-collective program: same math, but
    a different XLA executable than the plain entry, so agreement is
    asserted to last-ulp tolerance here. TRUE bit-stability of the
    mesh_shards=1 *solver* knob is pinned in
    test_sharded_and_f64_match_north_star — backend_jax dispatches
    shards=1 onto the plain path, byte-identical by construction."""
    rng = np.random.default_rng(7)
    batch, _ = _random_feasible_batch(rng, m=9, n=20, B=4)
    ref = pdhg_solve_batch(batch, iters=5000)
    res = pdhg_solve_batch_sharded(batch, mesh_shards=1, iters=5000)
    np.testing.assert_allclose(
        np.array(res.obj), np.array(ref.obj), rtol=1e-12, atol=1e-12
    )
    np.testing.assert_allclose(
        np.array(res.bound), np.array(ref.bound), rtol=1e-12, atol=1e-12
    )
    np.testing.assert_allclose(
        np.array(res.v), np.array(ref.v), rtol=1e-9, atol=1e-9
    )
    np.testing.assert_allclose(
        np.array(res.y_dual), np.array(ref.y_dual), rtol=1e-9, atol=1e-9
    )
    assert np.array_equal(np.array(res.iters_run), np.array(ref.iters_run))


@requires_mesh
def test_sharded_warm_states_interchange_with_unsharded():
    """Warm states cross the mesh boundary in both directions: the sharded
    kernel's result (full-array coordinates by construction) warm-starts
    the unsharded kernel and vice versa, early-exiting both ways — no
    shard count is baked into the iterate."""
    rng = np.random.default_rng(11)
    B = 6
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=B)
    cold = pdhg_solve_batch_sharded(
        batch, tol=1e-8, mesh_shards=SHARDS, iters=20000
    )
    assert np.all(np.array(cold.converged))
    warm_state = PDHGWarmState(
        v=cold.v, y=cold.y_dual, z=cold.z_dual, f=cold.f_dual,
        ok=jnp.ones(B, bool),
    )
    # sharded iterate -> unsharded kernel
    w_u = pdhg_solve_batch(batch, iters=20000, tol=1e-8, warm=warm_state)
    assert np.all(np.array(w_u.converged))
    assert np.array(w_u.iters_run).max() < np.array(cold.iters_run).max()
    np.testing.assert_allclose(np.array(w_u.obj), refs, rtol=1e-5, atol=1e-5)
    # unsharded iterate -> sharded kernel (y is sliced into row blocks on
    # entry; the skip mask must still freeze elements shard-locally)
    cold_u = pdhg_solve_batch(batch, iters=20000, tol=1e-8)
    w_s = pdhg_solve_batch_sharded(
        batch, tol=1e-8,
        warm=PDHGWarmState(
            v=cold_u.v, y=cold_u.y_dual, z=cold_u.z_dual, f=cold_u.f_dual,
            ok=jnp.ones(B, bool),
        ),
        skip=jnp.zeros(B, bool).at[3].set(True),
        mesh_shards=SHARDS, iters=20000,
    )
    runs = np.array(w_s.iters_run)
    assert runs[3] == 0
    live = np.delete(np.arange(B), 3)
    assert np.all(runs[live] > 0)
    assert runs[live].max() < np.array(cold.iters_run).max()


# --------------------------------------------------------------------------
# Mixed precision: f32 iterates + f64 certificate, and the fallback.


@requires_mesh
def test_mp_f32_sound_vs_f64_vs_highs():
    """f32 iterates with the f64 certificate: both precisions' bounds are
    VALID lower bounds on the HiGHS optimum (soundness is precision-
    independent), f32 objectives agree at first-order-appropriate
    tolerance, f64 tighter — and no element needed the fallback."""
    rng = np.random.default_rng(21)
    batch, refs = _random_feasible_batch(rng, m=10, n=25, B=8)
    rep32: dict = {}
    r32 = pdhg_solve_batch_mp(
        batch, mesh_shards=SHARDS, iters=40000, dtype="f32",
        fallback_report=rep32,
    )
    r64 = pdhg_solve_batch_mp(
        batch, mesh_shards=SHARDS, iters=40000, dtype="f64",
    )
    assert rep32["n_fallback"] == 0
    assert np.all(np.array(r32.converged))
    assert np.all(np.array(r64.converged))
    # Bound validity holds for ANY dual — including an f32 iterate's.
    assert np.all(np.array(r32.bound) <= refs + 1e-5)
    assert np.all(np.array(r64.bound) <= refs + 1e-6)
    np.testing.assert_allclose(np.array(r32.obj), refs, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.array(r64.obj), refs, rtol=1e-5, atol=1e-5)


@requires_mesh
def test_mp_nonfinite_f32_falls_back_to_f64():
    """An element whose data overflows f32 entirely (b ~ 1e39 casts to
    inf) is re-solved on the f64 path and spliced in per element; the
    other elements keep their f32 results untouched."""
    rng = np.random.default_rng(33)
    B = 4
    batch, _ = _random_feasible_batch(rng, m=8, n=18, B=B)
    b_bad = np.array(batch.b, dtype=np.float64)
    b_bad[0] *= 1e39  # f32(1e39) == inf: the f32 run cannot be finite
    poisoned = LPBatch(
        batch.A, jnp.array(b_bad), batch.c, batch.l, batch.u
    )
    rep: dict = {}
    res = pdhg_solve_batch_mp(
        poisoned, mesh_shards=SHARDS, iters=4000, dtype="f32",
        fallback_report=rep,
    )
    assert rep["n_fallback"] >= 1
    # Splice correctness: the fallen-back element carries the pure-f64
    # run's values (cast to the f32 result dtype), the healthy elements
    # the pure-f32 run's — bit-for-bit in both directions.
    r32 = pdhg_solve_batch_mp(
        poisoned, mesh_shards=SHARDS, iters=4000, dtype="f32",
        f64_fallback=False,
    )
    r64 = pdhg_solve_batch_mp(
        poisoned, mesh_shards=SHARDS, iters=4000, dtype="f64",
    )
    bad = ~np.asarray(r32.converged) | ~np.isfinite(np.asarray(r32.bound))
    assert bad[0]
    np.testing.assert_array_equal(
        np.array(res.obj)[bad],
        np.array(r64.obj).astype(np.array(res.obj).dtype)[bad],
    )
    np.testing.assert_array_equal(
        np.array(res.obj)[~bad], np.array(r32.obj)[~bad]
    )
    assert np.all(np.isfinite(np.array(res.bound)[~bad]))


def test_mp_rejects_unknown_dtype():
    rng = np.random.default_rng(3)
    batch, _ = _random_feasible_batch(rng, m=6, n=12, B=2)
    with pytest.raises(ValueError, match="pdhg_dtype"):
        pdhg_solve_batch_mp(batch, dtype="bf16")


# --------------------------------------------------------------------------
# Solver level: mesh_shards/pdhg_dtype through halda_solve — golden
# fixtures, north star, bit-stability, validation, streaming warm state.


@requires_mesh
@pytest.mark.parametrize("folder,k_star,obj", GOLDEN)
def test_sharded_backend_matches_golden(profiles_dir, folder, k_star, obj):
    """mesh_shards=4 certifies the same optimum as the committed golden
    objectives on every dense fixture — the B&B search cannot tell the
    sharded engine ran."""
    devs, model = load_from_profile_folder(profiles_dir / folder)
    result = halda_solve(
        devs, model, mip_gap=1e-4, kv_bits="4bit", backend="jax",
        lp_backend="pdhg", mesh_shards=SHARDS,
    )
    assert result.k == k_star
    assert result.obj_value == pytest.approx(obj, rel=2e-4)
    assert sum(result.w) * result.k == model.L
    for wi, ni in zip(result.w, result.n):
        assert 0 <= ni <= wi


@requires_mesh
def test_sharded_and_f64_match_north_star(profiles_dir):
    """The north-star agreement grid: sharded f32-iterate and sharded
    f64-iterate solves both certify within mip_gap of the HiGHS oracle,
    mesh_shards=1 is BIT-stable against the default path, and the shard
    count is echoed in timings."""
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(16, seed=123)
    ref = halda_solve(devs, model, mip_gap=GAP, kv_bits="4bit", backend="cpu")
    base = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax",
        lp_backend="pdhg",
    )
    one = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax",
        lp_backend="pdhg", mesh_shards=1,
    )
    assert one.obj_value == base.obj_value  # bit-stable, not merely close
    assert one.k == base.k and one.w == base.w and one.n == base.n
    for dtype in (None, "f64"):
        tm: dict = {}
        res = halda_solve(
            devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax",
            lp_backend="pdhg", mesh_shards=SHARDS, pdhg_dtype=dtype,
            timings=tm,
        )
        assert tm["mesh_shards"] == SHARDS
        assert res.certified
        assert res.obj_value == pytest.approx(ref.obj_value, rel=2 * GAP)
        assert res.obj_value == pytest.approx(base.obj_value, rel=2 * GAP)
        assert sum(res.w) * res.k == model.L


def test_mesh_knob_validation(profiles_dir):
    """The row mesh is a PDHG capability: asking the IPM for it (or a
    nonsense shard count / dtype spelling) fails loudly at resolve time,
    before any device program is built."""
    devs, model = load_from_profile_folder(
        profiles_dir / "llama_3_70b" / "online"
    )
    with pytest.raises(ValueError, match="mesh_shards"):
        halda_solve(
            devs, model, backend="jax", lp_backend="ipm", mesh_shards=2
        )
    with pytest.raises(ValueError, match="mesh_shards"):
        halda_solve(
            devs, model, backend="jax", lp_backend="pdhg", mesh_shards=0
        )
    with pytest.raises(ValueError, match="pdhg_dtype"):
        halda_solve(
            devs, model, backend="jax", lp_backend="ipm", pdhg_dtype="f64"
        )
    with pytest.raises(ValueError, match="pdhg_dtype"):
        halda_solve(
            devs, model, backend="jax", lp_backend="pdhg", pdhg_dtype="f16"
        )


@requires_mesh
def test_sharded_warm_state_roundtrips_through_dump_load(profiles_dir):
    """dump_warm_state/load_warm_state carry the sharded engine's warm
    state bit-exactly: a restored replanner's warm tick is identical to
    the uninterrupted replanner's — and the blob has no shard count in
    it, so it restores under any mesh size."""
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(16, seed=123)
    search = {"lp_backend": "pdhg", "mesh_shards": SHARDS}
    planner = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax", search=dict(search)
    )
    first = planner.step(devs, model)
    assert first.certified
    blob = planner.dump_warm_state()

    rng = np.random.default_rng(7)
    for d in devs:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
    uninterrupted = planner.step(devs, model)
    assert planner.last_tick_mode == "warm"

    restored = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax", search=dict(search)
    )
    restored.load_warm_state(blob)
    resumed = restored.step(devs, model)
    assert restored.last_tick_mode == "warm"
    assert resumed.obj_value == uninterrupted.obj_value
    assert resumed.k == uninterrupted.k
    assert resumed.w == uninterrupted.w and resumed.n == uninterrupted.n

    # The blob is mesh-size-agnostic: restore it into an UNSHARDED
    # replanner and the warm tick still certifies.
    unsharded = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax",
        search={"lp_backend": "pdhg"},
    )
    unsharded.load_warm_state(blob)
    crossed = unsharded.step(devs, model)
    assert unsharded.last_tick_mode == "warm"
    assert crossed.certified
    assert crossed.obj_value == pytest.approx(
        uninterrupted.obj_value, rel=2 * GAP
    )


@requires_mesh
def test_zero_warm_phase_compiles_for_sharded_entry(profiles_dir):
    """A warm streaming tick at a fixed shard count dispatches the sharded
    executable compiled on the cold tick — ZERO warm-phase compiles
    attributed to the meshlp entry (the PR 16 gate contract, extended to
    the mesh engine)."""
    from distilp_tpu.obs import compile_ledger as cl

    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    devs = make_synthetic_fleet(16, seed=123)
    led = cl.enable()
    try:
        planner = StreamingReplanner(
            mip_gap=GAP, kv_bits="4bit", backend="jax",
            search={"lp_backend": "pdhg", "mesh_shards": SHARDS},
        )
        planner.step(devs, model)
        tok = led.seq()
        rng = np.random.default_rng(5)
        for d in devs:
            d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.97, 1.03)))
        warm = planner.step(devs, model)
        assert planner.last_tick_mode == "warm"
        assert warm.certified
        warm_events = [
            e for e in led.events_since(tok)
            if e["entry"] == "ops.meshlp.pdhg_solve_batch_sharded"
            and e["cause"] != "cache_hit"
        ]
        assert warm_events == []
    finally:
        cl.disable()


@pytest.mark.slow
@requires_mesh
def test_fleet_scale_sharded_m16384_arm():
    """The capable-box ceiling arm: M=16384 sharded f32-iterate solve via
    the bench child (same code path as DPERF_FLEET_SHARD_SLOW=1), must
    certify at the fleet-scale gap. Hours of wall clock on a CPU box —
    slow-marked on purpose."""
    import subprocess
    import sys as _sys

    import bench

    proc = subprocess.run(
        [
            _sys.executable, "-c", bench._FLEET_SCALE_SRC,
            "16384", "pdhg", "0.05", "1000", str(SHARDS), "f32",
        ],
        capture_output=True, text=True, timeout=4 * 3600,
        cwd=str(bench.REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = next(
        ln for ln in proc.stdout.splitlines()
        if ln.startswith("DPERF_FLEET ")
    )
    import json

    got = json.loads(line[len("DPERF_FLEET "):])
    assert got["certified"]
    assert got["mesh_shards"] == SHARDS
    assert got["shard_temp_bytes_measured"] is None or (
        1.0
        <= got["shard_temp_bytes_measured"]
        / got["shard_temp_bytes_predicted"]
        <= 100.0
    )


# --------------------------------------------------------------------------
# memmodel: the per-shard sizing that CHOOSES the mesh (stdlib-only).


def test_memmodel_shard_peak_reduces_and_ceils():
    M = 512
    assert memmodel.pdhg_shard_peak_bytes(M, 1) == memmodel.pdhg_peak_bytes(M)
    m_rows, n_cols = memmodel.standard_form_dims(M)
    # m_rows = 3075 on 4 shards -> ceil to 769-row blocks, modeled exactly.
    assert memmodel.pdhg_shard_peak_bytes(M, 4) == -(-m_rows // 4) * n_cols * 4
    assert memmodel.pdhg_shard_peak_bytes(M, 4, dtype_bytes=8) == (
        2 * memmodel.pdhg_shard_peak_bytes(M, 4)
    )
    with pytest.raises(ValueError, match="mesh_shards"):
        memmodel.pdhg_shard_peak_bytes(M, 0)


def test_memmodel_choose_mesh_shards():
    M = 512
    full = memmodel.pdhg_peak_bytes(M)
    # A budget that fits the whole operator prefers no mesh at all.
    assert memmodel.choose_mesh_shards(M, full, max_shards=8) == 1
    # A budget fitting half the operator needs (at least) 2 shards; the
    # ceil'd block makes exactly-half slightly too big, so budget for the
    # block, not the naive half.
    two = memmodel.pdhg_shard_peak_bytes(M, 2)
    assert memmodel.choose_mesh_shards(M, two, max_shards=8) == 2
    assert memmodel.choose_mesh_shards(M, two - 1, max_shards=8) == 3
    # Even max_shards devices can't fit: refuse, don't lie.
    assert memmodel.choose_mesh_shards(M, 1024, max_shards=8) is None
    # f64 iterates double the block: the same budget needs more shards.
    s32 = memmodel.choose_mesh_shards(M, two, max_shards=16)
    s64 = memmodel.choose_mesh_shards(M, two, max_shards=16, dtype_bytes=8)
    assert s64 > s32
    with pytest.raises(ValueError, match="max_shards"):
        memmodel.choose_mesh_shards(M, 1, max_shards=0)


def test_memmodel_dtype_bytes_of():
    assert memmodel.dtype_bytes_of(None) == 4
    assert memmodel.dtype_bytes_of("f32") == 4
    assert memmodel.dtype_bytes_of("f64") == 8
    with pytest.raises(ValueError, match="pdhg_dtype"):
        memmodel.dtype_bytes_of("bf16")

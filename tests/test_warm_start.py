"""Warm-started solve equivalence: the iterate-carrying fast paths must
reach the cold solve's certified answer.

The PR's perf contract: warm starts (incumbent seed, Lagrangian duals, root
IPM iterates) and the truncated warm-round IPM budget may only change how
FAST the certificate closes, never what it certifies. These tests pin that
on the 16-device north-star fixture and the MoE family fixtures.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_enable_x64", True)

from distilp_tpu.common import load_model_profile  # noqa: E402
from distilp_tpu.solver import halda_solve  # noqa: E402
from distilp_tpu.solver.streaming import StreamingReplanner  # noqa: E402
from distilp_tpu.utils import make_synthetic_fleet  # noqa: E402

GAP = 1e-3


def _north_star(profiles_dir):
    model = load_model_profile(
        profiles_dir / "llama_3_70b" / "online" / "model_profile.json"
    )
    return make_synthetic_fleet(16, seed=123), model


def test_warm_equals_cold_on_north_star(profiles_dir):
    """Acceptance: warm and cold solves agree within mip_gap on the
    16-device north-star fixture, and the warm solve demonstrably reuses
    the iterates (fewer executed IPM iterations)."""
    devs, model = _north_star(profiles_dir)
    tm_cold: dict = {}
    cold = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax",
        timings=tm_cold,
    )
    assert cold.certified
    assert cold.ipm_state is not None
    assert np.asarray(cold.ipm_state["ok"]).any()

    tm_warm: dict = {}
    warm = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax", warm=cold,
        timings=tm_warm,
    )
    assert warm.certified
    assert abs(warm.obj_value - cold.obj_value) <= GAP * abs(cold.obj_value)
    assert warm.k == cold.k
    assert tm_warm["ipm_iters_executed"] <= tm_cold["ipm_iters_executed"]


def test_warm_equals_cold_under_drift(profiles_dir):
    """Streaming regime: drifted coefficients, warm seed from the previous
    tick. The warm result must match a from-scratch cold solve of the SAME
    drifted instance within the certificate window."""
    devs, model = _north_star(profiles_dir)
    prev = halda_solve(devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax")
    rng = np.random.default_rng(7)
    for d in devs:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.9, 1.1)))
    warm = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax", warm=prev
    )
    cold = halda_solve(devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax")
    assert warm.certified and cold.certified
    assert abs(warm.obj_value - cold.obj_value) <= GAP * abs(cold.obj_value)


def test_warm_iters_knob_plumbed(profiles_dir):
    """ipm_warm_iters reaches the device program: a full-budget override
    must still certify and agree; an equal-budget override disables the
    truncation without changing the answer."""
    devs, model = _north_star(profiles_dir)
    devs = devs[:6]
    base = halda_solve(devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax")
    full = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="4bit", backend="jax",
        ipm_iters=8, ipm_warm_iters=8,
    )
    assert base.certified and full.certified
    assert abs(full.obj_value - base.obj_value) <= GAP * abs(base.obj_value)


@pytest.mark.parametrize("cfg", ["qwen15_moe_a27b", "mixtral_8x7b"])
def test_warm_equals_cold_on_moe_families(cfg):
    """Acceptance: MoE family fixtures — warm ticks (incumbent + duals +
    root iterates riding the streaming replanner) certify the same optimum
    as a cold solve of the drifted instance."""
    from distilp_tpu.profiler.api import profile_model

    model = profile_model(
        f"tests/configs/{cfg}.json", batch_sizes=[1], sequence_length=128
    ).to_model_profile()
    devs = make_synthetic_fleet(4, seed=3, pool_bytes=int(48e9))

    planner = StreamingReplanner(mip_gap=GAP, kv_bits="8bit", backend="jax")
    planner.step(devs, model)  # cold + compile
    rng = np.random.default_rng(17)
    for d in devs:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
    warm = planner.step(devs, model)
    assert warm.certified
    assert planner.last_tick_mode in ("warm", "margin")

    cold = halda_solve(
        devs, model, mip_gap=GAP, kv_bits="8bit", backend="jax"
    )
    assert cold.certified
    assert abs(warm.obj_value - cold.obj_value) <= GAP * abs(cold.obj_value)
    if model.n_routed_experts:
        assert sum(warm.y) == model.n_routed_experts


def test_cold_start_flag_disables_reuse_but_matches(profiles_dir):
    """`--cold-start` A/B mode: every tick reports mode='cold' and still
    lands on the warm run's objective within the certificate."""
    devs, model = _north_star(profiles_dir)
    devs = devs[:6]
    warm_p = StreamingReplanner(mip_gap=GAP, kv_bits="4bit", backend="jax")
    cold_p = StreamingReplanner(
        mip_gap=GAP, kv_bits="4bit", backend="jax", cold_start=True
    )
    warm_p.step(devs, model)
    cold_p.step(devs, model)
    rng = np.random.default_rng(9)
    for d in devs:
        d.t_comm = max(0.0, d.t_comm * float(rng.uniform(0.95, 1.05)))
    w = warm_p.step(devs, model)
    c = cold_p.step(devs, model)
    assert warm_p.last_tick_mode in ("warm", "margin")
    assert cold_p.last_tick_mode == "cold"
    assert c.certified and w.certified
    assert abs(w.obj_value - c.obj_value) <= GAP * abs(c.obj_value)
